"""Serving: batched greedy/sampled decode with the jitted KV cache.

Run: JAX_PLATFORMS=cpu python examples/serve_generate.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    # honor an explicit CPU request at config level (a TPU-tunnel
    # sitecustomize may override the env var after import)
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def main():
    paddle.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    model = LlamaForCausalLM(cfg)
    prompts = paddle.to_tensor(
        np.random.randint(0, cfg.vocab_size, (2, 8)))
    # greedy, static-KV jitted decode
    out = model.generate(prompts, max_new_tokens=8)
    print("greedy:", out.shape, out.numpy()[0][-8:])
    # nucleus sampling
    out2 = model.generate(prompts, max_new_tokens=8, do_sample=True,
                          top_p=0.9, temperature=0.8)
    print("sampled:", out2.shape)

    # continuous batching: requests of different lengths admitted
    # mid-flight into a fixed slot pool over ONE paged KV cache
    from paddle_tpu.serving import ContinuousBatchEngine

    eng = ContinuousBatchEngine(model, max_batch=2, max_len=64, page_size=8)
    rng = np.random.RandomState(0)
    rids = [eng.add_request(rng.randint(0, cfg.vocab_size, (n,)),
                            max_new_tokens=6) for n in (5, 9, 3)]
    done = eng.run_until_done()
    for rid in rids:
        print(f"request {rid}: {done[rid].tolist()}")

    # DeepSeek MLA serves through the SAME engine in latent mode: the
    # cache holds the compressed latent (kv_lora_rank + qk_rope_head_dim
    # floats/token) per slot row instead of paged per-head K/V
    from paddle_tpu.models import DeepseekV2Config, DeepseekV2ForCausalLM

    mla = DeepseekV2ForCausalLM(DeepseekV2Config.tiny_mla(
        num_hidden_layers=2))
    eng2 = ContinuousBatchEngine(mla, max_batch=2, max_len=64)
    assert eng2._latent_mode
    rid = eng2.add_request(rng.randint(0, 512, (7,)), max_new_tokens=6)
    print("mla request:", eng2.run_until_done()[rid].tolist())


if __name__ == "__main__":
    main()
