"""Serving: batched greedy/sampled decode with the jitted KV cache.

Run: JAX_PLATFORMS=cpu python examples/serve_generate.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    # honor an explicit CPU request at config level (a TPU-tunnel
    # sitecustomize may override the env var after import)
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def main():
    paddle.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    model = LlamaForCausalLM(cfg)
    prompts = paddle.to_tensor(
        np.random.randint(0, cfg.vocab_size, (2, 8)))
    # greedy, static-KV jitted decode
    out = model.generate(prompts, max_new_tokens=8)
    print("greedy:", out.shape, out.numpy()[0][-8:])
    # nucleus sampling
    out2 = model.generate(prompts, max_new_tokens=8, do_sample=True,
                          top_p=0.9, temperature=0.8)
    print("sampled:", out2.shape)


if __name__ == "__main__":
    main()
