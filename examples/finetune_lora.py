"""LoRA fine-tuning + weight-only int8 serving, end to end:

1. wrap a Llama causal LM with LoRA adapters (base frozen),
2. fine-tune — the jit TrainStep differentiates ONLY the adapters,
3. merge the adapters into the base weights,
4. quantize the merged model to int8 weight-only and serve it through the
   continuous-batching engine.

Run: JAX_PLATFORMS=cpu python examples/finetune_lora.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import optimizer as opt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.nn.quant import quantize_for_serving
from paddle_tpu.peft import LoRAConfig, get_peft_model, lora_state_dict, merge_lora
from paddle_tpu.serving import ContinuousBatchEngine


def main():
    paddle.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    model = LlamaForCausalLM(cfg)

    # 1. adapters in, base frozen
    model, n = get_peft_model(model, LoRAConfig(r=8, lora_alpha=16))
    trainable = sum(p.size for _, p in model.named_parameters()
                    if not p.stop_gradient)
    total = sum(p.size for _, p in model.named_parameters())
    print(f"LoRA: wrapped {n} projections; trainable {trainable:,}/{total:,} "
          f"params ({100 * trainable / total:.2f}%)")

    # 2. fine-tune (adapters only)
    def loss_fn(m, x, y):
        loss, _ = m(x, labels=y)
        return loss

    step = paddle.jit.train_step(
        model, loss_fn, opt.AdamW(1e-3, parameters=model.parameters()))
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (4, 33))
    x, y = paddle.to_tensor(ids[:, :-1]), paddle.to_tensor(ids[:, 1:])
    for i in range(10):
        loss = step(x, y)
    print(f"fine-tuned 10 steps, loss {float(loss.numpy()):.4f}")
    print(f"adapter checkpoint tensors: {len(lora_state_dict(model))}")

    # 3. merge for deployment (plain Linears again, zero adapter overhead)
    model, merged = merge_lora(model)
    print(f"merged {merged} adapters")

    # 4. int8 weight-only serving
    model, nq = quantize_for_serving(model)
    print(f"quantized {nq} projections to int8")
    eng = ContinuousBatchEngine(model, max_batch=4, max_len=64, page_size=8)
    rids = [eng.add_request(rng.randint(0, cfg.vocab_size, (8 + i,)),
                            max_new_tokens=8,
                            do_sample=(i % 2 == 1), temperature=0.8)
            for i in range(4)]
    done = eng.run_until_done()
    for rid in rids:
        print(f"request {rid}: {done[rid].tolist()}")


if __name__ == "__main__":
    main()
