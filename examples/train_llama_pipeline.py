"""Pipeline-parallel Llama training on the fused hybrid mesh:
pp (pipeline stages) x mp (tensor parallel) x sharding (ZeRO-3) on ONE
5-axis mesh. Each stage jits over its (dp, sharding, sep, mp) submesh —
GSPMD inserts the in-stage collectives — while micro-batches flow between
stages under the chosen schedule (1F1B / FThenB / ZBH1 zero-bubble).

Run on a virtual 8-device CPU mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/train_llama_pipeline.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import optimizer as opt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLMPipe


def main():
    import jax

    n = jax.device_count()
    pp = 2 if n % 2 == 0 else 1
    mp = 2 if n % 4 == 0 else 1
    sharding = max(1, n // (pp * mp))

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 1, "mp_degree": mp, "sep_degree": 1,
        "sharding_degree": sharding, "pp_degree": pp,
    }
    strategy.sharding_configs = {"stage": 3}
    strategy.pipeline_configs = {"accumulate_steps": 4,
                                 "schedule_mode": "1F1B"}
    dist.fleet.init(is_collective=True, strategy=strategy)
    print(f"mesh: pp={pp} mp={mp} sharding={sharding} over {n} devices")

    paddle.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=4, use_flash_attention=False,
                           tie_word_embeddings=True)
    model = LlamaForCausalLMPipe(cfg)          # stages cut at decoder layers
    pp_runtime = dist.fleet.distributed_model(model)
    optimizer = opt.AdamW(5e-3, parameters=model.parameters(),
                          grad_clip=opt.ClipGradByGlobalNorm(1.0))

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (8, 65))
    x = paddle.to_tensor(ids[:, :-1])
    y = paddle.to_tensor(ids[:, 1:])
    for step in range(10):
        loss = pp_runtime.train_batch([x, y], optimizer)
        print(f"step {step}: loss {float(np.asarray(loss)):.4f}")


if __name__ == "__main__":
    main()
