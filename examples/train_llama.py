"""End-to-end Llama pretraining loop on paddle_tpu.

Run (CPU smoke): JAX_PLATFORMS=cpu python examples/train_llama.py
On a TPU pod the same script scales by enlarging the topology degrees —
GSPMD inserts the collectives from the sharding annotations.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    # honor an explicit CPU request at config level (a TPU-tunnel
    # sitecustomize may override the env var after import)
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import optimizer as opt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def main():
    tiny = os.environ.get("JAX_PLATFORMS") == "cpu"
    cfg = LlamaConfig.tiny(num_hidden_layers=2) if tiny else LlamaConfig(
        vocab_size=32000, hidden_size=2048, intermediate_size=5632,
        num_hidden_layers=8, num_attention_heads=16, num_key_value_heads=8,
        max_position_embeddings=2048, use_flash_attention=True,
        dtype="bfloat16")
    seq, batch = (32, 2) if tiny else (2048, 4)

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    optimizer = opt.AdamW(3e-4, parameters=model.parameters(),
                          weight_decay=0.1)

    # one fused XLA computation: forward + backward + AdamW, donated buffers
    step = paddle.jit.train_step(
        model, lambda m, x, y: m(x, labels=y)[0], optimizer)

    rng = np.random.RandomState(0)
    for it in range(5):
        ids = rng.randint(0, cfg.vocab_size, (batch, seq + 1))
        loss = step(paddle.to_tensor(ids[:, :-1]),
                    paddle.to_tensor(ids[:, 1:]))
        print(f"step {it}: loss {float(loss.numpy()):.4f}")

    # checkpoint + resume
    paddle.save(model.state_dict(), "/tmp/llama_example.pdparams")
    model2 = LlamaForCausalLM(cfg)
    model2.set_state_dict(paddle.load("/tmp/llama_example.pdparams"))
    print("checkpoint round-trip OK")


if __name__ == "__main__":
    main()
