"""Hybrid-parallel Llama training: tensor parallel x ZeRO-3 x sequence
parallel over one jax Mesh. GSPMD inserts the collectives; the same script
drives a v5p slice by just raising the degrees.

Run on a virtual 8-device CPU mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/distributed_hybrid.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import optimizer as opt
from paddle_tpu.distributed.engine import parallelize
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def main():
    import jax

    n = jax.device_count()
    mp = 2 if n % 2 == 0 else 1
    sharding = 2 if n % 4 == 0 else 1
    sep = 2 if n % 8 == 0 else 1
    dp = n // (mp * sharding * sep)

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": dp, "mp_degree": mp, "sep_degree": sep,
        "sharding_degree": sharding, "pp_degree": 1,
    }
    strategy.sharding_configs = {"stage": 3}  # ZeRO-3 param sharding
    dist.fleet.init(is_collective=True, strategy=strategy)

    paddle.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, use_flash_attention=False,
                           num_attention_heads=4,
                           num_key_value_heads=max(2, mp))
    model = dist.fleet.distributed_model(LlamaForCausalLM(cfg))
    optimizer = dist.fleet.distributed_optimizer(
        opt.AdamW(1e-3, parameters=model.parameters(),
                  grad_clip=opt.ClipGradByGlobalNorm(1.0)))
    step = parallelize(model, lambda m, x, y: m(x, labels=y)[0], optimizer)

    batch = max(2 * dp * sharding, 2)
    seq = 32 * sep
    rng = np.random.RandomState(0)
    for it in range(3):
        ids = rng.randint(0, cfg.vocab_size, (batch, seq + 1))
        loss = step(paddle.to_tensor(ids[:, :-1]),
                    paddle.to_tensor(ids[:, 1:]))
        print(f"step {it}: devices={n} degrees=dp{dp}/mp{mp}/"
              f"sharding{sharding}/sep{sep} loss={float(loss.numpy()):.4f}")
    dist.set_hybrid_communicate_group(None)


if __name__ == "__main__":
    main()
