"""Auto-parallel training with the dist.to_static surface: topology via
fleet, a DistModel over the compiled hybrid step, a sharded input
pipeline, and the auto_parallel Strategy spelling — the reference's
semi-automatic parallelism workflow, GSPMD underneath.

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python examples/auto_parallel_to_static.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import optimizer as opt
from paddle_tpu.io import DataLoader, TensorDataset
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def main():
    # the auto_parallel Strategy spelling writes the same knob store the
    # fleet spelling reads
    strategy = dist.Strategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                               "sharding_degree": 2, "pp_degree": 1,
                               "sep_degree": 1}
    strategy.sharding.stage = 3
    dist.fleet.init(is_collective=True, strategy=strategy)

    paddle.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, use_flash_attention=False)
    model = dist.fleet.distributed_model(LlamaForCausalLM(cfg))

    def loss_fn(m, x, y):
        loss, _ = m(x, labels=y)
        return loss

    dm = dist.to_static(model, loss_fn=loss_fn,
                        optimizer=opt.AdamW(1e-3,
                                            parameters=model.parameters()))

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (64, 33))
    ds = TensorDataset([paddle.to_tensor(ids[:, :-1]),
                        paddle.to_tensor(ids[:, 1:])])
    mesh = dist.get_hybrid_communicate_group().mesh
    loader = dist.shard_dataloader(DataLoader(ds, batch_size=8), mesh,
                                   shard_dims="dp")

    for epoch in range(2):
        for step, (x, y) in enumerate(loader):
            loss = dm(x, y)
        print(f"epoch {epoch}: loss {float(np.asarray(loss.numpy())):.4f}")

    dm.eval()
    x0, y0 = next(iter(loader))
    print(f"eval loss: {float(np.asarray(dm(x0, y0).numpy())):.4f}")
    dist.set_hybrid_communicate_group(None)
    print("OK")


if __name__ == "__main__":
    main()
