"""Seq2seq training end to end: T5 learns to sort token sequences.

The whole step (encoder + decoder + tied-head loss + AdamW) is one
donated-buffer XLA computation via paddle.jit.train_step; greedy decode
at the end shows the learned behavior through the cached enc-dec
generate path.

Run: JAX_PLATFORMS=cpu python examples/train_seq2seq.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import optimizer as opt
from paddle_tpu.models.t5 import T5Config, T5ForConditionalGeneration


def batch(rng, n, s, vocab, start_id):
    """Input: random tokens; target: the tokens SORTED ascending — the
    classic content-addressable attention task (cross-attention selects
    the smallest not-yet-emitted source token at each step)."""
    src = rng.randint(10, vocab, (n, s))
    tgt = np.sort(src, axis=1)
    dec_in = np.concatenate(
        [np.full((n, 1), start_id, np.int64), tgt[:, :-1]], axis=1)
    return (paddle.to_tensor(src), paddle.to_tensor(dec_in),
            paddle.to_tensor(tgt))


def main():
    cfg = T5Config.tiny(vocab_size=64, num_layers=2)
    paddle.seed(0)
    model = T5ForConditionalGeneration(cfg)

    def loss_fn(m, x, dec_x, y):
        loss, _ = m(x, dec_x, labels=y)
        return loss

    step = paddle.jit.train_step(
        model, loss_fn, opt.AdamW(1e-3, parameters=model.parameters()))
    rng = np.random.RandomState(0)
    for i in range(801):
        loss = step(*batch(rng, 32, 4, cfg.vocab_size,
                           cfg.decoder_start_token_id))
        if i % 100 == 0:
            print(f"step {i:3d}  loss {float(loss.numpy()):.4f}")

    src, _, tgt = batch(rng, 4, 4, cfg.vocab_size,
                        cfg.decoder_start_token_id)
    out = model.generate(src, max_new_tokens=4, eos_token_id=-1).numpy()
    acc = (out == tgt.numpy()).mean()
    print(f"\nsort accuracy on fresh samples: {acc:.2%}")
    print("src:", src.numpy()[0].tolist())
    print("out:", out[0].tolist())
    print("tgt:", tgt.numpy()[0].tolist())


if __name__ == "__main__":
    main()
