"""Elastic data-parallel training: crash one worker, scale in, resume.

The launcher runs this file's WORKER mode on two processes. At step 3,
rank 1 dies. The launcher (``--max_restarts 1 --np_range 1:2``) detects
the death, drops the failed rank, and relaunches the survivor as a world
of ONE; the worker reshard-loads the newest checkpoint — including the
one rank 0 wrote from its SIGTERM save-on-signal handler mid-step — and
the loss continues its descent to convergence.

Run: JAX_PLATFORMS=cpu python examples/elastic_training.py
"""
import glob
import os
import pickle
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def worker():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.elastic import on_restart_signal
    from paddle_tpu.distributed.store import TCPStore
    from paddle_tpu.optimizer import SGD

    out = sys.argv[2]
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    world = int(os.environ["PADDLE_TRAINERS_NUM"])
    inc = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))
    host, port = os.environ["PADDLE_MASTER"].rsplit(":", 1)
    store = TCPStore(host, int(port), is_master=(rank == 0),
                     world_size=world, timeout=60)
    store.barrier(f"boot{inc}")

    paddle.seed(0)  # same init everywhere; checkpoints overwrite it
    model = nn.Linear(4, 1)
    opt = SGD(learning_rate=0.1, parameters=model.parameters())

    # resume from the NEWEST checkpoint across all former ranks (weights
    # replicated under dp, so any newest copy is valid at any world size)
    state = {"step": 0}
    for f in sorted(glob.glob(os.path.join(out, "ck_*.pkl"))):
        with open(f, "rb") as fh:
            s = pickle.load(fh)
        if s["step"] > state["step"]:
            state = s
    if state["step"]:
        own = model.state_dict()
        for k, v in state["w"].items():
            own[k].set_value(paddle.to_tensor(v))
        print(f"rank {rank}: resumed step {state['step']} world {world}",
              flush=True)

    def save():
        state["w"] = {k: np.asarray(v._array)
                      for k, v in model.state_dict().items()}
        with open(os.path.join(out, f"ck_{rank}.pkl"), "wb") as f:
            pickle.dump(state, f)
        print(f"rank {rank}: signal-saved step {state['step']}", flush=True)

    guard = on_restart_signal(save)

    rng = np.random.RandomState(42)
    X = rng.randn(64, 4).astype("float32")
    Y = X @ np.array([[3.0], [-1.0], [2.0], [0.5]], np.float32) - 2.0
    for step in range(state["step"], 30):
        if rank == 1 and inc == 0 and step == 3:
            print("rank 1: simulated hardware failure", flush=True)
            os._exit(7)
        shard = np.array_split(np.arange(64), world)[rank]
        d = model(paddle.to_tensor(X[shard])) - paddle.to_tensor(Y[shard])
        loss = (d * d).mean()
        loss.backward()
        # dp grad average over the store (the example rig's allreduce)
        g = {k: p.grad.numpy() for k, p in zip("wb", model.parameters())}
        store.set(f"g{inc}_{step}_{rank}", pickle.dumps(g))
        acc = None
        for r in range(world):
            gr = pickle.loads(store.get(f"g{inc}_{step}_{r}", timeout=60))
            acc = gr if acc is None else {k: acc[k] + gr[k] for k in acc}
        with guard.shield():  # SIGTERM inside the update span defers save
            for k, p in zip("wb", model.parameters()):
                p.grad.set_value(paddle.to_tensor(acc[k] / world))
            opt.step()
            opt.clear_grad()
            state["step"] = step + 1
        if rank == 0 and (step + 1) % 10 == 0:
            print(f"rank 0: step {step + 1} loss {float(loss.numpy()):.4f}",
                  flush=True)
    save()
    print(f"rank {rank}: DONE loss={float(loss.numpy()):.5f} "
          f"w={model.weight.numpy().reshape(-1).round(2).tolist()}",
          flush=True)


def main():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    with tempfile.TemporaryDirectory() as out:
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--master", f"127.0.0.1:{port}",
             "--max_restarts", "1", "--np_range", "1:2",
             "--log_dir", os.path.join(out, "logs"),
             os.path.abspath(__file__), "--worker", out],
            cwd=REPO, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "PYTHONPATH": REPO})
        print("\nworker logs:")
        for lp in sorted(glob.glob(os.path.join(out, "logs", "*"))):
            with open(lp) as f:
                body = f.read().strip()
            print(f"--- {os.path.basename(lp)} ---\n{body}")
        assert r.returncode == 0, r.returncode


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        worker()
    else:
        main()
