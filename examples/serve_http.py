"""Deploy a model behind the OpenAI-style HTTP endpoint and exercise it:
health check, a batch completion, an SSE streaming completion, two
concurrent clients riding one continuous-batching engine in-flight, and
the request-scoped trace a completion leaves behind (W3C traceparent in,
span tree and chrome-trace download out).

Run: JAX_PLATFORMS=cpu python examples/serve_http.py
"""
import http.client
import json
import os
import sys
import threading

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import ContinuousBatchEngine
from paddle_tpu.serving_http import CompletionServer


def post(addr, body, stream=False):
    conn = http.client.HTTPConnection(*addr, timeout=300)
    conn.request("POST", "/v1/completions", json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    raw = resp.read().decode()
    conn.close()
    if stream:
        return [line[len("data: "):] for line in raw.splitlines()
                if line.startswith("data: ")]
    return json.loads(raw)


def main():
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=2))
    engine = ContinuousBatchEngine(model, max_batch=4, max_len=64,
                                   page_size=8)
    with CompletionServer(engine, model_name="tiny-llama") as srv:
        addr = srv.address
        conn = http.client.HTTPConnection(*addr, timeout=30)
        conn.request("GET", "/health")
        print("health:", json.loads(conn.getresponse().read()))
        conn.close()

        rng = np.random.RandomState(0)
        out = post(addr, {"prompt_token_ids": rng.randint(1, 512, 8).tolist(),
                          "max_tokens": 6})
        print("completion:", out["choices"][0]["token_ids"],
              out["usage"])

        events = post(addr, {"prompt_token_ids":
                             rng.randint(1, 512, 5).tolist(),
                             "max_tokens": 5, "stream": True}, stream=True)
        toks = [json.loads(e)["choices"][0]["token_ids"][0]
                for e in events if e != "[DONE]"]
        print("streamed:", toks, "| terminator:", events[-1])

        results = {}

        def client(name, n):
            results[name] = post(
                addr, {"prompt_token_ids": rng.randint(1, 512, n).tolist(),
                       "max_tokens": 6})["choices"][0]["token_ids"]

        a = threading.Thread(target=client, args=("a", 9))
        b = threading.Thread(target=client, args=("b", 4))
        a.start(); b.start(); a.join(); b.join()
        print("concurrent:", results)

        # request-scoped tracing: send a W3C traceparent, read the span
        # tree back by trace id (GET /trace/chrome downloads the same
        # trace as chrome://tracing JSON)
        from paddle_tpu.observability import tracing

        inbound = tracing.format_traceparent("ab" * 16, "cd" * 8)
        conn = http.client.HTTPConnection(*addr, timeout=300)
        conn.request("POST", "/v1/completions",
                     json.dumps({"prompt_token_ids":
                                 rng.randint(1, 512, 6).tolist(),
                                 "max_tokens": 4}),
                     {"Content-Type": "application/json",
                      "traceparent": inbound})
        resp = conn.getresponse()
        resp.read()
        echoed = resp.getheader("traceparent")
        conn.request("GET", "/trace?trace_id=" + echoed.split("-")[1])
        spans = json.loads(conn.getresponse().read())["spans"]
        conn.close()
        print("trace:", [(s["name"],
                          round((s["end_ns"] - s["start_ns"]) / 1e6, 3))
                         for s in spans])


if __name__ == "__main__":
    main()
