"""High-level hapi training: Model.fit on a vision-zoo network.

Run: JAX_PLATFORMS=cpu python examples/finetune_vision.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    # honor an explicit CPU request at config level (a TPU-tunnel
    # sitecustomize may override the env var after import)
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import optimizer as opt
from paddle_tpu.io.dataset import Dataset
from paddle_tpu.vision.models import mobilenet_v3_small


class SyntheticImages(Dataset):
    def __init__(self, n=32):
        rng = np.random.RandomState(0)
        self.x = rng.rand(n, 3, 32, 32).astype("float32")
        self.y = rng.randint(0, 4, (n, 1))

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def main():
    paddle.seed(0)
    net = mobilenet_v3_small(num_classes=4)
    model = paddle.Model(net)
    model.prepare(opt.Adam(1e-3, parameters=net.parameters()),
                  paddle.nn.CrossEntropyLoss(),
                  paddle.metric.Accuracy())
    model.fit(SyntheticImages(), epochs=1, batch_size=8, verbose=1)
    result = model.evaluate(SyntheticImages(16), batch_size=8, verbose=0)
    print("eval:", result)


if __name__ == "__main__":
    main()
