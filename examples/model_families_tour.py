"""Every causal/seq2seq family in the zoo, built + generating in one run:
Llama-3 (RoPE GQA), Qwen2 (qkv bias), Mistral (sliding window), GPT-2
(learned positions), Gemma (GeGLU + (1+w) norms + scaled embeddings),
Gemma2 (sandwich norms, soft caps, alternating windows), Phi-3 (LongRoPE),
DeepSeekMoE (routed experts), Qwen2-MoE (sigmoid shared gate), Mixtral
(all-sparse top-2), ERNIE-4.5 (MoE decoder), DeepSeek-V2/V3 (MLA latent
cache, group-limited routing), T5/BART (encoder-decoder) — all
through the same generate surface, then one continuous-batching engine
serving three different families' requests back to back.

Run: JAX_PLATFORMS=cpu python examples/model_families_tour.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import models as M


def main():
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(2, 256, (1, 10)))

    paddle.seed(0)
    zoo = [
        ("llama-3", M.LlamaForCausalLM(
            M.LlamaConfig.tiny(num_hidden_layers=2, vocab_size=256))),
        ("qwen2", M.Qwen2ForCausalLM(
            M.Qwen2Config.tiny(num_hidden_layers=2, vocab_size=256))),
        ("qwen3", M.Qwen3ForCausalLM(
            M.Qwen3Config.tiny(num_hidden_layers=2, vocab_size=256))),
        ("mistral", M.MistralForCausalLM(
            M.MistralConfig.tiny(num_hidden_layers=2, vocab_size=256,
                                 sliding_window=8))),
        ("gpt2", M.GPT2LMHeadModel(
            M.GPT2Config.tiny(num_hidden_layers=2, vocab_size=256))),
        ("gemma", M.GemmaForCausalLM(
            M.GemmaConfig.tiny(num_hidden_layers=2, vocab_size=256))),
        ("gemma2", M.Gemma2ForCausalLM(
            M.Gemma2Config.tiny(num_hidden_layers=2, vocab_size=256))),
        ("phi3", M.Phi3ForCausalLM(
            M.Phi3Config.tiny(num_hidden_layers=2, vocab_size=256))),
        ("glm4", M.Glm4ForCausalLM(
            M.Glm4Config.tiny(num_hidden_layers=2, vocab_size=256))),
        ("olmo2", M.Olmo2ForCausalLM(
            M.Olmo2Config.tiny(num_hidden_layers=2, vocab_size=256))),
        ("llama-moe", M.LlamaMoEForCausalLM(
            M.LlamaMoEConfig.tiny_moe(vocab_size=256))),
        ("qwen2-moe", M.Qwen2MoeForCausalLM(
            M.Qwen2MoeConfig.tiny(vocab_size=256))),
        ("qwen3-moe", M.Qwen3MoeForCausalLM(
            M.Qwen3MoeConfig.tiny(vocab_size=256))),
        ("mixtral", M.MixtralForCausalLM(
            M.MixtralConfig.tiny(vocab_size=256))),
        ("ernie-4.5", M.Ernie45ForCausalLM(
            M.Ernie45Config.tiny_moe(vocab_size=256))),
        ("deepseek-v2", M.DeepseekV2ForCausalLM(
            M.DeepseekV2Config.tiny_mla(vocab_size=256))),
        ("deepseek-v3", M.DeepseekV2ForCausalLM(
            M.DeepseekV2Config.tiny_v3(vocab_size=256))),
        ("llava", M.LlavaForConditionalGeneration(M.LlavaConfig(
            text_config=M.LlamaConfig.tiny(num_hidden_layers=2,
                                           vocab_size=256),
            vision_config=M.CLIPVisionConfig.tiny(),
            image_token_index=255))),
        ("t5", M.T5ForConditionalGeneration(M.T5Config.tiny(vocab_size=256))),
        ("bart", M.BartForConditionalGeneration(
            M.BartConfig.tiny(vocab_size=256))),
    ]
    for name, model in zoo:
        out = model.generate(ids, max_new_tokens=6)
        params = model.num_parameters() / 1e6
        print(f"{name:>10} ({params:5.2f}M params): {out.numpy()[0].tolist()}")

    # audio: whisper transcribes a mel spectrogram (encoder conv frontend
    # + cross-attending decoder) through the same cached generate shape
    wh = M.WhisperForConditionalGeneration(M.WhisperConfig.tiny())
    mel = paddle.to_tensor(rng.randn(1, 8, 32).astype("float32"))
    wh_out = wh.generate(mel, max_new_tokens=6, eos_token_id=None)
    print(f"\n{'whisper':>10}: {wh_out.numpy()[0].tolist()}")

    # ...and Whisper through the enc-dec continuous-batching engine
    from paddle_tpu.serving import Seq2SeqBatchEngine

    s2s = Seq2SeqBatchEngine(wh, max_batch=2, max_decode_len=16,
                             max_encoder_len=16)
    rid = s2s.add_request(rng.randn(8, 32).astype("float32"),
                          max_new_tokens=5)
    print(f"{'whisper-engine':>14}: {s2s.run_until_done()[rid].tolist()}")

    # multimodal: the llava member again, now WITH an image — placeholder
    # tokens in the prompt are replaced by projected CLIP patch features
    llava = dict(zoo)["llava"]
    mm_ids = rng.randint(2, 250, (1, 10))
    mm_ids[0, 2:6] = 255                      # 4 patches at 16px/8px
    pixels = paddle.to_tensor(rng.randn(1, 3, 16, 16).astype("float32"))
    mm_out = llava.generate(paddle.to_tensor(mm_ids), pixel_values=pixels,
                            max_new_tokens=6)
    print(f"\n{'llava+img':>10}: {mm_out.numpy()[0].tolist()}")

    # one engine per family class, three families served in-flight
    from paddle_tpu.serving import ContinuousBatchEngine

    print("\ncontinuous batching across families:")
    for name, model in zoo[:2] + [zoo[3]]:
        eng = ContinuousBatchEngine(model, max_batch=2, max_len=64,
                                    page_size=8)
        rid = eng.add_request(rng.randint(2, 256, (7,)), max_new_tokens=5)
        done = eng.run_until_done()
        print(f"{name:>10}: request {rid} -> {done[rid].tolist()}")


if __name__ == "__main__":
    main()
