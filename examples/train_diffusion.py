"""Latent-diffusion training + sampling (the DiT / SD3 workload family).

Two recipes in one script:
- ``--model dit``: class-conditional DiT with the DDPM eps objective and
  DDIM sampling (classifier-free guidance via the null class).
- ``--model sd3``: text-conditioned MMDiT with the rectified-flow objective
  and Euler flow sampling (text context here is random features standing in
  for a frozen text encoder).

Both train through ``paddle.jit.train_step`` — one donated XLA computation
per step — and sample with a single ``lax.scan`` dispatch. Scale-out is the
same as any model: wrap with ``fleet.distributed_model`` + ``parallelize``
under a hybrid topology (see examples/distributed_hybrid.py).

Run (CPU smoke):
  JAX_PLATFORMS=cpu python examples/train_diffusion.py --model dit --steps 20
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    # honor a CPU request at the config level too (the TPU-tunnel plugin
    # overrides the env var after jax import)
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt


def train_dit(steps: int):
    from paddle_tpu.models.sd3 import (cfg_label_dropout, ddpm_eps_loss,
                                       sample_ddim)
    from paddle_tpu.vision.models import AutoencoderKL, VAEConfig
    from paddle_tpu.vision.models.dit import DiT, DiTConfig

    paddle.seed(0)
    vae = AutoencoderKL(VAEConfig.tiny())          # frozen in this recipe
    model = DiT(DiTConfig.tiny())
    optimizer = opt.AdamW(1e-4, parameters=model.parameters())

    def loss_fn(m, z, y):
        y = cfg_label_dropout(y, m.config.num_classes, prob=0.1)
        return ddpm_eps_loss(m, z, y)

    step = paddle.jit.train_step(model, loss_fn, optimizer)
    rng = np.random.RandomState(0)
    for i in range(steps):
        images = paddle.to_tensor(rng.rand(8, 3, 16, 16).astype("float32"))
        labels = paddle.to_tensor(rng.randint(0, 10, (8,)).astype("int64"))
        z = vae.scale_latents(vae.encode(images).sample())
        loss = step(z, labels)
        if i % 5 == 0 or i == steps - 1:
            print(f"dit step {i}: loss={float(loss.numpy()):.4f}")

    # CFG sampling: null class = num_classes
    y = paddle.to_tensor(np.arange(4, dtype="int64") % 10)
    null = paddle.to_tensor(np.full((4,), 10, dtype="int64"))
    lat = sample_ddim(model, (4, 4, 8, 8), y, steps=8,
                      guidance_scale=3.0, uncond=(null,))
    images = vae.decode(vae.unscale_latents(lat))
    print("dit samples:", tuple(images.shape))


def train_sd3(steps: int):
    from paddle_tpu.models.sd3 import (MMDiT, MMDiTConfig,
                                       rectified_flow_loss, sample_flow)

    paddle.seed(0)
    model = MMDiT(MMDiTConfig.tiny())
    optimizer = opt.AdamW(1e-4, parameters=model.parameters())
    step = paddle.jit.train_step(
        model, lambda m, z, c, p: rectified_flow_loss(m, z, c, p), optimizer)
    rng = np.random.RandomState(0)
    for i in range(steps):
        z = paddle.to_tensor(rng.randn(8, 4, 8, 8).astype("float32"))
        ctx = paddle.to_tensor(rng.randn(8, 6, 32).astype("float32"))
        pool = paddle.to_tensor(rng.randn(8, 16).astype("float32"))
        loss = step(z, ctx, pool)
        if i % 5 == 0 or i == steps - 1:
            print(f"sd3 step {i}: loss={float(loss.numpy()):.4f}")

    ctx = paddle.to_tensor(rng.randn(4, 6, 32).astype("float32"))
    pool = paddle.to_tensor(rng.randn(4, 16).astype("float32"))
    lat = sample_flow(model, (4, 4, 8, 8), ctx, pool, steps=8)
    print("sd3 latents:", tuple(lat.shape))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", choices=["dit", "sd3"], default="dit")
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()
    (train_dit if args.model == "dit" else train_sd3)(args.steps)
