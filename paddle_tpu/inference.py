"""paddle.inference parity: Config + create_predictor.

Reference parity: paddle/fluid/inference/api/analysis_predictor.h —
``paddle_infer.Config(prog, params)`` + ``create_predictor`` + the
``Run``/``ZeroCopyRun`` tensor-feeding surface. The reference's IR analysis
passes collapse into XLA compilation here (SURVEY §7: AnalysisPredictor →
jit + AOT export); what remains is the user-facing predictor object.

Two predictor kinds:
- static predictor: a ``jax.export``-serialized StableHLO computation
  (produced by ``paddle_tpu.static.save_inference_model`` or
  ``paddle_tpu.jit.save``) — fixed signature, fastest path.
- generation predictor: weights loaded back into a causal-LM module with
  the static-KV-cache / paged decode loop (paddle_tpu.generation), the
  serving configuration of the reference's block_multi_head_attention.
"""
from __future__ import annotations

import os
import pickle
from typing import Optional, Sequence

import numpy as np


class Config:
    """paddle.inference.Config subset (analysis_predictor.h config)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        self.prog_file = prog_file
        self.params_file = params_file
        self._model_dir = None
        if prog_file is not None and params_file is None and os.path.isdir(prog_file):
            self._model_dir = prog_file
        self._memory_optim = True
        self._extra = {}

    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        self.prog_file = prog_file
        self.params_file = params_file

    def model_dir(self):
        return self._model_dir

    def enable_memory_optim(self, flag: bool = True):
        self._memory_optim = flag

    # accepted-for-compat GPU/IR switches (meaningless under XLA — loud)
    def enable_use_gpu(self, *a, **k):
        import warnings

        warnings.warn("inference.Config.enable_use_gpu has no effect on the "
                      "TPU backend (device placement is jax-managed)")

    def switch_ir_optim(self, flag: bool = True):
        pass  # XLA always optimizes; kept for API parity


class Predictor:
    """Static predictor over an exported StableHLO computation
    (the AnalysisPredictor::Run surface)."""

    def __init__(self, loaded, feed_names, num_fetch):
        self._pred = loaded
        self._feed_names = list(feed_names)
        self._num_fetch = num_fetch
        self._inputs = {}

    # paddle_infer handle-style surface
    def get_input_names(self):
        return list(self._feed_names)

    def get_input_handle(self, name):
        return _IOHandle(self._inputs, name)

    def run(self, feeds: Optional[Sequence[np.ndarray]] = None):
        if feeds is None:
            feeds = [self._inputs[n] for n in self._feed_names]
        return self._pred.run([np.asarray(f) for f in feeds])


class _IOHandle:
    def __init__(self, store, name):
        self._store = store
        self._name = name

    def copy_from_cpu(self, arr):
        self._store[self._name] = np.asarray(arr)

    def reshape(self, shape):
        pass  # shapes are taken from the fed array


def create_predictor(config: Config) -> Predictor:
    """paddle_infer.create_predictor parity: load the exported computation
    named by ``config.prog_file`` (path prefix without extension)."""
    from .static import load_inference_model

    prefix = config.prog_file
    if prefix is None:
        raise ValueError("Config.prog_file (path prefix) is required")
    if prefix.endswith(".stablehlo") or prefix.endswith(".pdmodel"):
        prefix = prefix.rsplit(".", 1)[0]
    pred, feed_names, num_fetch = load_inference_model(prefix)
    return Predictor(pred, feed_names, num_fetch)


class GenerationPredictor:
    """Serving predictor for causal-LM decode: loads ``jit.save``d weights
    (.pdiparams) back into a model and decodes with the static-KV or paged
    cache (paddle_tpu.generation)."""

    def __init__(self, path_prefix: str, model):
        with open(path_prefix + ".pdiparams", "rb") as f:
            state = pickle.load(f)
        import jax.numpy as jnp

        own = model.functional_state()
        missing = set(own) - set(state)
        if missing:
            raise ValueError(f"checkpoint missing parameters: {sorted(missing)[:5]}")
        model.load_functional_state(
            {k: jnp.asarray(v) for k, v in state.items() if k in own})
        self.model = model

    def generate(self, input_ids, paged: bool = False, page_size: int = 16,
                 **kwargs):
        from . import generation

        if paged:
            return generation.generate_paged(self.model, input_ids,
                                             page_size=page_size, **kwargs)
        return generation.generate(self.model, input_ids, **kwargs)


# ---------------------------------------------------------------------------
# Round-3 surface tail (python/paddle/inference/__init__.py parity)
# ---------------------------------------------------------------------------

class DataType:
    """inference.DataType enum parity."""

    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6
    BOOL = 7
    FLOAT64 = 8


class PlaceType:
    UNK = -1
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM = 3


class PrecisionType:
    Float32 = 0
    Int8 = 1
    Half = 2
    Bfloat16 = 3


class Tensor:
    """inference.Tensor handle parity: a named in/out slot of a Predictor
    (copy_from_cpu / copy_to_cpu reference API)."""

    def __init__(self, name="", value=None):
        self.name = name
        self._value = value

    def copy_from_cpu(self, arr):
        import jax.numpy as jnp
        import numpy as np

        self._value = jnp.asarray(np.asarray(arr))

    def copy_to_cpu(self):
        import numpy as np

        return np.asarray(self._value)

    def shape(self):
        return list(self._value.shape) if self._value is not None else []

    def reshape(self, shape):
        self._value = self._value.reshape(tuple(shape))


class XpuConfig:
    """Accepted-for-compat device-config holder (no XPU in this build)."""

    def __init__(self, **kwargs):
        self.__dict__.update(kwargs)


def get_version() -> str:
    from . import version

    return f"paddle_tpu inference {version.full_version} (StableHLO/XLA)"


def _get_phi_kernel_name(op_name: str) -> str:
    """Reference maps fluid op names to phi kernel names; here the registry
    name IS the kernel name."""
    return op_name


def get_trt_compile_version():
    """TensorRT is not part of the TPU build (XLA is the engine)."""
    return (0, 0, 0)


def get_trt_runtime_version():
    return (0, 0, 0)


def get_num_bytes_of_data_type(dtype) -> int:
    sizes = {DataType.FLOAT32: 4, DataType.INT64: 8, DataType.INT32: 4,
             DataType.UINT8: 1, DataType.INT8: 1, DataType.FLOAT16: 2,
             DataType.BFLOAT16: 2, DataType.BOOL: 1, DataType.FLOAT64: 8}
    return sizes.get(dtype, 4)


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision=None,
                               backend=None, keep_io_types=True,
                               black_list=None, **kwargs):
    """Reference rewrites a saved program to fp16/bf16. StableHLO artifacts
    re-specialize dtype at compile time under amp/auto_cast, so conversion
    copies the artifact and writes a <model>.precision.json sidecar
    recording the requested precision/black_list for loaders to consult."""
    import json
    import shutil

    shutil.copy(model_file, mixed_model_file)
    if params_file and mixed_params_file and params_file != mixed_params_file:
        try:
            shutil.copy(params_file, mixed_params_file)
        except FileNotFoundError:
            pass
    with open(str(mixed_model_file) + ".precision.json", "w") as f:
        json.dump({"mixed_precision": mixed_precision,
                   "keep_io_types": keep_io_types,
                   "black_list": sorted(black_list or [])}, f)
    return mixed_model_file


class PredictorPool:
    """inference.PredictorPool parity: N predictors over one config (the
    reference clones zero-copy; jitted executables are shared here)."""

    def __init__(self, config, size=1):
        self._predictors = [create_predictor(config) for _ in range(size)]

    def retrieve(self, idx):
        return self._predictors[idx]
