"""paddle.sparse parity (python/paddle/sparse/, phi sparse kernels
paddle/phi/kernels/sparse/ — SURVEY.md §2.2).

TPU-native: sparse tensors wrap jax.experimental.sparse BCOO/BCSR; unary
math runs on the values, matmul goes through the BCOO matmul lowering
(which XLA executes as gather/scatter + dense MXU tiles).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..tensor_class import Tensor, unwrap, wrap

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "is_same_shape",
    "add", "subtract", "multiply", "divide", "matmul", "masked_matmul",
    "relu", "abs", "sin", "tanh", "sqrt", "pow", "neg", "cast",
    "transpose", "sum", "nn",
]


class SparseTensor(Tensor):
    """A Tensor whose _array is a jax BCOO/BCSR. Dense-only methods fall
    back through to_dense()."""

    def __init__(self, sp, stop_gradient=True):
        self._array = sp
        self.stop_gradient = stop_gradient
        self.grad = None
        self.name = None

    # paddle Tensor sparse surface
    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        from jax.experimental import sparse as jsp

        return isinstance(self._array, jsp.BCOO)

    def is_sparse_csr(self):
        from jax.experimental import sparse as jsp

        return isinstance(self._array, jsp.BCSR)

    def to_dense(self):
        return wrap(self._array.todense(), self.stop_gradient)

    def values(self):
        return wrap(self._array.data, self.stop_gradient)

    def indices(self):
        import jax.numpy as jnp

        return wrap(jnp.swapaxes(self._array.indices, -1, -2))

    def crows(self):
        return wrap(self._array.indptr)

    def cols(self):
        return wrap(self._array.indices)

    def nnz(self):
        return int(self._array.nse)

    def numpy(self):
        return np.asarray(self._array.todense())

    def __repr__(self):
        kind = "coo" if self.is_sparse_coo() else "csr"
        return (f"SparseTensor({kind}, shape={list(self._array.shape)}, "
                f"nnz={self.nnz()})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """paddle.sparse.sparse_coo_tensor parity: indices [ndim, nnz]."""
    import jax.numpy as jnp
    from jax.experimental import sparse as jsp

    idx = jnp.asarray(unwrap(indices)).T  # BCOO wants [nnz, ndim]
    vals = jnp.asarray(unwrap(values))
    if dtype is not None:
        from ..framework.dtype import convert_dtype

        vals = vals.astype(convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=0))
    sp = jsp.BCOO((vals, idx), shape=tuple(shape))
    return SparseTensor(sp, stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    import jax.numpy as jnp
    from jax.experimental import sparse as jsp

    vals = jnp.asarray(unwrap(values))
    if dtype is not None:
        from ..framework.dtype import convert_dtype

        vals = vals.astype(convert_dtype(dtype))
    sp = jsp.BCSR((vals, jnp.asarray(unwrap(cols)),
                   jnp.asarray(unwrap(crows))), shape=tuple(shape))
    return SparseTensor(sp, stop_gradient)


def is_same_shape(x, y) -> bool:
    return tuple(x._array.shape) == tuple(y._array.shape)


def _coo(x):
    from jax.experimental import sparse as jsp

    a = x._array
    return a if isinstance(a, jsp.BCOO) else a.to_bcoo()


def _unary(fn_name):
    import jax.numpy as jnp

    fn = getattr(jnp, fn_name)

    def op(x, name=None):
        sp = _coo(x)
        out = sp.__class__((fn(sp.data), sp.indices), shape=sp.shape)
        return SparseTensor(out, x.stop_gradient)

    op.__name__ = fn_name
    return op


sin = _unary("sin")
tanh = _unary("tanh")
sqrt = _unary("sqrt")
abs = _unary("abs")


def neg(x, name=None):
    sp = _coo(x)
    return SparseTensor(sp.__class__((-sp.data, sp.indices), shape=sp.shape),
                        x.stop_gradient)


def pow(x, factor, name=None):
    sp = _coo(x)
    return SparseTensor(sp.__class__((sp.data ** factor, sp.indices),
                                     shape=sp.shape), x.stop_gradient)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..framework.dtype import convert_dtype

    sp = _coo(x)
    data = sp.data if value_dtype is None else sp.data.astype(
        convert_dtype(value_dtype))
    idx = sp.indices if index_dtype is None else sp.indices.astype(
        convert_dtype(index_dtype))
    return SparseTensor(sp.__class__((data, idx), shape=sp.shape),
                        x.stop_gradient)


def relu(x, name=None):
    import jax.numpy as jnp

    sp = _coo(x)
    return SparseTensor(sp.__class__((jnp.maximum(sp.data, 0), sp.indices),
                                     shape=sp.shape), x.stop_gradient)


def _binary(opname, jop):
    def op(x, y, name=None):
        from jax.experimental import sparse as jsp

        if isinstance(x, SparseTensor) and isinstance(y, SparseTensor):
            # same-pattern fast path, else densify (reference CPU kernels
            # merge patterns; pattern-union on TPU would be scatter-heavy)
            xs, ys = _coo(x), _coo(y)
            import jax.numpy as jnp

            if xs.indices.shape == ys.indices.shape and bool(
                    jnp.all(xs.indices == ys.indices)):
                return SparseTensor(
                    xs.__class__((jop(xs.data, ys.data), xs.indices),
                                 shape=xs.shape), x.stop_gradient)
            dense = jop(xs.todense(), ys.todense())
            return wrap(dense)
        raise TypeError(f"sparse.{opname} expects two sparse tensors")

    op.__name__ = opname
    return op


import jax.numpy as _jnp  # noqa: E402

add = _binary("add", _jnp.add)
subtract = _binary("subtract", _jnp.subtract)
multiply = _binary("multiply", _jnp.multiply)
divide = _binary("divide", _jnp.divide)


def matmul(x, y, name=None):
    """sparse @ dense (or sparse @ sparse → dense)."""
    from jax.experimental import sparse as jsp

    if isinstance(x, SparseTensor):
        xs = _coo(x)
        yv = _coo(y) if isinstance(y, SparseTensor) else unwrap(y)
        out = xs @ yv
        if isinstance(out, jsp.BCOO):
            return SparseTensor(out)
        return wrap(out)
    raise TypeError("sparse.matmul expects a sparse lhs")


def masked_matmul(x, y, mask, name=None):
    """dense @ dense evaluated only at mask's nonzeros (sddmm)."""
    import jax.numpy as jnp

    ms = _coo(mask)
    xv, yv = unwrap(x), unwrap(y)
    rows, cols = ms.indices[:, 0], ms.indices[:, 1]
    vals = jnp.einsum("nk,nk->n", xv[rows, :], yv[:, cols].T)
    return SparseTensor(ms.__class__((vals, ms.indices), shape=ms.shape))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    import jax.numpy as jnp

    sp = _coo(x)
    if axis is None:
        out = sp.data.sum()
        return wrap(out if not keepdim else out.reshape((1,) * len(sp.shape)))
    return wrap(jnp.sum(sp.todense(), axis=axis, keepdims=keepdim))




# ---------------------------------------------------------------------------
# Round-3 breadth: the rest of sparse_ops.yaml
# (reference paddle/phi/ops/yaml/sparse_ops.yaml — 51 ops; unary/binary ops
# map over stored values, structural ops remap COO indices, and the
# conv/pool/attention family computes DENSE on the MXU with sparse storage
# at the boundary — XLA has no sparse conv, and a gather/scatter emulation
# would be slower than the dense tile it avoids.)
# ---------------------------------------------------------------------------

acos = _unary("arccos")
acosh = _unary("arccosh")
asin = _unary("arcsin")
asinh = _unary("arcsinh")
atan = _unary("arctan")
atanh = _unary("arctanh")
expm1 = _unary("expm1")
log1p = _unary("log1p")
sinh = _unary("sinh")
tan = _unary("tan")
square = _unary("square")
isnan = _unary("isnan")


def leaky_relu(x, negative_slope=0.01, name=None):
    sp = _coo(x)
    data = _jnp.where(sp.data >= 0, sp.data, negative_slope * sp.data)
    return SparseTensor(sp.__class__((data, sp.indices), shape=sp.shape),
                        x.stop_gradient)


def relu6(x, name=None):
    sp = _coo(x)
    return SparseTensor(
        sp.__class__((_jnp.clip(sp.data, 0, 6), sp.indices), shape=sp.shape),
        x.stop_gradient)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, name=None):
    sp = _coo(x)
    data = sp.data * scale + bias if bias_after_scale else (sp.data + bias) * scale
    return SparseTensor(sp.__class__((data, sp.indices), shape=sp.shape),
                        x.stop_gradient)


def divide_scalar(x, scalar, name=None):
    sp = _coo(x)
    return SparseTensor(sp.__class__((sp.data / scalar, sp.indices),
                                     shape=sp.shape), x.stop_gradient)


def coalesce(x, name=None):
    """sparse_ops.yaml `coalesce`: merge duplicate coordinates (sum)."""
    sp = _coo(x).sum_duplicates()
    return SparseTensor(sp, x.stop_gradient)


def full_like(x, fill_value, dtype=None, name=None):
    from ..framework.dtype import convert_dtype

    sp = _coo(x)
    dt = sp.data.dtype if dtype is None else convert_dtype(dtype)
    return SparseTensor(
        sp.__class__((_jnp.full(sp.data.shape, fill_value, dt), sp.indices),
                     shape=sp.shape), x.stop_gradient)


def mask_as(x, mask, name=None):
    """sparse_ops.yaml `mask_as`: take dense x's values at mask's pattern."""
    sp = _coo(mask)
    xv = unwrap(x)
    vals = xv[tuple(sp.indices[:, i] for i in range(sp.indices.shape[1]))]
    return SparseTensor(sp.__class__((vals, sp.indices), shape=sp.shape))


def indices(x, name=None):
    return wrap(_coo(x).indices.T)


def values(x, name=None):
    return wrap(_coo(x).data)


def to_dense(x, name=None):
    return wrap(_coo(x).todense())


def to_sparse_coo(x, sparse_dim=None, name=None):
    from jax.experimental import sparse as jsp

    if isinstance(x, SparseTensor):
        return SparseTensor(_coo(x))
    a = unwrap(x)
    n = sparse_dim if sparse_dim is not None else a.ndim
    return SparseTensor(jsp.BCOO.fromdense(a, n_batch=0, n_dense=a.ndim - n))


def to_sparse_csr(x, name=None):
    from jax.experimental import sparse as jsp

    a = _coo(x).todense() if isinstance(x, SparseTensor) else unwrap(x)
    return SparseTensor(jsp.BCSR.fromdense(a))


def softmax(x, axis=-1, name=None):
    """sparse_ops.yaml `softmax`: softmax over stored values per row, with
    absent entries treated as -inf (CSR softmax semantics). Pattern-aware
    for any ndim: the leading indices form the segment key, segment max/sum
    normalize the stored values — no densification, sparse in/sparse out."""
    import jax

    sp = _coo(x).sum_duplicates()
    ndim = len(sp.shape)
    if axis not in (-1, ndim - 1):
        raise NotImplementedError(
            "sparse.softmax: only the last axis is supported (matches the "
            "reference CSR kernel, sparse_ops.yaml `softmax`)")
    lead = sp.indices[:, :-1]  # [nnz, ndim-1]
    # linearize the leading coordinates into one segment id
    seg = _jnp.zeros((sp.indices.shape[0],), _jnp.int32)
    nseg = 1
    for d in range(ndim - 1):
        seg = seg * sp.shape[d] + lead[:, d].astype(_jnp.int32)
        nseg *= sp.shape[d]
    smax = jax.ops.segment_max(sp.data, seg, num_segments=nseg)
    e = _jnp.exp(sp.data - smax[seg])
    ssum = jax.ops.segment_sum(e, seg, num_segments=nseg)
    return SparseTensor(sp.__class__((e / ssum[seg], sp.indices),
                                     shape=sp.shape), x.stop_gradient)


def transpose(x, perm, name=None):
    """Index-remap transpose (no densify)."""
    sp = _coo(x).sum_duplicates()
    idx = sp.indices[:, _jnp.asarray(perm)]
    shape = tuple(sp.shape[p] for p in perm)
    return SparseTensor(sp.__class__((sp.data, idx), shape=shape),
                        x.stop_gradient)


def reshape(x, shape, name=None):
    """Linear-index remap reshape (no densify)."""
    import numpy as _np

    sp = _coo(x).sum_duplicates()
    old = _np.asarray(sp.shape)
    shape = list(shape)
    if -1 in shape:
        known = int(_np.prod([s for s in shape if s != -1]))
        shape[shape.index(-1)] = int(_np.prod(old)) // known
    strides_old = _jnp.asarray(
        _np.concatenate([_np.cumprod(old[::-1])[::-1][1:], [1]]))
    lin = (sp.indices * strides_old[None, :]).sum(-1)
    new = _np.asarray(shape)
    strides_new = _np.concatenate([_np.cumprod(new[::-1])[::-1][1:], [1]])
    idx = _jnp.stack([(lin // int(s)) % int(d)
                      for s, d in zip(strides_new, new)], -1)
    return SparseTensor(sp.__class__((sp.data, idx), shape=tuple(shape)),
                        x.stop_gradient)


def slice(x, axes, starts, ends, name=None):
    """Host-side index filter (data-dependent nnz — eager only)."""
    import numpy as _np

    sp = _coo(x).sum_duplicates()
    idx = _np.asarray(sp.indices)
    data = _np.asarray(sp.data)
    shape = list(sp.shape)
    keep = _np.ones(idx.shape[0], bool)
    for ax, s, e in zip(axes, starts, ends):
        s = s + shape[ax] if s < 0 else s
        e = e + shape[ax] if e < 0 else min(e, shape[ax])
        keep &= (idx[:, ax] >= s) & (idx[:, ax] < e)
        shape[ax] = e - s
    idx = idx[keep].copy()
    for ax, s in zip(axes, starts):
        s = s + sp.shape[ax] if s < 0 else s
        idx[:, ax] -= s
    sp2 = sp.__class__((_jnp.asarray(data[keep]), _jnp.asarray(idx)),
                       shape=tuple(shape))
    return SparseTensor(sp2, x.stop_gradient)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """sparse_ops.yaml `addmm`: beta*input + alpha*(x @ y)."""
    prod = matmul(x, y)
    pv = _coo(prod).todense() if isinstance(prod, SparseTensor) else unwrap(prod)
    iv = _coo(input).todense() if isinstance(input, SparseTensor) else unwrap(input)
    return wrap(beta * iv + alpha * pv)


def mv(x, vec, name=None):
    """sparse matrix @ dense vector."""
    return matmul(x, vec)


def fused_attention(query, key, value, sparse_mask, key_padding_mask=None,
                    attn_mask=None, name=None):
    """sparse_ops.yaml `fused_attention`: attention restricted to
    sparse_mask's pattern. Dense QK^T on the MXU, additive -inf mask from
    the sparse pattern (the CUDA kernel's gather loop would be
    scatter-bound on TPU)."""
    import jax

    q, k, v = unwrap(query), unwrap(key), unwrap(value)
    d = q.shape[-1]
    scores = q @ _jnp.swapaxes(k, -1, -2) / _jnp.sqrt(float(d))
    mask_dense = _coo(sparse_mask).todense() != 0
    neg = _jnp.asarray(-1e9, scores.dtype)
    scores = _jnp.where(mask_dense, scores, neg)
    if attn_mask is not None:
        scores = scores + unwrap(attn_mask)
    if key_padding_mask is not None:
        pad = unwrap(key_padding_mask)[..., None, :]
        scores = _jnp.where(pad != 0, scores, neg)
    return wrap(jax.nn.softmax(scores, -1) @ v)

from . import nn  # noqa: E402,F401  (real module: conv3d/pool/BN layers)


def deg2rad(x, name=None):
    sp = _coo(x)
    import numpy as _np

    return SparseTensor(sp.__class__((sp.data * (_np.pi / 180.0),
                                      sp.indices), shape=sp.shape),
                        x.stop_gradient)


def rad2deg(x, name=None):
    sp = _coo(x)
    import numpy as _np

    return SparseTensor(sp.__class__((sp.data * (180.0 / _np.pi),
                                      sp.indices), shape=sp.shape),
                        x.stop_gradient)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """paddle.sparse.pca_lowrank: dense lowrank PCA of the materialized
    matrix (the factors are dense by definition)."""
    from .. import linalg

    from ..tensor_class import wrap

    dense = wrap(_coo(x).todense())
    return linalg.pca_lowrank(dense, q=q, center=center, niter=niter)
