"""paddle.sparse parity (python/paddle/sparse/, phi sparse kernels
paddle/phi/kernels/sparse/ — SURVEY.md §2.2).

TPU-native: sparse tensors wrap jax.experimental.sparse BCOO/BCSR; unary
math runs on the values, matmul goes through the BCOO matmul lowering
(which XLA executes as gather/scatter + dense MXU tiles).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..tensor_class import Tensor, unwrap, wrap

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "is_same_shape",
    "add", "subtract", "multiply", "divide", "matmul", "masked_matmul",
    "relu", "abs", "sin", "tanh", "sqrt", "pow", "neg", "cast",
    "transpose", "sum", "nn",
]


class SparseTensor(Tensor):
    """A Tensor whose _array is a jax BCOO/BCSR. Dense-only methods fall
    back through to_dense()."""

    def __init__(self, sp, stop_gradient=True):
        self._array = sp
        self.stop_gradient = stop_gradient
        self.grad = None
        self.name = None

    # paddle Tensor sparse surface
    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        from jax.experimental import sparse as jsp

        return isinstance(self._array, jsp.BCOO)

    def is_sparse_csr(self):
        from jax.experimental import sparse as jsp

        return isinstance(self._array, jsp.BCSR)

    def to_dense(self):
        return wrap(self._array.todense(), self.stop_gradient)

    def values(self):
        return wrap(self._array.data, self.stop_gradient)

    def indices(self):
        import jax.numpy as jnp

        return wrap(jnp.swapaxes(self._array.indices, -1, -2))

    def crows(self):
        return wrap(self._array.indptr)

    def cols(self):
        return wrap(self._array.indices)

    def nnz(self):
        return int(self._array.nse)

    def numpy(self):
        return np.asarray(self._array.todense())

    def __repr__(self):
        kind = "coo" if self.is_sparse_coo() else "csr"
        return (f"SparseTensor({kind}, shape={list(self._array.shape)}, "
                f"nnz={self.nnz()})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """paddle.sparse.sparse_coo_tensor parity: indices [ndim, nnz]."""
    import jax.numpy as jnp
    from jax.experimental import sparse as jsp

    idx = jnp.asarray(unwrap(indices)).T  # BCOO wants [nnz, ndim]
    vals = jnp.asarray(unwrap(values))
    if dtype is not None:
        from ..framework.dtype import convert_dtype

        vals = vals.astype(convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=0))
    sp = jsp.BCOO((vals, idx), shape=tuple(shape))
    return SparseTensor(sp, stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    import jax.numpy as jnp
    from jax.experimental import sparse as jsp

    vals = jnp.asarray(unwrap(values))
    if dtype is not None:
        from ..framework.dtype import convert_dtype

        vals = vals.astype(convert_dtype(dtype))
    sp = jsp.BCSR((vals, jnp.asarray(unwrap(cols)),
                   jnp.asarray(unwrap(crows))), shape=tuple(shape))
    return SparseTensor(sp, stop_gradient)


def is_same_shape(x, y) -> bool:
    return tuple(x._array.shape) == tuple(y._array.shape)


def _coo(x):
    from jax.experimental import sparse as jsp

    a = x._array
    return a if isinstance(a, jsp.BCOO) else a.to_bcoo()


def _unary(fn_name):
    import jax.numpy as jnp

    fn = getattr(jnp, fn_name)

    def op(x, name=None):
        sp = _coo(x)
        out = sp.__class__((fn(sp.data), sp.indices), shape=sp.shape)
        return SparseTensor(out, x.stop_gradient)

    op.__name__ = fn_name
    return op


sin = _unary("sin")
tanh = _unary("tanh")
sqrt = _unary("sqrt")
abs = _unary("abs")


def neg(x, name=None):
    sp = _coo(x)
    return SparseTensor(sp.__class__((-sp.data, sp.indices), shape=sp.shape),
                        x.stop_gradient)


def pow(x, factor, name=None):
    sp = _coo(x)
    return SparseTensor(sp.__class__((sp.data ** factor, sp.indices),
                                     shape=sp.shape), x.stop_gradient)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..framework.dtype import convert_dtype

    sp = _coo(x)
    data = sp.data if value_dtype is None else sp.data.astype(
        convert_dtype(value_dtype))
    idx = sp.indices if index_dtype is None else sp.indices.astype(
        convert_dtype(index_dtype))
    return SparseTensor(sp.__class__((data, idx), shape=sp.shape),
                        x.stop_gradient)


def relu(x, name=None):
    import jax.numpy as jnp

    sp = _coo(x)
    return SparseTensor(sp.__class__((jnp.maximum(sp.data, 0), sp.indices),
                                     shape=sp.shape), x.stop_gradient)


def _binary(opname, jop):
    def op(x, y, name=None):
        from jax.experimental import sparse as jsp

        if isinstance(x, SparseTensor) and isinstance(y, SparseTensor):
            # same-pattern fast path, else densify (reference CPU kernels
            # merge patterns; pattern-union on TPU would be scatter-heavy)
            xs, ys = _coo(x), _coo(y)
            import jax.numpy as jnp

            if xs.indices.shape == ys.indices.shape and bool(
                    jnp.all(xs.indices == ys.indices)):
                return SparseTensor(
                    xs.__class__((jop(xs.data, ys.data), xs.indices),
                                 shape=xs.shape), x.stop_gradient)
            dense = jop(xs.todense(), ys.todense())
            return wrap(dense)
        raise TypeError(f"sparse.{opname} expects two sparse tensors")

    op.__name__ = opname
    return op


import jax.numpy as _jnp  # noqa: E402

add = _binary("add", _jnp.add)
subtract = _binary("subtract", _jnp.subtract)
multiply = _binary("multiply", _jnp.multiply)
divide = _binary("divide", _jnp.divide)


def matmul(x, y, name=None):
    """sparse @ dense (or sparse @ sparse → dense)."""
    from jax.experimental import sparse as jsp

    if isinstance(x, SparseTensor):
        xs = _coo(x)
        yv = _coo(y) if isinstance(y, SparseTensor) else unwrap(y)
        out = xs @ yv
        if isinstance(out, jsp.BCOO):
            return SparseTensor(out)
        return wrap(out)
    raise TypeError("sparse.matmul expects a sparse lhs")


def masked_matmul(x, y, mask, name=None):
    """dense @ dense evaluated only at mask's nonzeros (sddmm)."""
    import jax.numpy as jnp

    ms = _coo(mask)
    xv, yv = unwrap(x), unwrap(y)
    rows, cols = ms.indices[:, 0], ms.indices[:, 1]
    vals = jnp.einsum("nk,nk->n", xv[rows, :], yv[:, cols].T)
    return SparseTensor(ms.__class__((vals, ms.indices), shape=ms.shape))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    import jax.numpy as jnp

    sp = _coo(x)
    if axis is None:
        out = sp.data.sum()
        return wrap(out if not keepdim else out.reshape((1,) * len(sp.shape)))
    return wrap(jnp.sum(sp.todense(), axis=axis, keepdims=keepdim))


class nn:
    """paddle.sparse.nn subset: ReLU layer (conv3d submanifold kernels are
    a tracked gap — SURVEY §2.2 sparse conv)."""

    class ReLU:
        def __call__(self, x):
            return relu(x)
