"""paddle.sparse.nn.functional parity (python/paddle/sparse/nn/functional/):
functional faces of the sparse conv/pool family + value-wise activations."""
from __future__ import annotations


def _pkg():
    from paddle_tpu.sparse import nn as _nn

    return _nn


def conv3d(*args, **kwargs):
    return _pkg().conv3d(*args, **kwargs)


def subm_conv3d(*args, **kwargs):
    return _pkg().subm_conv3d(*args, **kwargs)


def max_pool3d(*args, **kwargs):
    return _pkg().max_pool3d(*args, **kwargs)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", name=None):
    return _pkg()._conv2d_impl(x, weight, bias, stride, padding, dilation,
                               groups, data_format, subm=False)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    return _pkg()._conv2d_impl(x, weight, bias, stride, padding, dilation,
                               groups, data_format, subm=True)


def relu(x, name=None):
    from .. import relu as _f

    return _f(x)


def relu6(x, name=None):
    from .. import relu6 as _f

    return _f(x)


def leaky_relu(x, negative_slope=0.01, name=None):
    from .. import leaky_relu as _f

    return _f(x, negative_slope)


def softmax(x, axis=-1, name=None):
    from .. import softmax as _f

    return _f(x, axis)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    from .. import fused_attention

    return fused_attention(query, key, value, sparse_mask,
                           key_padding_mask, attn_mask)


def subm_conv2d_igemm(*args, **kwargs):
    """Implicit-GEMM variant: on TPU the dense-MXU path IS the GEMM
    formulation, so this aliases subm_conv2d."""
    return subm_conv2d(*args, **kwargs)


def subm_conv3d_igemm(*args, **kwargs):
    return subm_conv3d(*args, **kwargs)
