"""paddle.sparse.nn parity (python/paddle/sparse/nn/, phi sparse conv
kernels paddle/phi/kernels/sparse/conv_kernel.h, pool_kernel.cc).

TPU-native design: sparse conv/pool compute DENSE on the MXU (XLA
conv_general_dilated over NDHWC) with sparse COO storage at the module
boundary. The reference's gather-GEMM-scatter CUDA pipeline exists because
GPU warps can chase indices; on TPU the systolic array wants dense tiles,
and typical point-cloud occupancies (1-10%) still beat an index-chasing
emulation after XLA fusion. SubmConv3D preserves the input's coordinate
pattern exactly (submanifold semantics); Conv3D re-sparsifies the dense
output.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...tensor_class import unwrap, wrap
from ...nn import Layer
from ...nn.initializer_core import Uniform, Constant


def _triple(v):
    return (v, v, v) if isinstance(v, int) else tuple(v)


def _dense_ndhwc(x):
    from .. import SparseTensor, _coo

    if isinstance(x, SparseTensor):
        return _coo(x).todense(), _coo(x)
    return unwrap(x), None


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    """F-style sparse conv3d (sparse_ops.yaml `conv3d`). weight layout
    [kd, kh, kw, c_in/groups, c_out] (the reference's DHWCK)."""
    from .. import SparseTensor, to_sparse_coo

    dense, _ = _dense_ndhwc(x)
    w = unwrap(weight)
    s, p, d = _triple(stride), _triple(padding), _triple(dilation)
    out = jax.lax.conv_general_dilated(
        dense.astype(w.dtype), w,
        window_strides=s,
        padding=[(pi, pi) for pi in p],
        rhs_dilation=d,
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
        feature_group_count=groups)
    if bias is not None:
        out = out + unwrap(bias)
    return to_sparse_coo(wrap(out), sparse_dim=4)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold conv3d (sparse_ops.yaml `conv3d` subm=True): the output
    keeps the INPUT's coordinate set — values elsewhere are dropped."""
    from .. import SparseTensor, _coo

    dense, sp = _dense_ndhwc(x)
    w = unwrap(weight)
    d = _triple(dilation)
    k = w.shape[:3]
    # 'same' padding so output spatial dims == input dims (subm requires it)
    pad = [((ki - 1) * di // 2, (ki - 1) * di - (ki - 1) * di // 2)
           for ki, di in zip(k, d)]
    out = jax.lax.conv_general_dilated(
        dense.astype(w.dtype), w, window_strides=(1, 1, 1), padding=pad,
        rhs_dilation=d, dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
        feature_group_count=groups)
    if bias is not None:
        out = out + unwrap(bias)
    if sp is None:
        return wrap(out)
    # restrict to the input pattern: gather dense outputs at input coords
    coords = sp.indices  # [nnz, 4] over (n, d, h, w); dense tail = channels
    vals = out[tuple(coords[:, i] for i in range(coords.shape[1]))]
    shape = tuple(sp.shape[:-1]) + (w.shape[-1],)
    return SparseTensor(sp.__class__((vals, coords), shape=shape))


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    """sparse_ops.yaml `maxpool`: dense reduce_window, re-sparsified."""
    from .. import to_sparse_coo

    dense, _ = _dense_ndhwc(x)
    k = _triple(kernel_size)
    s = _triple(stride if stride is not None else kernel_size)
    p = _triple(padding)
    neg = jnp.asarray(-jnp.inf, dense.dtype)
    padded = jnp.pad(dense, ((0, 0),) + tuple((pi, pi) for pi in p)
                     + ((0, 0),), constant_values=neg)
    out = jax.lax.reduce_window(
        padded, neg, jax.lax.max, (1,) + k + (1,), (1,) + s + (1,), "VALID")
    out = jnp.where(jnp.isinf(out), 0.0, out)
    return to_sparse_coo(wrap(out), sparse_dim=4)


class _SparseConvBase(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__()
        k = _triple(kernel_size)
        fan_in = in_channels * k[0] * k[1] * k[2]
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            list(k) + [in_channels // groups, out_channels],
            default_initializer=Uniform(-bound, bound))
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], is_bias=True,
            default_initializer=Uniform(-bound, bound))
        self._cfg = (stride, padding, dilation, groups, data_format)

    def extra_repr(self):
        return f"weight={list(self.weight.shape)}"


class Conv3D(_SparseConvBase):
    """paddle.sparse.nn.Conv3D."""

    def forward(self, x):
        stride, padding, dilation, groups, fmt = self._cfg
        return conv3d(x, self.weight, self.bias, stride, padding, dilation,
                      groups, fmt)


class SubmConv3D(_SparseConvBase):
    """paddle.sparse.nn.SubmConv3D (submanifold: output pattern = input)."""

    def forward(self, x):
        stride, padding, dilation, groups, fmt = self._cfg
        return subm_conv3d(x, self.weight, self.bias, stride, padding,
                           dilation, groups, fmt)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 data_format="NDHWC"):
        super().__init__()
        self._cfg = (kernel_size, stride, padding, ceil_mode, data_format)

    def forward(self, x):
        k, s, p, cm, fmt = self._cfg
        return max_pool3d(x, k, s, p, cm, fmt)


class BatchNorm(Layer):
    """paddle.sparse.nn.BatchNorm (sparse_ops.yaml `batch_norm_`):
    normalizes the stored values per channel — exactly the reference
    semantics (only nonzero sites contribute statistics)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [num_features], default_initializer=Constant(1.0))
        self.bias = self.create_parameter([num_features], is_bias=True)
        # registered buffers → serialized in state_dict like the dense BN
        self._mean = self.register_buffer(
            "_mean", wrap(jnp.zeros((num_features,), jnp.float32)))
        self._var = self.register_buffer(
            "_var", wrap(jnp.ones((num_features,), jnp.float32)))
        self.momentum = momentum
        self.epsilon = epsilon
        self.training = True

    def forward(self, x):
        from .. import SparseTensor, _coo

        sp = _coo(x)
        vals = sp.data  # [nnz, C]
        if self.training:
            mean = vals.mean(0)
            var = vals.var(0)
            m = self.momentum
            self._mean.set_value(
                m * unwrap(self._mean) + (1 - m) * mean.astype(jnp.float32))
            self._var.set_value(
                m * unwrap(self._var) + (1 - m) * var.astype(jnp.float32))
        else:
            mean = unwrap(self._mean).astype(vals.dtype)
            var = unwrap(self._var).astype(vals.dtype)
        w, b = unwrap(self.weight), unwrap(self.bias)
        out = (vals - mean) * jax.lax.rsqrt(var + self.epsilon) * w + b
        return SparseTensor(sp.__class__((out.astype(vals.dtype), sp.indices),
                                         shape=sp.shape))


class SyncBatchNorm(BatchNorm):
    """Cross-replica BN: under pjit/GSPMD the batch statistics reduce over
    the data-parallel mesh axis automatically (mean over the global nnz
    axis); eager multi-process training should all_reduce the moments —
    matching sync_batch_norm_ (sparse_ops.yaml)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, BatchNorm) and not isinstance(layer, cls):
            out = cls(int(unwrap(layer.weight).shape[0]),
                      momentum=layer.momentum, epsilon=layer.epsilon)
            out.weight = layer.weight
            out.bias = layer.bias
            out._mean.set_value(unwrap(layer._mean))
            out._var.set_value(unwrap(layer._var))
            return out
        for name, sub in list(getattr(layer, "_sub_layers", {}).items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class ReLU(Layer):
    def forward(self, x):
        from .. import relu as _relu

        return _relu(x)


class ReLU6(Layer):
    def forward(self, x):
        from .. import relu6 as _relu6

        return _relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        from .. import leaky_relu as _lr

        return _lr(x, self.negative_slope)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        from .. import softmax as _softmax

        return _softmax(x, self.axis)


from . import functional  # noqa: E402,F401


def _conv2d_impl(x, weight, bias, stride, padding, dilation, groups,
                 data_format, subm):
    """2-D sparse conv via the 3-D path (depth-1 axis) — one kernel serves
    both ranks, like the reference's shared sparse conv kernel."""
    from .. import SparseTensor, _coo
    from ...tensor_class import unwrap, wrap

    import jax.numpy as jnp

    def to3d_stride(v):
        return (1, v, v) if isinstance(v, int) else (1, *v)

    def to3d_pad(v):
        # depth axis must NOT be padded: kernel depth is 1, and any depth
        # padding would shift the real result off plane 0
        return (0, v, v) if isinstance(v, int) else (0, *v)

    from jax.experimental import sparse as jsp

    sp = _coo(x)
    dense5 = sp.todense()[:, None]               # [N, 1, H, W, C]
    w5 = unwrap(weight)[None]                    # [1, kh, kw, cin/g, cout]
    if subm:
        x5 = SparseTensor(jsp.BCOO.fromdense(dense5, n_dense=1))
        out = subm_conv3d(x5, wrap(w5), bias, to3d_stride(stride),
                          to3d_pad(padding), to3d_stride(dilation), groups)
        o = _coo(out).todense()[:, 0]
    else:
        # conv3d accepts dense input directly — skip the BCOO round-trip
        out = conv3d(wrap(dense5), wrap(w5), bias, to3d_stride(stride),
                     to3d_pad(padding), to3d_stride(dilation), groups)
        o = _coo(out).todense()[:, 0]            # drop the depth-1 axis
    from .. import to_sparse_coo

    return to_sparse_coo(wrap(o), sparse_dim=3)


class Conv2D(_SparseConvBase):
    """paddle.sparse.nn.Conv2D (NHWC)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, bias_attr=None,
                 data_format="NHWC"):
        Layer.__init__(self)
        k = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        fan_in = in_channels * k[0] * k[1]
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            list(k) + [in_channels // groups, out_channels],
            default_initializer=Uniform(-bound, bound))
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], is_bias=True,
            default_initializer=Uniform(-bound, bound))
        self._cfg = (stride, padding, dilation, groups, data_format)

    def forward(self, x):
        stride, padding, dilation, groups, fmt = self._cfg
        return _conv2d_impl(x, self.weight, self.bias, stride, padding,
                            dilation, groups, fmt, subm=False)


class SubmConv2D(Conv2D):
    """paddle.sparse.nn.SubmConv2D (output pattern = input pattern)."""

    def forward(self, x):
        stride, padding, dilation, groups, fmt = self._cfg
        return _conv2d_impl(x, self.weight, self.bias, stride, padding,
                            dilation, groups, fmt, subm=True)
