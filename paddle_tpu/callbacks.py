"""paddle.callbacks parity (python/paddle/callbacks.py): re-export of the
hapi callback family."""
from .hapi.callbacks import (  # noqa: F401
    Callback, EarlyStopping, LRScheduler, ModelCheckpoint, ProgBarLogger)

try:  # optional extras if present
    from .hapi.callbacks import ReduceLROnPlateau, VisualDL  # noqa: F401
except ImportError:  # pragma: no cover
    pass

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "LRScheduler",
           "EarlyStopping"]
