"""MultivariateNormal.

Reference parity: python/paddle/distribution/multivariate_normal.py
(loc + one of covariance_matrix / precision_matrix / scale_tril).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..ops.registry import apply
from ..framework import random as _random
from .distribution import Distribution, _arr, _param, _shape_of


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None):
        self.loc = _param(loc)
        if len(_shape_of(self.loc)) < 1:
            raise ValueError("MultivariateNormal loc must be at least 1-D")
        given = [a is not None
                 for a in (covariance_matrix, precision_matrix, scale_tril)]
        if sum(given) != 1:
            raise ValueError(
                "exactly one of covariance_matrix, precision_matrix, "
                "scale_tril must be specified")
        if scale_tril is not None:
            self.scale_tril = _param(scale_tril)
        elif covariance_matrix is not None:
            self.covariance_matrix = _param(covariance_matrix)
            self.scale_tril = apply("mvn_cholesky", jnp.linalg.cholesky,
                                    self.covariance_matrix)
        else:
            self.precision_matrix = _param(precision_matrix)
            self.scale_tril = apply(
                "mvn_prec_cholesky",
                lambda pm: jnp.linalg.cholesky(jnp.linalg.inv(pm)),
                self.precision_matrix)
        lshape, sshape = _shape_of(self.loc), _shape_of(self.scale_tril)
        d = lshape[-1]
        if sshape[-1] != d or sshape[-2] != d:
            raise ValueError("scale_tril/covariance shape mismatch with loc")
        batch = jnp.broadcast_shapes(lshape[:-1], sshape[:-2])
        super().__init__(batch_shape=batch, event_shape=(d,))

    @property
    def mean(self):
        return apply("mvn_mean",
                     lambda l: jnp.broadcast_to(
                         l, tuple(self.batch_shape) + tuple(self.event_shape)),
                     self.loc)

    @property
    def variance(self):
        def fn(st):
            var = (st * st).sum(-1)
            return jnp.broadcast_to(
                var, tuple(self.batch_shape) + tuple(self.event_shape))

        return apply("mvn_variance", fn, self.scale_tril)

    def rsample(self, shape=()):
        out_shape = self._extend_shape(shape)
        key = _random.next_key()

        def fn(l, st):
            eps = jax.random.normal(key, out_shape, dtype=l.dtype)
            return l + jnp.einsum("...ij,...j->...i", st, eps)

        return apply("mvn_rsample", fn, self.loc, self.scale_tril)

    def log_prob(self, value):
        def fn(l, st, v):
            diff = v - l
            # solve L y = diff  → mahalanobis = ||y||²
            y = jax.scipy.linalg.solve_triangular(st, diff[..., None],
                                                  lower=True)[..., 0]
            m = (y * y).sum(-1)
            half_logdet = jnp.log(
                jnp.diagonal(st, axis1=-2, axis2=-1)).sum(-1)
            d = v.shape[-1]
            return -0.5 * (m + d * math.log(2 * math.pi)) - half_logdet

        return apply("mvn_log_prob", fn, self.loc, self.scale_tril, value)

    def entropy(self):
        def fn(st):
            d = st.shape[-1]
            half_logdet = jnp.log(
                jnp.diagonal(st, axis1=-2, axis2=-1)).sum(-1)
            h = 0.5 * d * (1 + math.log(2 * math.pi)) + half_logdet
            return jnp.broadcast_to(h, tuple(self.batch_shape))

        return apply("mvn_entropy", fn, self.scale_tril)

    def kl_divergence(self, other):
        from .kl import kl_divergence

        return kl_divergence(self, other)
