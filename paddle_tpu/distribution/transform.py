"""Bijective transforms.

Reference parity: python/paddle/distribution/transform.py — the full
``__all__`` list (Transform, AbsTransform, AffineTransform, ChainTransform,
ExpTransform, IndependentTransform, PowerTransform, ReshapeTransform,
SigmoidTransform, SoftmaxTransform, StackTransform, StickBreakingTransform,
TanhTransform) with forward/inverse/forward_log_det_jacobian/
inverse_log_det_jacobian and shape propagation.

TPU-native: pure jnp math on unwrapped arrays, wrapped back into Tensors via
the op registry so transforms stay differentiable on the eager tape.
"""
from __future__ import annotations

import enum
import math

import jax
import jax.numpy as jnp

from ..ops.registry import apply
from ..tensor_class import Tensor, unwrap
from .distribution import _to_arr

__all__ = [
    "Type",
    "Transform",
    "AbsTransform",
    "AffineTransform",
    "ChainTransform",
    "ExpTransform",
    "IndependentTransform",
    "PowerTransform",
    "ReshapeTransform",
    "SigmoidTransform",
    "SoftmaxTransform",
    "StackTransform",
    "StickBreakingTransform",
    "TanhTransform",
]


class Type(enum.Enum):
    """Mapping type of a transformation (transform.py:57)."""

    BIJECTION = "bijection"
    INJECTION = "injection"
    SURJECTION = "surjection"
    OTHER = "other"

    @classmethod
    def is_injective(cls, _type):
        return _type in (cls.BIJECTION, cls.INJECTION)


class Transform:
    _type = Type.OTHER

    @property
    def type(self):
        return self._type

    # event dims consumed/produced (paddle's _domain/_codomain event_rank)
    _domain_event_rank = 0
    _codomain_event_rank = 0

    def forward(self, x):
        return apply(f"{type(self).__name__.lower()}_fwd", self._forward, x)

    def inverse(self, y):
        return apply(f"{type(self).__name__.lower()}_inv", self._inverse, y)

    def forward_log_det_jacobian(self, x):
        return apply(f"{type(self).__name__.lower()}_fldj",
                     self._forward_log_det_jacobian, x)

    def inverse_log_det_jacobian(self, y):
        return apply(f"{type(self).__name__.lower()}_ildj",
                     self._inverse_log_det_jacobian, y)

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    # ---- raw-array implementations (override) --------------------------------
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def _inverse_log_det_jacobian(self, y):
        # default: -fldj(inverse(y))
        return -self._forward_log_det_jacobian(self._inverse(y))


class AbsTransform(Transform):
    """y = |x| (surjective; inverse returns the positive branch)."""

    _type = Type.SURJECTION

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _forward_log_det_jacobian(self, x):
        return jnp.zeros_like(x)


class AffineTransform(Transform):
    """y = loc + scale * x."""

    _type = Type.BIJECTION

    def __init__(self, loc, scale):
        self.loc = _to_arr(loc)
        self.scale = _to_arr(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), jnp.shape(x))


class ExpTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    """y = x ** power on the positive half-line."""

    _type = Type.BIJECTION

    def __init__(self, power):
        self.power = _to_arr(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        # log(1 - tanh(x)^2) = 2 (log2 - x - softplus(-2x))
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    """x → softmax(x) (surjective onto the simplex; inverse = log up to a
    constant, matching the reference)."""

    _type = Type.OTHER
    _domain_event_rank = 1
    _codomain_event_rank = 1

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)


class StickBreakingTransform(Transform):
    """R^{K-1} → open simplex in R^K via stick breaking."""

    _type = Type.BIJECTION
    _domain_event_rank = 1
    _codomain_event_rank = 1

    def _forward(self, x):
        k = x.shape[-1]
        offset = jnp.arange(k, 0, -1, dtype=x.dtype)
        z = jax.nn.sigmoid(x - jnp.log(offset))
        zcum = jnp.cumprod(1 - z, axis=-1)
        pad = jnp.ones_like(x[..., :1])
        return jnp.concatenate([z, pad], -1) * jnp.concatenate([pad, zcum], -1)

    def _inverse(self, y):
        ycum = jnp.cumsum(y[..., :-1], axis=-1)
        rem = 1 - ycum
        k = y.shape[-1] - 1
        offset = jnp.arange(k, 0, -1, dtype=y.dtype)
        z = y[..., :-1] / jnp.concatenate(
            [jnp.ones_like(y[..., :1]), rem[..., :-1]], -1)
        return jnp.log(z) - jnp.log1p(-z) + jnp.log(offset)

    def _forward_log_det_jacobian(self, x):
        k = x.shape[-1]
        offset = jnp.arange(k, 0, -1, dtype=x.dtype)
        t = x - jnp.log(offset)
        z = jax.nn.sigmoid(t)
        zcum1 = jnp.cumprod(1 - z, axis=-1)
        shifted = jnp.concatenate(
            [jnp.ones_like(x[..., :1]), zcum1[..., :-1]], -1)
        return (jnp.log(z) + jnp.log1p(-z) + jnp.log(shifted)
                ).sum(-1) - 0  # log|det J|

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class ChainTransform(Transform):
    """Composition t_n ∘ ... ∘ t_1 (applied left to right)."""

    def __init__(self, transforms):
        self.transforms = list(transforms)
        self._type = (Type.BIJECTION
                      if all(Type.is_injective(t.type) for t in self.transforms)
                      else Type.OTHER)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + _sum_event(t._forward_log_det_jacobian(x),
                                       self._max_event_rank()
                                       - t._codomain_event_rank)
            x = t._forward(x)
        return total

    def _max_event_rank(self):
        return max([t._codomain_event_rank for t in self.transforms] + [0])

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return shape


def _sum_event(x, ndims):
    for _ in range(max(0, ndims)):
        x = x.sum(-1)
    return x


class IndependentTransform(Transform):
    """Reinterpret ``reinterpreted_batch_rank`` batch dims as event dims:
    the log-det sums over them."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        self._type = base.type
        self._domain_event_rank = (base._domain_event_rank
                                   + self.reinterpreted_batch_rank)
        self._codomain_event_rank = (base._codomain_event_rank
                                     + self.reinterpreted_batch_rank)

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        return _sum_event(self.base._forward_log_det_jacobian(x),
                          self.reinterpreted_batch_rank)

    def forward_shape(self, shape):
        return self.base.forward_shape(shape)

    def inverse_shape(self, shape):
        return self.base.inverse_shape(shape)


class ReshapeTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, in_event_shape, out_event_shape):
        import numpy as np

        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        if int(np.prod(self.in_event_shape)) != int(np.prod(self.out_event_shape)):
            raise ValueError("in_event_shape and out_event_shape sizes differ")
        self._domain_event_rank = len(self.in_event_shape)
        self._codomain_event_rank = len(self.out_event_shape)

    def _forward(self, x):
        batch = x.shape[: x.ndim - len(self.in_event_shape)]
        return x.reshape(tuple(batch) + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[: y.ndim - len(self.out_event_shape)]
        return y.reshape(tuple(batch) + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        batch = x.shape[: x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, x.dtype)

    def forward_shape(self, shape):
        n = len(self.in_event_shape)
        if tuple(shape[len(shape) - n:]) != self.in_event_shape:
            raise ValueError("shape mismatch in ReshapeTransform.forward_shape")
        return tuple(shape[: len(shape) - n]) + self.out_event_shape

    def inverse_shape(self, shape):
        n = len(self.out_event_shape)
        if tuple(shape[len(shape) - n:]) != self.out_event_shape:
            raise ValueError("shape mismatch in ReshapeTransform.inverse_shape")
        return tuple(shape[: len(shape) - n]) + self.in_event_shape


class StackTransform(Transform):
    """Apply a list of transforms to slices along ``axis``."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = int(axis)
        self._type = (Type.BIJECTION
                      if all(Type.is_injective(t.type) for t in self.transforms)
                      else Type.OTHER)

    def _map(self, fns, x):
        parts = [
            fn(xi) for fn, xi in zip(
                fns, jnp.split(x, len(self.transforms), axis=self.axis))
        ]
        return jnp.concatenate(parts, axis=self.axis)

    def _forward(self, x):
        return self._map([t._forward for t in self.transforms], x)

    def _inverse(self, y):
        return self._map([t._inverse for t in self.transforms], y)

    def _forward_log_det_jacobian(self, x):
        return self._map(
            [t._forward_log_det_jacobian for t in self.transforms], x)
