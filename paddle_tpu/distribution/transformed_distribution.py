"""TransformedDistribution + Independent.

Reference parity: python/paddle/distribution/transformed_distribution.py and
independent.py.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..ops.registry import apply
from ..tensor_class import Tensor, unwrap
from .distribution import Distribution, _shape_tuple
from .transform import ChainTransform, Transform, Type, _sum_event


class TransformedDistribution(Distribution):
    """base distribution pushed through a chain of transforms."""

    def __init__(self, base, transforms):
        if isinstance(transforms, Transform):
            transforms = [transforms]
        for t in transforms:
            if not isinstance(t, Transform):
                raise TypeError(f"not a Transform: {t!r}")
        self.base = base
        self.transforms = list(transforms)
        chain = ChainTransform(self.transforms)
        base_shape = tuple(base.batch_shape) + tuple(base.event_shape)
        out_shape = chain.forward_shape(base_shape)
        event_rank = max(chain._codomain_event_rank, len(base.event_shape))
        cut = len(out_shape) - event_rank
        super().__init__(batch_shape=out_shape[:cut],
                         event_shape=out_shape[cut:])
        self._chain = chain

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def sample(self, shape=()):
        from ..autograd import tape as _tape

        with _tape.no_grad():
            out = self.rsample(shape)
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        event_rank = max(self._chain._codomain_event_rank,
                         len(self.base.event_shape))
        x = value
        terms = []
        for t in reversed(self.transforms):
            if not Type.is_injective(t.type):
                raise NotImplementedError(
                    "log_prob through a non-injective transform")
            x_prev = t.inverse(x)
            ldj = t.forward_log_det_jacobian(x_prev)
            terms.append((ldj, event_rank - t._codomain_event_rank))
            x = x_prev
            event_rank = max(event_rank - t._codomain_event_rank
                             + t._domain_event_rank, len(self.base.event_shape))
        base_lp = self.base.log_prob(x)

        def fn(blp, *ldjs):
            total = blp
            for (arr, extra) in zip(ldjs, [e for (_, e) in terms]):
                total = total - _sum_event(arr, extra)
            return total

        return apply("transformed_log_prob", fn, base_lp,
                     *[ldj for (ldj, _) in terms])


class Independent(Distribution):
    """Reinterpret trailing batch dims of ``base`` as event dims
    (python/paddle/distribution/independent.py)."""

    def __init__(self, base, reinterpreted_batch_rank):
        r = int(reinterpreted_batch_rank)
        if r <= 0 or r > len(base.batch_shape):
            raise ValueError(
                f"reinterpreted_batch_rank must be in (0, {len(base.batch_shape)}]")
        self.base = base
        self.reinterpreted_batch_rank = r
        bshape = tuple(base.batch_shape)
        super().__init__(
            batch_shape=bshape[: len(bshape) - r],
            event_shape=bshape[len(bshape) - r:] + tuple(base.event_shape))

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        return apply("independent_log_prob",
                     lambda a: _sum_event(a, self.reinterpreted_batch_rank), lp)

    def entropy(self):
        h = self.base.entropy()
        return apply("independent_entropy",
                     lambda a: _sum_event(a, self.reinterpreted_batch_rank), h)
