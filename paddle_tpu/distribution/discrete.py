"""Discrete distributions.

Reference parity: python/paddle/distribution/{bernoulli,binomial,categorical,
geometric,multinomial,poisson}.py. Sampling via jax.random; none are
reparameterizable, so only ``sample`` is offered (rsample raises, matching
the reference's behavior for discrete families).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from ..ops.registry import apply
from ..framework import random as _random
from ..autograd import tape as _tape
from .distribution import (Distribution, ExponentialFamily, _arr, _param,
                           _shape_of, _shape_tuple)


def _probs_to_logits(p, eps=1e-7):
    pc = jnp.clip(p, eps, 1 - eps)
    return jnp.log(pc) - jnp.log1p(-pc)


class Bernoulli(ExponentialFamily):
    """python/paddle/distribution/bernoulli.py parity (probs)."""

    def __init__(self, probs, name=None):
        self.probs = _param(probs)
        super().__init__(batch_shape=_shape_of(self.probs))

    @property
    def logits(self):
        return apply("bernoulli_logits", _probs_to_logits, self.probs)

    @property
    def mean(self):
        return apply("bernoulli_mean", lambda p: p + 0, self.probs)

    @property
    def variance(self):
        return apply("bernoulli_variance", lambda p: p * (1 - p), self.probs)

    def sample(self, shape=()):
        out_shape = self._extend_shape(shape)
        key = _random.next_key()

        def fn(p):
            return jax.random.bernoulli(
                key, jnp.broadcast_to(p, out_shape)).astype(p.dtype)

        with _tape.no_grad():
            out = apply("bernoulli_sample", fn, self.probs, differentiable=False)
        out.stop_gradient = True
        return out

    def rsample(self, shape=(), temperature=1.0):
        """Gumbel-softmax relaxation (bernoulli.py rsample parity: returns a
        continuous relaxation in (0,1), differentiable wrt probs)."""
        out_shape = self._extend_shape(shape)
        key = _random.next_key()
        t = float(temperature)

        def fn(p):
            logits = _probs_to_logits(p)
            u = jax.random.logistic(key, out_shape, dtype=p.dtype)
            return jax.nn.sigmoid((logits + u) / t)

        return apply("bernoulli_rsample", fn, self.probs)

    def log_prob(self, value):
        def fn(p, v):
            eps = 1e-7
            pc = jnp.clip(p, eps, 1 - eps)
            return v * jnp.log(pc) + (1 - v) * jnp.log1p(-pc)

        return apply("bernoulli_log_prob", fn, self.probs, value)

    def entropy(self):
        def fn(p):
            eps = 1e-7
            pc = jnp.clip(p, eps, 1 - eps)
            return -(pc * jnp.log(pc) + (1 - pc) * jnp.log1p(-pc))

        return apply("bernoulli_entropy", fn, self.probs)

    def cdf(self, value):
        def fn(p, v):
            return jnp.where(v < 0, 0.0, jnp.where(v < 1, 1 - p, 1.0))

        return apply("bernoulli_cdf", fn, self.probs, value)

    def kl_divergence(self, other):
        from .kl import kl_divergence

        return kl_divergence(self, other)


class Categorical(Distribution):
    """python/paddle/distribution/categorical.py parity (logits)."""

    def __init__(self, logits, name=None):
        self.logits = _param(logits)
        lshape = _shape_of(self.logits)
        if len(lshape) < 1:
            raise ValueError("Categorical logits must be at least 1-D")
        super().__init__(batch_shape=lshape[:-1])

    @property
    def probs(self):
        # paddle's Categorical accepts unnormalized non-negative weights in
        # `logits`... the modern surface treats them as log-weights
        return apply("categorical_probs", jax.nn.softmax, self.logits)

    def sample(self, shape=()):
        out_shape = _shape_tuple(shape) + tuple(self.batch_shape)
        key = _random.next_key()

        def fn(lg):
            return jax.random.categorical(key, lg, shape=out_shape)

        with _tape.no_grad():
            out = apply("categorical_sample", fn, self.logits,
                        differentiable=False)
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        def fn(lg, v):
            logp = jax.nn.log_softmax(lg, axis=-1)
            # value may carry extra sample dims in front of the batch dims
            logp = jnp.broadcast_to(logp, jnp.shape(v) + logp.shape[-1:])
            return jnp.take_along_axis(
                logp, v.astype(jnp.int32)[..., None], axis=-1)[..., 0]

        return apply("categorical_log_prob", fn, self.logits, value)

    def probabilities(self, value):
        return self.prob(value)

    def prob(self, value):
        return apply("categorical_prob", jnp.exp, self.log_prob(value))

    def entropy(self):
        def fn(lg):
            logp = jax.nn.log_softmax(lg, axis=-1)
            return -(jnp.exp(logp) * logp).sum(-1)

        return apply("categorical_entropy", fn, self.logits)

    def kl_divergence(self, other):
        from .kl import kl_divergence

        return kl_divergence(self, other)


class Geometric(Distribution):
    """python/paddle/distribution/geometric.py parity: #failures before the
    first success, support {0, 1, 2, ...}."""

    def __init__(self, probs):
        self.probs = _param(probs)
        super().__init__(batch_shape=_shape_of(self.probs))

    @property
    def mean(self):
        return apply("geometric_mean", lambda p: (1 - p) / p, self.probs)

    @property
    def variance(self):
        return apply("geometric_variance", lambda p: (1 - p) / (p * p),
                     self.probs)

    @property
    def stddev(self):
        return apply("geometric_stddev",
                     lambda p: jnp.sqrt(1 - p) / p, self.probs)

    def sample(self, shape=()):
        out_shape = self._extend_shape(shape)
        key = _random.next_key()

        def fn(p):
            u = jax.random.uniform(
                key, out_shape, dtype=p.dtype,
                minval=jnp.finfo(p.dtype).tiny)
            return jnp.floor(jnp.log(u) / jnp.log1p(-p))

        with _tape.no_grad():
            out = apply("geometric_sample", fn, self.probs, differentiable=False)
        out.stop_gradient = True
        return out

    def rsample(self, shape=()):
        """Continuous relaxation: the underlying exponential draw, as in the
        reference (geometric.py rsample uses uniform reparameterization)."""
        out_shape = self._extend_shape(shape)
        key = _random.next_key()

        def fn(p):
            u = jax.random.uniform(key, out_shape, dtype=p.dtype,
                                   minval=jnp.finfo(p.dtype).tiny)
            return jnp.log(u) / jnp.log1p(-p)

        return apply("geometric_rsample", fn, self.probs)

    def log_prob(self, value):
        def fn(p, v):
            return v * jnp.log1p(-p) + jnp.log(p)

        return apply("geometric_log_prob", fn, self.probs, value)

    def pmf(self, value):
        return self.prob(value)

    def entropy(self):
        def fn(p):
            q = 1 - p
            return -(q * jnp.log(q) + p * jnp.log(p)) / p

        return apply("geometric_entropy", fn, self.probs)

    def cdf(self, value):
        def fn(p, v):
            return 1 - jnp.power(1 - p, v + 1)

        return apply("geometric_cdf", fn, self.probs, value)

    def kl_divergence(self, other):
        from .kl import kl_divergence

        return kl_divergence(self, other)


class Binomial(Distribution):
    """python/paddle/distribution/binomial.py parity (total_count, probs)."""

    def __init__(self, total_count, probs):
        self.total_count = jnp.asarray(_arr(total_count))
        self.probs = _param(probs)
        super().__init__(
            batch_shape=jnp.broadcast_shapes(jnp.shape(self.total_count),
                                             _shape_of(self.probs)))

    @property
    def mean(self):
        return apply("binomial_mean",
                     lambda n, p: n.astype(p.dtype) * p,
                     self.total_count, self.probs)

    @property
    def variance(self):
        return apply("binomial_variance",
                     lambda n, p: n.astype(p.dtype) * p * (1 - p),
                     self.total_count, self.probs)

    def sample(self, shape=()):
        out_shape = self._extend_shape(shape)
        key = _random.next_key()

        def fn(n, p):
            return jax.random.binomial(
                key, jnp.broadcast_to(n, out_shape).astype(p.dtype),
                jnp.broadcast_to(p, out_shape), dtype=p.dtype)

        with _tape.no_grad():
            out = apply("binomial_sample", fn, self.total_count, self.probs,
                        differentiable=False)
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        def fn(n, p, v):
            n = n.astype(p.dtype)
            eps = 1e-7
            pc = jnp.clip(p, eps, 1 - eps)
            return (jsp.gammaln(n + 1) - jsp.gammaln(v + 1)
                    - jsp.gammaln(n - v + 1)
                    + v * jnp.log(pc) + (n - v) * jnp.log1p(-pc))

        return apply("binomial_log_prob", fn, self.total_count, self.probs, value)

    def entropy(self):
        """Exact entropy by summing the pmf over the support (matches the
        reference, which enumerates 0..n; requires a scalar/uniform n)."""
        def fn(n, p):
            nmax = int(jnp.max(n))
            k = jnp.arange(nmax + 1, dtype=p.dtype)
            shape = jnp.broadcast_shapes(jnp.shape(n), jnp.shape(p))
            nb = jnp.broadcast_to(n, shape).astype(p.dtype)[..., None]
            pb = jnp.clip(jnp.broadcast_to(p, shape), 1e-7, 1 - 1e-7)[..., None]
            logpmf = (jsp.gammaln(nb + 1) - jsp.gammaln(k + 1)
                      - jsp.gammaln(nb - k + 1)
                      + k * jnp.log(pb) + (nb - k) * jnp.log1p(-pb))
            valid = k <= nb
            pmf = jnp.where(valid, jnp.exp(logpmf), 0.0)
            return -(pmf * jnp.where(valid, logpmf, 0.0)).sum(-1)

        return apply("binomial_entropy", fn, self.total_count, self.probs)

    def kl_divergence(self, other):
        from .kl import kl_divergence

        return kl_divergence(self, other)


class Multinomial(Distribution):
    """python/paddle/distribution/multinomial.py parity (total_count, probs)."""

    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs = _param(probs)
        pshape = _shape_of(self.probs)
        if len(pshape) < 1:
            raise ValueError("Multinomial probs must be at least 1-D")
        super().__init__(batch_shape=pshape[:-1], event_shape=pshape[-1:])

    @property
    def mean(self):
        return apply("multinomial_mean",
                     lambda p: self.total_count * (p / p.sum(-1, keepdims=True)),
                     self.probs)

    @property
    def variance(self):
        def fn(p):
            pn = p / p.sum(-1, keepdims=True)
            return self.total_count * pn * (1 - pn)

        return apply("multinomial_variance", fn, self.probs)

    def sample(self, shape=()):
        sample_shape = _shape_tuple(shape) + tuple(self.batch_shape)
        key = _random.next_key()
        n = self.total_count

        def fn(p):
            pn = p / p.sum(-1, keepdims=True)
            return jax.random.multinomial(
                key, n, pn, shape=sample_shape + tuple(self.event_shape),
            ).astype(p.dtype)

        with _tape.no_grad():
            out = apply("multinomial_sample", fn, self.probs,
                        differentiable=False)
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        def fn(p, v):
            pn = jnp.clip(p / p.sum(-1, keepdims=True), 1e-7, 1.0)
            return (jsp.gammaln(jnp.asarray(self.total_count + 1.0, p.dtype))
                    - jsp.gammaln(v + 1).sum(-1)
                    + (v * jnp.log(pn)).sum(-1))

        return apply("multinomial_log_prob", fn, self.probs, value)

    def entropy(self):
        """Reference computes entropy via the categorical decomposition
        bound; we match the exact formula for n=1 and use the standard
        approximation-free sum otherwise is intractable — follow the
        reference's implementation: n*H(p) - correction-free form."""
        def fn(p):
            pn = jnp.clip(p / p.sum(-1, keepdims=True), 1e-7, 1.0)
            return -self.total_count * (pn * jnp.log(pn)).sum(-1)

        return apply("multinomial_entropy", fn, self.probs)


class Poisson(ExponentialFamily):
    """python/paddle/distribution/poisson.py parity (rate)."""

    def __init__(self, rate):
        self.rate = _param(rate)
        super().__init__(batch_shape=_shape_of(self.rate))

    @property
    def mean(self):
        return apply("poisson_mean", lambda r: r + 0, self.rate)

    @property
    def variance(self):
        return apply("poisson_variance", lambda r: r + 0, self.rate)

    def sample(self, shape=()):
        out_shape = self._extend_shape(shape)
        key = _random.next_key()

        def fn(r):
            return jax.random.poisson(key, r, out_shape).astype(r.dtype)

        with _tape.no_grad():
            out = apply("poisson_sample", fn, self.rate, differentiable=False)
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        def fn(r, v):
            return v * jnp.log(r) - r - jsp.gammaln(v + 1)

        return apply("poisson_log_prob", fn, self.rate, value)

    def entropy(self):
        """Series entropy (reference enumerates a truncated support)."""
        def fn(r):
            kmax = int(jnp.maximum(20, jnp.max(r) * 3 + 20))
            k = jnp.arange(kmax, dtype=r.dtype)
            rb = r[..., None]
            logpmf = k * jnp.log(rb) - rb - jsp.gammaln(k + 1)
            pmf = jnp.exp(logpmf)
            return -(pmf * logpmf).sum(-1)

        return apply("poisson_entropy", fn, self.rate)

    def kl_divergence(self, other):
        from .kl import kl_divergence

        return kl_divergence(self, other)
