"""paddle.distribution parity package.

Reference: python/paddle/distribution/__init__.py — same ``__all__``
(26 distributions + kl_divergence/register_kl + the transform list).
TPU-native: jax.random sampling (implicit-reparameterization gradients for
gamma/beta/dirichlet/student-t), jax.scipy special-function math, op-registry
routing for eager tape recording.
"""
from .distribution import Distribution, ExponentialFamily
from .continuous import (
    Beta,
    Cauchy,
    Chi2,
    ContinuousBernoulli,
    Dirichlet,
    Exponential,
    Gamma,
    Gumbel,
    Laplace,
    LKJCholesky,
    LogNormal,
    Normal,
    StudentT,
    Uniform,
)
from .discrete import (
    Bernoulli,
    Binomial,
    Categorical,
    Geometric,
    Multinomial,
    Poisson,
)
from .multivariate_normal import MultivariateNormal
from .transformed_distribution import Independent, TransformedDistribution
from .kl import kl_divergence, register_kl
from .transform import (
    AbsTransform,
    AffineTransform,
    ChainTransform,
    ExpTransform,
    IndependentTransform,
    PowerTransform,
    ReshapeTransform,
    SigmoidTransform,
    SoftmaxTransform,
    StackTransform,
    StickBreakingTransform,
    TanhTransform,
    Transform,
)

__all__ = [
    "Bernoulli",
    "Beta",
    "Binomial",
    "Categorical",
    "Cauchy",
    "Chi2",
    "ContinuousBernoulli",
    "Dirichlet",
    "Distribution",
    "Exponential",
    "ExponentialFamily",
    "Gamma",
    "Geometric",
    "Gumbel",
    "Independent",
    "Laplace",
    "LKJCholesky",
    "LogNormal",
    "Multinomial",
    "MultivariateNormal",
    "Normal",
    "Poisson",
    "StudentT",
    "TransformedDistribution",
    "Uniform",
    "kl_divergence",
    "register_kl",
    "Transform",
    "AbsTransform",
    "AffineTransform",
    "ChainTransform",
    "ExpTransform",
    "IndependentTransform",
    "PowerTransform",
    "ReshapeTransform",
    "SigmoidTransform",
    "SoftmaxTransform",
    "StackTransform",
    "StickBreakingTransform",
    "TanhTransform",
]
