"""Continuous distributions.

Reference parity: python/paddle/distribution/{normal,uniform,beta,gamma,
chi2,dirichlet,exponential,laplace,lognormal,cauchy,gumbel,student_t,
continuous_bernoulli,lkj_cholesky}.py — same constructor signatures and the
sample/rsample/log_prob/entropy/mean/variance surface.

TPU-native: sampling uses jax.random (gamma/beta/dirichlet/t carry JAX's
implicit-reparameterization gradients, so ``rsample`` is differentiable for
those families too); math goes through the op-registry ``apply`` for tape
recording.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from ..ops.registry import apply
from ..framework import random as _random
from ..autograd import tape as _tape
from .distribution import (Distribution, ExponentialFamily, _arr, _param,
                           _shape_of, _shape_tuple)

_EULER = 0.5772156649015329  # Euler–Mascheroni


def _bshape(*arrs) -> tuple:
    return jnp.broadcast_shapes(*[_shape_of(a) for a in arrs])


class Normal(ExponentialFamily):
    """python/paddle/distribution/normal.py parity."""

    def __init__(self, loc, scale, name=None):
        self.loc = _param(loc)
        self.scale = _param(scale)
        super().__init__(batch_shape=_bshape(self.loc, self.scale))

    @property
    def mean(self):
        return apply("normal_mean", lambda l, s: jnp.broadcast_to(l, _bshape(l, s)),
                     self.loc, self.scale)

    @property
    def variance(self):
        return apply("normal_variance",
                     lambda l, s: jnp.broadcast_to(s * s, _bshape(l, s)),
                     self.loc, self.scale)

    def rsample(self, shape=()):
        out_shape = self._extend_shape(shape)
        key = _random.next_key()

        def fn(l, s):
            return l + s * jax.random.normal(key, out_shape, dtype=s.dtype)

        return apply("normal_rsample", fn, self.loc, self.scale)

    def log_prob(self, value):
        def fn(l, s, v):
            var = s * s
            return (-((v - l) ** 2) / (2 * var) - jnp.log(s)
                    - 0.5 * math.log(2 * math.pi))

        return apply("normal_log_prob", fn, self.loc, self.scale, value)

    def entropy(self):
        def fn(l, s):
            h = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s)
            return jnp.broadcast_to(h, _bshape(l, s))

        return apply("normal_entropy", fn, self.loc, self.scale)

    def cdf(self, value):
        def fn(l, s, v):
            return 0.5 * (1 + jsp.erf((v - l) / (s * math.sqrt(2.0))))

        return apply("normal_cdf", fn, self.loc, self.scale, value)

    def icdf(self, value):
        def fn(l, s, v):
            return l + s * math.sqrt(2.0) * jsp.erfinv(2 * v - 1)

        return apply("normal_icdf", fn, self.loc, self.scale, value)


class Uniform(Distribution):
    """python/paddle/distribution/uniform.py parity."""

    def __init__(self, low, high, name=None):
        self.low = _param(low)
        self.high = _param(high)
        super().__init__(batch_shape=_bshape(self.low, self.high))

    @property
    def mean(self):
        return apply("uniform_mean", lambda a, b: (a + b) / 2, self.low, self.high)

    @property
    def variance(self):
        return apply("uniform_variance", lambda a, b: (b - a) ** 2 / 12,
                     self.low, self.high)

    def rsample(self, shape=()):
        out_shape = self._extend_shape(shape)
        key = _random.next_key()

        def fn(a, b):
            u = jax.random.uniform(key, out_shape, dtype=a.dtype)
            return a + (b - a) * u

        return apply("uniform_rsample", fn, self.low, self.high)

    def log_prob(self, value):
        def fn(a, b, v):
            inside = (v >= a) & (v < b)
            return jnp.where(inside, -jnp.log(b - a), -jnp.inf)

        return apply("uniform_log_prob", fn, self.low, self.high, value)

    def entropy(self):
        return apply("uniform_entropy", lambda a, b: jnp.log(b - a),
                     self.low, self.high)


class Beta(ExponentialFamily):
    """python/paddle/distribution/beta.py parity."""

    def __init__(self, alpha, beta):
        self.alpha = _param(alpha)
        self.beta = _param(beta)
        super().__init__(batch_shape=_bshape(self.alpha, self.beta))

    @property
    def mean(self):
        return apply("beta_mean", lambda a, b: a / (a + b), self.alpha, self.beta)

    @property
    def variance(self):
        def fn(a, b):
            s = a + b
            return a * b / (s * s * (s + 1))

        return apply("beta_variance", fn, self.alpha, self.beta)

    def rsample(self, shape=()):
        out_shape = self._extend_shape(shape)
        key = _random.next_key()

        def fn(a, b):
            return jax.random.beta(key, a, b, out_shape, dtype=a.dtype)

        return apply("beta_rsample", fn, self.alpha, self.beta)

    sample = Distribution.sample

    def log_prob(self, value):
        def fn(a, b, v):
            return ((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
                    - (jsp.gammaln(a) + jsp.gammaln(b) - jsp.gammaln(a + b)))

        return apply("beta_log_prob", fn, self.alpha, self.beta, value)

    def entropy(self):
        def fn(a, b):
            s = a + b
            lbeta = jsp.gammaln(a) + jsp.gammaln(b) - jsp.gammaln(s)
            return (lbeta - (a - 1) * jsp.digamma(a) - (b - 1) * jsp.digamma(b)
                    + (s - 2) * jsp.digamma(s))

        return apply("beta_entropy", fn, self.alpha, self.beta)


class Gamma(ExponentialFamily):
    """python/paddle/distribution/gamma.py parity (concentration, rate)."""

    def __init__(self, concentration, rate):
        self.concentration = _param(concentration)
        self.rate = _param(rate)
        super().__init__(batch_shape=_bshape(self.concentration, self.rate))

    @property
    def mean(self):
        return apply("gamma_mean", lambda c, r: c / r, self.concentration, self.rate)

    @property
    def variance(self):
        return apply("gamma_variance", lambda c, r: c / (r * r),
                     self.concentration, self.rate)

    def rsample(self, shape=()):
        out_shape = self._extend_shape(shape)
        key = _random.next_key()

        def fn(c, r):
            return jax.random.gamma(key, c, out_shape, dtype=c.dtype) / r

        return apply("gamma_rsample", fn, self.concentration, self.rate)

    def log_prob(self, value):
        def fn(c, r, v):
            return (c * jnp.log(r) + (c - 1) * jnp.log(v) - r * v
                    - jsp.gammaln(c))

        return apply("gamma_log_prob", fn, self.concentration, self.rate, value)

    def entropy(self):
        def fn(c, r):
            return (c - jnp.log(r) + jsp.gammaln(c)
                    + (1 - c) * jsp.digamma(c))

        return apply("gamma_entropy", fn, self.concentration, self.rate)


class Chi2(Gamma):
    """python/paddle/distribution/chi2.py parity: Gamma(df/2, 1/2)."""

    def __init__(self, df):
        self.df = _param(df)
        half = jnp.asarray(0.5, _arr(self.df).dtype)
        super().__init__(self.df / 2, half)


class Dirichlet(ExponentialFamily):
    """python/paddle/distribution/dirichlet.py parity."""

    def __init__(self, concentration):
        self.concentration = _param(concentration)
        cshape = _shape_of(self.concentration)
        if len(cshape) < 1:
            raise ValueError("Dirichlet concentration must be at least 1-D")
        super().__init__(batch_shape=cshape[:-1], event_shape=cshape[-1:])

    @property
    def mean(self):
        return apply("dirichlet_mean",
                     lambda c: c / c.sum(-1, keepdims=True), self.concentration)

    @property
    def variance(self):
        def fn(c):
            s = c.sum(-1, keepdims=True)
            m = c / s
            return m * (1 - m) / (s + 1)

        return apply("dirichlet_variance", fn, self.concentration)

    def rsample(self, shape=()):
        key = _random.next_key()
        sample_shape = _shape_tuple(shape) + tuple(self.batch_shape)

        def fn(c):
            return jax.random.dirichlet(key, c, sample_shape, dtype=c.dtype)

        return apply("dirichlet_rsample", fn, self.concentration)

    def log_prob(self, value):
        def fn(c, v):
            return ((jnp.log(v) * (c - 1)).sum(-1)
                    + jsp.gammaln(c.sum(-1)) - jsp.gammaln(c).sum(-1))

        return apply("dirichlet_log_prob", fn, self.concentration, value)

    def entropy(self):
        def fn(c):
            a0 = c.sum(-1)
            k = c.shape[-1]
            lnB = jsp.gammaln(c).sum(-1) - jsp.gammaln(a0)
            return (lnB + (a0 - k) * jsp.digamma(a0)
                    - ((c - 1) * jsp.digamma(c)).sum(-1))

        return apply("dirichlet_entropy", fn, self.concentration)


class Exponential(ExponentialFamily):
    """python/paddle/distribution/exponential.py parity (rate)."""

    def __init__(self, rate):
        self.rate = _param(rate)
        super().__init__(batch_shape=_shape_of(self.rate))

    @property
    def mean(self):
        return apply("exponential_mean", lambda r: 1 / r, self.rate)

    @property
    def variance(self):
        return apply("exponential_variance", lambda r: 1 / (r * r), self.rate)

    def rsample(self, shape=()):
        out_shape = self._extend_shape(shape)
        key = _random.next_key()

        def fn(r):
            return jax.random.exponential(key, out_shape, dtype=r.dtype) / r

        return apply("exponential_rsample", fn, self.rate)

    def log_prob(self, value):
        def fn(r, v):
            return jnp.where(v >= 0, jnp.log(r) - r * v, -jnp.inf)

        return apply("exponential_log_prob", fn, self.rate, value)

    def entropy(self):
        return apply("exponential_entropy", lambda r: 1 - jnp.log(r), self.rate)


class Laplace(Distribution):
    """python/paddle/distribution/laplace.py parity."""

    def __init__(self, loc, scale):
        self.loc = _param(loc)
        self.scale = _param(scale)
        super().__init__(batch_shape=_bshape(self.loc, self.scale))

    @property
    def mean(self):
        return apply("laplace_mean", lambda l, s: jnp.broadcast_to(l, _bshape(l, s)),
                     self.loc, self.scale)

    @property
    def variance(self):
        return apply("laplace_variance",
                     lambda l, s: jnp.broadcast_to(2 * s * s, _bshape(l, s)),
                     self.loc, self.scale)

    def rsample(self, shape=()):
        out_shape = self._extend_shape(shape)
        key = _random.next_key()

        def fn(l, s):
            return l + s * jax.random.laplace(key, out_shape, dtype=s.dtype)

        return apply("laplace_rsample", fn, self.loc, self.scale)

    def log_prob(self, value):
        def fn(l, s, v):
            return -jnp.abs(v - l) / s - jnp.log(2 * s)

        return apply("laplace_log_prob", fn, self.loc, self.scale, value)

    def entropy(self):
        def fn(l, s):
            return jnp.broadcast_to(1 + jnp.log(2 * s), _bshape(l, s))

        return apply("laplace_entropy", fn, self.loc, self.scale)

    def cdf(self, value):
        def fn(l, s, v):
            z = (v - l) / s
            return 0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z))

        return apply("laplace_cdf", fn, self.loc, self.scale, value)

    def icdf(self, value):
        def fn(l, s, v):
            term = v - 0.5
            return l - s * jnp.sign(term) * jnp.log1p(-2 * jnp.abs(term))

        return apply("laplace_icdf", fn, self.loc, self.scale, value)


class LogNormal(Distribution):
    """python/paddle/distribution/lognormal.py parity: exp(Normal(loc, scale))."""

    def __init__(self, loc, scale):
        self.loc = _param(loc)
        self.scale = _param(scale)
        self._base = Normal(self.loc, self.scale)
        super().__init__(batch_shape=_bshape(self.loc, self.scale))

    @property
    def mean(self):
        return apply("lognormal_mean",
                     lambda l, s: jnp.exp(l + s * s / 2), self.loc, self.scale)

    @property
    def variance(self):
        def fn(l, s):
            s2 = s * s
            return jnp.expm1(s2) * jnp.exp(2 * l + s2)

        return apply("lognormal_variance", fn, self.loc, self.scale)

    def rsample(self, shape=()):
        base = self._base.rsample(shape)
        return apply("lognormal_exp", jnp.exp, base)

    def log_prob(self, value):
        def fn(l, s, v):
            logv = jnp.log(v)
            return (-((logv - l) ** 2) / (2 * s * s) - jnp.log(s)
                    - logv - 0.5 * math.log(2 * math.pi))

        return apply("lognormal_log_prob", fn, self.loc, self.scale, value)

    def entropy(self):
        def fn(l, s):
            return l + 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s)

        return apply("lognormal_entropy", fn, self.loc, self.scale)


class Cauchy(Distribution):
    """python/paddle/distribution/cauchy.py parity."""

    def __init__(self, loc, scale):
        self.loc = _param(loc)
        self.scale = _param(scale)
        super().__init__(batch_shape=_bshape(self.loc, self.scale))

    @property
    def mean(self):
        raise ValueError("Cauchy distribution has no mean")

    @property
    def variance(self):
        raise ValueError("Cauchy distribution has no variance")

    @property
    def stddev(self):
        raise ValueError("Cauchy distribution has no stddev")

    def rsample(self, shape=()):
        out_shape = self._extend_shape(shape)
        key = _random.next_key()

        def fn(l, s):
            return l + s * jax.random.cauchy(key, out_shape, dtype=s.dtype)

        return apply("cauchy_rsample", fn, self.loc, self.scale)

    def log_prob(self, value):
        def fn(l, s, v):
            z = (v - l) / s
            return -jnp.log(math.pi * s * (1 + z * z))

        return apply("cauchy_log_prob", fn, self.loc, self.scale, value)

    def entropy(self):
        def fn(l, s):
            return jnp.broadcast_to(jnp.log(4 * math.pi * s), _bshape(l, s))

        return apply("cauchy_entropy", fn, self.loc, self.scale)

    def cdf(self, value):
        def fn(l, s, v):
            return jnp.arctan((v - l) / s) / math.pi + 0.5

        return apply("cauchy_cdf", fn, self.loc, self.scale, value)


class Gumbel(Distribution):
    """python/paddle/distribution/gumbel.py parity."""

    def __init__(self, loc, scale):
        self.loc = _param(loc)
        self.scale = _param(scale)
        super().__init__(batch_shape=_bshape(self.loc, self.scale))

    @property
    def mean(self):
        return apply("gumbel_mean", lambda l, s: l + s * _EULER,
                     self.loc, self.scale)

    @property
    def variance(self):
        return apply("gumbel_variance",
                     lambda l, s: jnp.broadcast_to(
                         (math.pi ** 2 / 6) * s * s, _bshape(l, s)),
                     self.loc, self.scale)

    def rsample(self, shape=()):
        out_shape = self._extend_shape(shape)
        key = _random.next_key()

        def fn(l, s):
            return l + s * jax.random.gumbel(key, out_shape, dtype=s.dtype)

        return apply("gumbel_rsample", fn, self.loc, self.scale)

    def log_prob(self, value):
        def fn(l, s, v):
            z = (v - l) / s
            return -(z + jnp.exp(-z)) - jnp.log(s)

        return apply("gumbel_log_prob", fn, self.loc, self.scale, value)

    def entropy(self):
        def fn(l, s):
            return jnp.broadcast_to(jnp.log(s) + 1 + _EULER, _bshape(l, s))

        return apply("gumbel_entropy", fn, self.loc, self.scale)


class StudentT(Distribution):
    """python/paddle/distribution/student_t.py parity (df, loc, scale)."""

    def __init__(self, df, loc, scale, name=None):
        self.df = _param(df)
        self.loc = _param(loc)
        self.scale = _param(scale)
        super().__init__(batch_shape=_bshape(self.df, self.loc, self.scale))

    @property
    def mean(self):
        def fn(df, l, s):
            return jnp.where(df > 1, jnp.broadcast_to(l, _bshape(df, l, s)),
                             jnp.nan)

        return apply("studentt_mean", fn, self.df, self.loc, self.scale)

    @property
    def variance(self):
        def fn(df, l, s):
            shape = _bshape(df, l, s)
            var = jnp.where(df > 2, s * s * df / (df - 2), jnp.inf)
            return jnp.broadcast_to(jnp.where(df > 1, var, jnp.nan), shape)

        return apply("studentt_variance", fn, self.df, self.loc, self.scale)

    def rsample(self, shape=()):
        out_shape = self._extend_shape(shape)
        key = _random.next_key()

        def fn(df, l, s):
            return l + s * jax.random.t(key, df, out_shape, dtype=s.dtype)

        return apply("studentt_rsample", fn, self.df, self.loc, self.scale)

    def log_prob(self, value):
        def fn(df, l, s, v):
            z = (v - l) / s
            return (jsp.gammaln((df + 1) / 2) - jsp.gammaln(df / 2)
                    - 0.5 * jnp.log(df * math.pi) - jnp.log(s)
                    - (df + 1) / 2 * jnp.log1p(z * z / df))

        return apply("studentt_log_prob", fn, self.df, self.loc, self.scale, value)

    def entropy(self):
        def fn(df, l, s):
            h = ((df + 1) / 2 * (jsp.digamma((df + 1) / 2) - jsp.digamma(df / 2))
                 + 0.5 * jnp.log(df) + jsp.gammaln(df / 2)
                 + jsp.gammaln(0.5) - jsp.gammaln((df + 1) / 2) + jnp.log(s))
            return jnp.broadcast_to(h, _bshape(df, l, s))

        return apply("studentt_entropy", fn, self.df, self.loc, self.scale)


class ContinuousBernoulli(Distribution):
    """python/paddle/distribution/continuous_bernoulli.py parity."""

    def __init__(self, probs, lims=(0.499, 0.501)):
        self.probs = _param(probs)
        self._lims = lims
        super().__init__(batch_shape=_shape_of(self.probs))

    def _clipped(self, p):
        eps = jnp.finfo(p.dtype).eps
        return jnp.clip(p, eps, 1 - eps)

    def _outside_unstable(self, p):
        lo, hi = self._lims
        return (p < lo) | (p > hi)

    def _log_norm_const(self, p):
        """log C(p); C = 2 atanh(1-2p)/(1-2p) for p != 1/2, else 2."""
        p = self._clipped(p)
        safe = jnp.where(self._outside_unstable(p), p, 0.25)
        c = 2 * jnp.arctanh(1 - 2 * safe) / (1 - 2 * safe)
        # Taylor expansion around p = 1/2: C ≈ 2 + (1-2p)^2 * 4/3
        t = 1 - 2 * p
        taylor = 2.0 + (4.0 / 3.0) * t * t
        return jnp.log(jnp.where(self._outside_unstable(p), c, taylor))

    @property
    def mean(self):
        def fn(p):
            p = self._clipped(p)
            safe = jnp.where(self._outside_unstable(p), p, 0.25)
            m = safe / (2 * safe - 1) + 1 / (2 * jnp.arctanh(1 - 2 * safe))
            # Taylor around 1/2: mean ≈ 1/2 + (p-1/2)/3
            taylor = 0.5 + (p - 0.5) / 3.0
            return jnp.where(self._outside_unstable(p), m, taylor)

        return apply("cb_mean", fn, self.probs)

    @property
    def variance(self):
        def fn(p):
            p = self._clipped(p)
            safe = jnp.where(self._outside_unstable(p), p, 0.25)
            t = 1 - 2 * safe
            v = safe * (safe - 1) / (t * t) + 1 / (2 * jnp.arctanh(t)) ** 2
            taylor = 1.0 / 12.0 - (p - 0.5) ** 2 * (2.0 / 15.0)
            return jnp.where(self._outside_unstable(p), v, taylor)

        return apply("cb_variance", fn, self.probs)

    def rsample(self, shape=()):
        out_shape = self._extend_shape(shape)
        key = _random.next_key()

        def fn(p):
            u = jax.random.uniform(key, out_shape, dtype=p.dtype)
            return self._icdf_arr(p, u)

        return apply("cb_rsample", fn, self.probs)

    def _icdf_arr(self, p, u):
        # F⁻¹(u) = log1p(u(2p-1)/(1-p)) / log(p/(1-p)) for p != 1/2; u at 1/2
        p = self._clipped(p)
        safe = jnp.where(self._outside_unstable(p), p, 0.25)
        num = jnp.log1p(u * (2 * safe - 1) / (1 - safe))
        den = jnp.log(safe) - jnp.log1p(-safe)
        x = num / den
        return jnp.where(self._outside_unstable(p), x, u)

    def log_prob(self, value):
        def fn(p, v):
            pc = self._clipped(p)
            return (v * jnp.log(pc) + (1 - v) * jnp.log1p(-pc)
                    + self._log_norm_const(p))

        return apply("cb_log_prob", fn, self.probs, value)

    def entropy(self):
        # mean recomputed inline so the op stays pure under jit
        def fn_pure(p):
            pc = self._clipped(p)
            safe = jnp.where(self._outside_unstable(pc), pc, 0.25)
            mu = safe / (2 * safe - 1) + 1 / (2 * jnp.arctanh(1 - 2 * safe))
            mu = jnp.where(self._outside_unstable(pc), mu,
                           0.5 + (pc - 0.5) / 3.0)
            return -(mu * jnp.log(pc) + (1 - mu) * jnp.log1p(-pc)
                     + self._log_norm_const(p))

        return apply("cb_entropy", fn_pure, self.probs)


class LKJCholesky(Distribution):
    """python/paddle/distribution/lkj_cholesky.py parity: distribution over
    Cholesky factors of correlation matrices (onion-method sampling)."""

    def __init__(self, dim, concentration=1.0, sample_method="onion"):
        if dim < 2:
            raise ValueError("LKJCholesky dim must be >= 2")
        if sample_method not in ("onion", "cvine"):
            raise ValueError(f"unknown sample_method {sample_method}")
        self.dim = int(dim)
        self.concentration = _param(concentration)
        self.sample_method = sample_method
        super().__init__(batch_shape=_shape_of(self.concentration),
                         event_shape=(self.dim, self.dim))

    def sample(self, shape=()):
        """Onion method (the cvine request also uses it — same law)."""
        d = self.dim
        sample_shape = _shape_tuple(shape) + tuple(self.batch_shape)
        key = _random.next_key()
        k1, k2 = jax.random.split(key)

        def fn(eta):
            # per-row beta draws: row i (1-based, i>=1) uses
            # Beta(i/2, eta + (d - 1 - i)/2)
            i = jnp.arange(1, d, dtype=eta.dtype)
            conc1 = i / 2
            conc0 = eta[..., None] + (d - 1 - i) / 2
            y = jax.random.beta(
                k1, jnp.broadcast_to(conc1, sample_shape + (d - 1,)),
                jnp.broadcast_to(conc0, sample_shape + (d - 1,)),
            )  # squared norms of each below-diagonal row
            # directions: rows of standard normals, normalized over the
            # first (i) coordinates via masking
            z = jax.random.normal(k2, sample_shape + (d - 1, d - 1),
                                  dtype=eta.dtype)
            mask = (jnp.arange(d - 1)[None, :]
                    <= jnp.arange(d - 1)[:, None]).astype(eta.dtype)
            zm = z * mask
            norm = jnp.sqrt((zm * zm).sum(-1, keepdims=True))
            u = zm / jnp.maximum(norm, jnp.finfo(eta.dtype).tiny)
            w = jnp.sqrt(y)[..., None] * u  # below-diagonal rows
            diag = jnp.sqrt(jnp.clip(1 - y, 0))  # row diagonals
            L = jnp.zeros(sample_shape + (d, d), eta.dtype)
            L = L.at[..., 0, 0].set(1.0)
            L = L.at[..., 1:, :-1].set(w)
            L = L.at[..., jnp.arange(1, d), jnp.arange(1, d)].set(diag)
            return L

        with _tape.no_grad():
            out = apply("lkj_sample", fn, self.concentration,
                        differentiable=False)
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        d = self.dim

        def fn(eta, L):
            # p(L) ∝ Π_{i=2..d} L_ii^{2(η-1) + d - i}; normalization via the
            # multivariate log-gamma (LKJ 2009, Theorem/p.1999 form, as in
            # the reference's lkj_cholesky.py)
            diag = jnp.diagonal(L, axis1=-2, axis2=-1)[..., 1:]
            order = jnp.arange(2, d + 1, dtype=eta.dtype)
            exponents = 2 * (eta[..., None] - 1) + d - order
            unnorm = (exponents * jnp.log(diag)).sum(-1)
            dm1 = d - 1
            alpha = eta + 0.5 * dm1
            denominator = jsp.gammaln(alpha) * dm1
            numerator = jsp.multigammaln(alpha - 0.5, dm1)
            pi_constant = 0.5 * dm1 * math.log(math.pi)
            return unnorm - (pi_constant + numerator - denominator)

        return apply("lkj_log_prob", fn, self.concentration, value)
