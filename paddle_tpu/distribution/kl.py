"""KL divergence registry.

Reference parity: python/paddle/distribution/kl.py — ``kl_divergence(p, q)``
dispatches on the most-derived registered (type(p), type(q)) pair;
``register_kl`` is the user-extension decorator.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
from jax.scipy import special as jsp

from ..ops.registry import apply
from .distribution import Distribution

_KL_REGISTRY = {}


def register_kl(cls_p, cls_q):
    """Decorator: register a pairwise KL implementation."""

    def deco(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn

    return deco


def _dispatch(type_p, type_q):
    matches = [
        (p, q) for (p, q) in _KL_REGISTRY
        if issubclass(type_p, p) and issubclass(type_q, q)
    ]
    if not matches:
        raise NotImplementedError(
            f"no KL(p || q) registered for ({type_p.__name__}, "
            f"{type_q.__name__})")

    # most-derived match (paddle kl.py uses total ordering by specificity)
    def key(pair):
        p, q = pair
        return (sum(issubclass(p2, p) for (p2, _) in matches),
                sum(issubclass(q2, q) for (_, q2) in matches))

    return _KL_REGISTRY[min(matches, key=key)]


def kl_divergence(p: Distribution, q: Distribution):
    return _dispatch(type(p), type(q))(p, q)


# ---- registered pairs --------------------------------------------------------

from .continuous import (  # noqa: E402
    Beta, Cauchy, Dirichlet, Exponential, Gamma, Gumbel, Laplace, LogNormal,
    Normal, Uniform)
from .discrete import Bernoulli, Categorical, Geometric, Poisson  # noqa: E402
from .multivariate_normal import MultivariateNormal  # noqa: E402
from .transformed_distribution import Independent  # noqa: E402


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    def fn(l1, s1, l2, s2):
        var_ratio = (s1 / s2) ** 2
        t1 = ((l1 - l2) / s2) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))

    return apply("kl_normal", fn, p.loc, p.scale, q.loc, q.scale)


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    def fn(a1, b1, a2, b2):
        res = jnp.log((b2 - a2) / (b1 - a1))
        return jnp.where((a2 <= a1) & (b1 <= b2), res, jnp.inf)

    return apply("kl_uniform", fn, p.low, p.high, q.low, q.high)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    def fn(p1, p2):
        eps = 1e-7
        a = jnp.clip(p1, eps, 1 - eps)
        b = jnp.clip(p2, eps, 1 - eps)
        return (a * (jnp.log(a) - jnp.log(b))
                + (1 - a) * (jnp.log1p(-a) - jnp.log1p(-b)))

    return apply("kl_bernoulli", fn, p.probs, q.probs)


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    def fn(lg1, lg2):
        lp = jnp.log(jnp.clip(jnp.exp(lg1 - jsp.logsumexp(lg1, -1, keepdims=True)), 1e-30))
        lq = lg2 - jsp.logsumexp(lg2, -1, keepdims=True)
        pr = jnp.exp(lp)
        return (pr * (lp - lq)).sum(-1)

    return apply("kl_categorical", fn, p.logits, q.logits)


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    def fn(a1, b1, a2, b2):
        s1 = a1 + b1
        lbeta1 = jsp.gammaln(a1) + jsp.gammaln(b1) - jsp.gammaln(s1)
        lbeta2 = jsp.gammaln(a2) + jsp.gammaln(b2) - jsp.gammaln(a2 + b2)
        return (lbeta2 - lbeta1
                + (a1 - a2) * jsp.digamma(a1)
                + (b1 - b2) * jsp.digamma(b1)
                + (a2 - a1 + b2 - b1) * jsp.digamma(s1))

    return apply("kl_beta", fn, p.alpha, p.beta, q.alpha, q.beta)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    def fn(c1, c2):
        s1 = c1.sum(-1)
        return (jsp.gammaln(s1) - jsp.gammaln(c2.sum(-1))
                - (jsp.gammaln(c1) - jsp.gammaln(c2)).sum(-1)
                + ((c1 - c2) * (jsp.digamma(c1)
                                - jsp.digamma(s1)[..., None])).sum(-1))

    return apply("kl_dirichlet", fn, p.concentration, q.concentration)


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    def fn(c1, r1, c2, r2):
        return ((c1 - c2) * jsp.digamma(c1)
                - jsp.gammaln(c1) + jsp.gammaln(c2)
                + c2 * (jnp.log(r1) - jnp.log(r2))
                + c1 * (r2 / r1 - 1))

    return apply("kl_gamma", fn, p.concentration, p.rate,
                 q.concentration, q.rate)


@register_kl(Exponential, Exponential)
def _kl_exponential_exponential(p, q):
    def fn(r1, r2):
        ratio = r2 / r1
        return ratio - 1 - jnp.log(ratio)

    return apply("kl_exponential", fn, p.rate, q.rate)


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    def fn(l1, s1, l2, s2):
        # log(s2/s1) + |l1-l2|/s2 + (s1/s2) e^{-|l1-l2|/s1} - 1
        diff = jnp.abs(l1 - l2)
        return (jnp.log(s2) - jnp.log(s1) + diff / s2
                + (s1 / s2) * jnp.exp(-diff / s1) - 1)

    return apply("kl_laplace", fn, p.loc, p.scale, q.loc, q.scale)


@register_kl(Geometric, Geometric)
def _kl_geometric_geometric(p, q):
    def fn(p1, p2):
        # Σ_k p1(1-p1)^k [log(p1/p2) + k log((1-p1)/(1-p2))]
        return (jnp.log(p1) - jnp.log(p2)
                + (1 - p1) / p1 * (jnp.log1p(-p1) - jnp.log1p(-p2)))

    return apply("kl_geometric", fn, p.probs, q.probs)


@register_kl(Poisson, Poisson)
def _kl_poisson_poisson(p, q):
    def fn(r1, r2):
        return r1 * (jnp.log(r1) - jnp.log(r2)) - r1 + r2

    return apply("kl_poisson", fn, p.rate, q.rate)


@register_kl(LogNormal, LogNormal)
def _kl_lognormal_lognormal(p, q):
    return _kl_normal_normal(p._base, q._base)


@register_kl(Gumbel, Gumbel)
def _kl_gumbel_gumbel(p, q):
    _EULER = 0.5772156649015329

    def fn(l1, s1, l2, s2):
        # log(s2/s1) + γ(s1/s2 - 1) + (l1-l2)/s2
        #   + e^{(l2-l1)/s2} Γ(1 + s1/s2) - 1
        ratio = s1 / s2
        return (jnp.log(s2) - jnp.log(s1) + _EULER * (ratio - 1)
                + (l1 - l2) / s2
                + jnp.exp((l2 - l1) / s2 + jsp.gammaln(1 + ratio)) - 1)

    return apply("kl_gumbel", fn, p.loc, p.scale, q.loc, q.scale)


@register_kl(Cauchy, Cauchy)
def _kl_cauchy_cauchy(p, q):
    def fn(l1, s1, l2, s2):
        return (jnp.log(((s1 + s2) ** 2 + (l1 - l2) ** 2)
                        / (4 * s1 * s2)))

    return apply("kl_cauchy", fn, p.loc, p.scale, q.loc, q.scale)


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn_mvn(p, q):
    import jax

    def fn(l1, st1, l2, st2):
        d = l1.shape[-1]
        half_logdet1 = jnp.log(jnp.diagonal(st1, axis1=-2, axis2=-1)).sum(-1)
        half_logdet2 = jnp.log(jnp.diagonal(st2, axis1=-2, axis2=-1)).sum(-1)
        # tr(Σ2⁻¹ Σ1) = ||L2⁻¹ L1||_F²
        m = jax.scipy.linalg.solve_triangular(st2, st1, lower=True)
        tr = (m * m).sum((-2, -1))
        diff = l2 - l1
        y = jax.scipy.linalg.solve_triangular(st2, diff[..., None],
                                              lower=True)[..., 0]
        maha = (y * y).sum(-1)
        return 0.5 * (tr + maha - d) + half_logdet2 - half_logdet1

    return apply("kl_mvn", fn, p.loc, p.scale_tril, q.loc, q.scale_tril)


@register_kl(Independent, Independent)
def _kl_independent_independent(p, q):
    if p.reinterpreted_batch_rank != q.reinterpreted_batch_rank:
        raise NotImplementedError("mismatched reinterpreted_batch_rank")
    from .transform import _sum_event

    inner = kl_divergence(p.base, q.base)
    return apply("kl_independent",
                 lambda a: _sum_event(a, p.reinterpreted_batch_rank), inner)
