"""Distribution base class.

Reference parity: python/paddle/distribution/distribution.py (class
``Distribution``: batch_shape/event_shape, sample/rsample/prob/log_prob/
entropy/kl_divergence surface) and exponential_family.py.

TPU-native design: parameters live as jax arrays; every differentiable
method (rsample, log_prob, entropy, mean, variance) routes through the op
registry's ``apply`` so eager calls are tape-recorded and jit-traced calls
stay pure. Sampling draws keys from the framework RNG
(paddle_tpu.framework.random), so ``paddle.seed`` governs reproducibility
exactly as for the rest of the framework.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor_class import Tensor, unwrap, wrap
from ..ops.registry import apply
from ..framework import random as _random
from ..autograd import tape as _tape


def _to_arr(x, dtype=None):
    """Normalize a parameter (Tensor | array | python scalar) to jnp array."""
    a = unwrap(x) if isinstance(x, Tensor) else jnp.asarray(x)
    if dtype is not None:
        a = a.astype(dtype)
    elif not jnp.issubdtype(a.dtype, jnp.floating):
        a = a.astype(jnp.float32)
    return a


def _param(x):
    """Keep a parameter AS a Tensor when one is given (so rsample/log_prob
    stay differentiable wrt it on the eager tape via ``apply``); normalize
    scalars/arrays to float jnp arrays otherwise."""
    if isinstance(x, Tensor):
        return x
    a = jnp.asarray(x)
    if not jnp.issubdtype(a.dtype, jnp.floating):
        a = a.astype(jnp.float32)
    return a


def _shape_of(x) -> tuple:
    return tuple(x.shape) if isinstance(x, Tensor) else tuple(jnp.shape(x))


def _arr(x):
    return x._array if isinstance(x, Tensor) else x


def _shape_tuple(shape) -> tuple:
    if shape is None:
        return ()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


class Distribution:
    """Abstract base (python/paddle/distribution/distribution.py:40)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = _shape_tuple(batch_shape)
        self._event_shape = _shape_tuple(event_shape)

    @property
    def batch_shape(self) -> Sequence[int]:
        return self._batch_shape

    @property
    def event_shape(self) -> Sequence[int]:
        return self._event_shape

    # ---- extension points ----------------------------------------------------
    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    @property
    def stddev(self):
        return apply(f"{type(self).__name__.lower()}_stddev",
                     jnp.sqrt, self.variance)

    def sample(self, shape=()):
        """Non-differentiable draw (paddle semantics: detached)."""
        with _tape.no_grad():
            out = self.rsample(shape)
        out.stop_gradient = True
        return out

    def rsample(self, shape=()):
        raise NotImplementedError(
            f"{type(self).__name__} does not implement rsample (not "
            "reparameterizable)")

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return apply(f"{type(self).__name__.lower()}_prob",
                     jnp.exp, self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other) -> Tensor:
        from .kl import kl_divergence

        return kl_divergence(self, other)

    # ---- helpers -------------------------------------------------------------
    def _extend_shape(self, sample_shape) -> tuple:
        """sample_shape + batch_shape + event_shape (distribution.py parity)."""
        return (_shape_tuple(sample_shape) + tuple(self._batch_shape)
                + tuple(self._event_shape))

    def __repr__(self):
        return (f"{type(self).__name__}(batch_shape={self._batch_shape}, "
                f"event_shape={self._event_shape})")


class ExponentialFamily(Distribution):
    """Base for exponential-family distributions
    (python/paddle/distribution/exponential_family.py). Subclasses expose
    natural parameters + log normalizer; the Bregman-divergence entropy
    shortcut is inherited where defined."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError
