"""Keras-style Model.

Reference parity: python/paddle/hapi/model.py (Model :~900, fit :1472,
evaluate :2200, predict, train_batch/eval_batch/predict_batch, save/load,
prepare). The TPU build's Model drives the eager layer system; the step
itself stays jittable through the layer forward (users wanting a compiled
step use paddle.jit.to_static or distributed.engine.parallelize).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..metric import Metric
from ..tensor_class import Tensor
from .callbacks import config_callbacks


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _to_tensor(x):
    import paddle_tpu as paddle

    if isinstance(x, Tensor):
        return x
    return paddle.to_tensor(np.asarray(x))


class Model:
    """paddle.Model(network, inputs=None, labels=None)."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self.stop_training = False

    # -- setup ---------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        for m in _to_list(metrics):
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m!r} is not a paddle.metric.Metric")
        self._metrics = _to_list(metrics)

    # -- single-batch entries ------------------------------------------------
    def _compute_loss(self, outputs, labels):
        outs = _to_list(outputs)
        lbls = _to_list(labels)
        if self._loss is None:
            raise RuntimeError("Model.prepare(loss=...) was not called")
        return self._loss(*outs, *lbls)

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        ins = [_to_tensor(i) for i in _to_list(inputs)]
        lbl = [_to_tensor(l) for l in _to_list(labels)]
        outputs = self.network(*ins)
        loss = self._compute_loss(outputs, lbl)
        loss.backward()
        if update and self._optimizer is not None:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = []
        for m in self._metrics:
            m.update(*m.compute(*_to_list(outputs), *lbl))
            metrics.append(m.accumulate())
        out = [float(loss.numpy())]
        return (out, metrics) if metrics else out

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        ins = [_to_tensor(i) for i in _to_list(inputs)]
        lbl = [_to_tensor(l) for l in _to_list(labels)]
        outputs = self.network(*ins)
        loss = self._compute_loss(outputs, lbl)
        metrics = []
        for m in self._metrics:
            m.update(*m.compute(*_to_list(outputs), *lbl))
            metrics.append(m.accumulate())
        out = [float(loss.numpy())]
        return (out, metrics) if metrics else out

    def predict_batch(self, inputs):
        self.network.eval()
        ins = [_to_tensor(i) for i in _to_list(inputs)]
        out = self.network(*ins)
        return [o.numpy() for o in _to_list(out)]

    # -- loops ---------------------------------------------------------------
    def _run_one_epoch(self, loader, cbs, mode, logs):
        step = 0
        for batch in loader:
            batch = list(batch) if isinstance(batch, (list, tuple)) else [batch]
            n_in = max(1, len(batch) - 1)
            ins, lbl = batch[:n_in], batch[n_in:]
            if mode == "train":
                cbs.on_train_batch_begin(step)
                res = self.train_batch(ins, lbl)
            else:
                cbs.on_eval_batch_begin(step)
                res = self.eval_batch(ins, lbl)
            if isinstance(res, tuple):
                losses, metrics = res
            else:
                losses, metrics = res, []
            logs["loss"] = losses[0]
            for m, v in zip(self._metrics, metrics):
                names = m.name()
                if isinstance(names, list):
                    for n, x in zip(names, v):
                        logs[n] = x
                else:
                    logs[names] = v
            batch_size = getattr(ins[0], "shape", [1])[0]
            logs["batch_size"] = batch_size
            if mode == "train":
                cbs.on_train_batch_end(step, logs)
            else:
                cbs.on_eval_batch_end(step, logs)
            step += 1
            if self.stop_training:
                break
        return logs

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        train_loader = self._make_loader(train_data, batch_size, shuffle,
                                         drop_last, num_workers)
        eval_loader = (self._make_loader(eval_data, batch_size, False, False,
                                         num_workers)
                       if eval_data is not None else None)
        steps = len(train_loader) if hasattr(train_loader, "__len__") else None
        cbs = config_callbacks(callbacks, model=self, epochs=epochs,
                               steps=steps, verbose=verbose,
                               save_freq=save_freq, save_dir=save_dir,
                               metrics=[m.name() for m in self._metrics])
        self.stop_training = False
        cbs.on_train_begin()
        history = []
        # crash boundary: a training crash (OOM mid-step, a raising
        # callback, SIGTERM handled elsewhere) writes an incident bundle
        # when a reporter is active — same forensics as the serving stack
        from ..observability import flightrecorder as _frec

        with _frec.incident_scope("hapi.fit"):
            for epoch in range(epochs):
                for m in self._metrics:
                    m.reset()
                cbs.on_epoch_begin(epoch)
                logs = self._run_one_epoch(train_loader, cbs, "train", {})
                cbs.on_epoch_end(epoch, logs)
                if eval_loader is not None and epoch % eval_freq == 0:
                    eval_logs = self.evaluate_with_callbacks(eval_loader,
                                                            cbs)
                    logs.update({f"eval_{k}": v
                                 for k, v in eval_logs.items()})
                history.append(dict(logs))
                if self.stop_training:
                    break
        cbs.on_train_end(logs if history else None)
        return history

    def evaluate_with_callbacks(self, loader, cbs):
        for m in self._metrics:
            m.reset()
        cbs.on_eval_begin()
        logs = self._run_one_epoch(loader, cbs, "eval", {})
        cbs.on_eval_end(logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = self._make_loader(eval_data, batch_size, False, False,
                                   num_workers)
        cbs = config_callbacks(callbacks, model=self, verbose=verbose,
                               steps=len(loader) if hasattr(loader, "__len__")
                               else None,
                               metrics=[m.name() for m in self._metrics])
        return self.evaluate_with_callbacks(loader, cbs)

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False, False,
                                   num_workers)
        outputs = []
        for batch in loader:
            batch = list(batch) if isinstance(batch, (list, tuple)) else [batch]
            # datasets that also yield labels (fit-style): drop the trailing
            # label element, same split rule as the train/eval loops
            if len(batch) > 1:
                batch = batch[:max(1, len(batch) - 1)]
            outputs.append(self.predict_batch(batch))
        if stack_outputs and outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    def _make_loader(self, data, batch_size, shuffle, drop_last, num_workers):
        from ..io import DataLoader
        from ..io.dataset import Dataset

        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              drop_last=drop_last, num_workers=num_workers)
        return data  # already a loader/iterable

    # -- persistence / inspection -------------------------------------------
    def save(self, path, training=True):
        import os

        from ..framework_io import save

        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        import os

        from ..framework_io import load

        self.network.set_state_dict(load(path + ".pdparams"))
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(load(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary

        return summary(self.network, input_size, dtype)
