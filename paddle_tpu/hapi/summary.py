"""Model summary + flops.

Reference parity: python/paddle/hapi/model_summary.py (summary table:
layer, output shape, params) and python/paddle/hapi/dynamic_flops.py
(paddle.flops).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..nn.layer import Layer


def _num_params(layer: Layer):
    return sum(int(np.prod(p.shape)) for p in layer.parameters())


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Print a per-sublayer table; returns {'total_params', 'trainable_params'}."""
    import paddle_tpu as paddle

    rows = []
    hooks = []
    seen = set()

    def make_hook(name, mod):
        def hook(layer, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
            shape = list(getattr(out, "shape", [])) if out is not None else []
            own = sum(int(np.prod(p.shape)) for p in layer.parameters(
                include_sublayers=False))
            rows.append((name or layer.__class__.__name__,
                         layer.__class__.__name__, shape, own))

        return hook

    for name, sub in net.named_sublayers(include_self=False):
        if id(sub) in seen:
            continue
        seen.add(id(sub))
        hooks.append(sub.register_forward_post_hook(make_hook(name, sub)))

    if input is not None:
        x = input if isinstance(input, (list, tuple)) else [input]
    else:
        if input_size is None:
            raise ValueError("summary needs input_size or input")
        sizes = input_size if isinstance(input_size, list) and isinstance(
            input_size[0], (list, tuple)) else [input_size]
        dts = dtypes or ["float32"] * len(sizes)
        x = [paddle.to_tensor(np.zeros(s, np.dtype(d)))
             for s, d in zip(sizes, dts)]
    was_training = net.training
    net.eval()
    try:
        net(*x)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()

    header = f"{'Layer (type)':<40}{'Output Shape':<24}{'Param #':>12}"
    lines = [header, "=" * len(header)]
    for name, cls, shape, own in rows:
        lines.append(f"{name + ' (' + cls + ')':<40}{str(shape):<24}{own:>12,}")
    total = _num_params(net)
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)
    lines += ["=" * len(header),
              f"Total params: {total:,}",
              f"Trainable params: {trainable:,}",
              f"Non-trainable params: {total - trainable:,}"]
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}


def flops(net: Layer, input_size, custom_ops=None, print_detail=False):
    """Rough per-layer FLOPs count (dynamic_flops.py parity for the common
    layer set: conv/linear/norm; other layers count 0)."""
    import paddle_tpu as paddle
    from .. import nn

    total = [0]
    hooks = []

    def conv_hook(layer, inputs, outputs):
        out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
        k = int(np.prod(layer._kernel_size)) if hasattr(layer, "_kernel_size") \
            else int(np.prod(layer.weight.shape[2:]))
        cin = layer.weight.shape[1]
        total[0] += int(np.prod(out.shape)) * cin * k * 2

    def linear_hook(layer, inputs, outputs):
        out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
        total[0] += int(np.prod(out.shape)) * layer.weight.shape[0] * 2

    for _, sub in net.named_sublayers():
        if isinstance(sub, (nn.Conv1D, nn.Conv2D, nn.Conv3D)):
            hooks.append(sub.register_forward_post_hook(conv_hook))
        elif isinstance(sub, nn.Linear):
            hooks.append(sub.register_forward_post_hook(linear_hook))

    x = paddle.to_tensor(np.zeros(input_size, np.float32))
    was_training = net.training
    net.eval()
    try:
        net(x)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()
    if print_detail:
        print(f"Total FLOPs: {total[0]:,}")
    return total[0]
