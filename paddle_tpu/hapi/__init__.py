"""paddle.hapi parity (python/paddle/hapi/): Model, callbacks, summary."""
from . import callbacks  # noqa: F401
from .callbacks import (  # noqa: F401
    Callback, EarlyStopping, LRScheduler, ModelCheckpoint, ProgBarLogger)
from .model import Model  # noqa: F401
from .summary import flops, summary  # noqa: F401
