"""hapi callbacks.

Reference parity: python/paddle/hapi/callbacks.py (Callback, ProgBarLogger,
ModelCheckpoint, EarlyStopping, LRScheduler, VisualDL/WandbCallback as
logging sinks).
"""
from __future__ import annotations

import os
import time
from typing import List, Optional


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        ...

    def on_train_end(self, logs=None):
        ...

    def on_eval_begin(self, logs=None):
        ...

    def on_eval_end(self, logs=None):
        ...

    def on_predict_begin(self, logs=None):
        ...

    def on_predict_end(self, logs=None):
        ...

    def on_epoch_begin(self, epoch, logs=None):
        ...

    def on_epoch_end(self, epoch, logs=None):
        ...

    def on_train_batch_begin(self, step, logs=None):
        ...

    def on_train_batch_end(self, step, logs=None):
        ...

    def on_eval_batch_begin(self, step, logs=None):
        ...

    def on_eval_batch_end(self, step, logs=None):
        ...

    def on_predict_batch_begin(self, step, logs=None):
        ...

    def on_predict_batch_end(self, step, logs=None):
        ...


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def dispatch(*args, **kwargs):
                for cb in self.callbacks:
                    getattr(cb, name)(*args, **kwargs)

            return dispatch
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """callbacks.py ProgBarLogger parity (line-per-epoch console logging)."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._start = time.time()
        if self.verbose and self.params.get("verbose", 1):
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def _fmt(self, logs):
        items = []
        for k, v in (logs or {}).items():
            if isinstance(v, (list, tuple)):
                items.append(f"{k}: {', '.join(f'{x:.4f}' for x in v)}")
            elif isinstance(v, float):
                items.append(f"{k}: {v:.4f}")
            else:
                items.append(f"{k}: {v}")
        return " - ".join(items)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and step % self.log_freq == 0:
            print(f"step {step + 1}/{self.steps or '?'} - {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._start
            print(f"Epoch {epoch + 1} done in {dt:.1f}s - {self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    """callbacks.py ModelCheckpoint parity: save every save_freq epochs."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and self.model and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir and self.model:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    """callbacks.py EarlyStopping parity."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.verbose = verbose
        if mode == "auto":
            mode = "min" if "loss" in monitor or "err" in monitor else "max"
        self.mode = mode
        self.stopped_epoch = 0
        self.wait = 0
        self.best = None
        self.stop_training = False

    def _better(self, cur, ref):
        if self.mode == "min":
            return cur < ref - self.min_delta
        return cur > ref + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        if self.monitor not in logs:
            return
        cur = logs[self.monitor]
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
            if self.save_best_model and self.model and \
                    self.params.get("save_dir"):
                self.model.save(os.path.join(self.params["save_dir"],
                                             "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True
                if self.model is not None:
                    self.model.stop_training = True


class LRScheduler(Callback):
    """callbacks.py LRScheduler parity: steps the optimizer's LR scheduler."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        assert by_step != by_epoch
        self.by_step = by_step

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if not self.by_step and s is not None:
            s.step()


class ReduceLROnPlateau(Callback):
    """callbacks.py ReduceLROnPlateau parity: when the monitored metric
    stops improving for ``patience`` epochs, multiply the optimizer's
    learning rate by ``factor`` (not below ``min_lr``)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10,
                 verbose=1, mode="auto", min_delta=1e-4, cooldown=0,
                 min_lr=0.0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self._best = None
        self._wait = 0
        self._cooldown_left = 0
        self._eval_fired = False

    def _better(self, cur):
        if self._best is None:
            return True
        if self.mode == "min":
            return cur < self._best - self.min_delta
        return cur > self._best + self.min_delta

    def on_eval_end(self, logs=None):
        # the reference monitors the EVAL metric; once an eval has fired,
        # epoch-end train logs are ignored (firing on both would double-
        # count patience and mix train/eval values of the same name)
        self._eval_fired = True
        self._check(logs)

    def on_epoch_end(self, epoch, logs=None):
        if not self._eval_fired:
            self._check(logs)

    def _check(self, logs):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(cur[0] if isinstance(cur, (list, tuple)) else cur)
        if self._cooldown_left > 0:
            # cooldown suppresses patience counting entirely (Keras/paddle
            # semantics), it does not just reset the counter
            self._cooldown_left -= 1
            self._wait = 0
            if self._better(cur):
                self._best = cur
            return
        if self._better(cur):
            self._best = cur
            self._wait = 0
            return
        self._wait += 1
        if self._wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is None:
                return
            old = float(opt.get_lr())
            new = max(old * self.factor, self.min_lr)
            if new < old:
                opt.set_lr(new)
                if self.verbose:
                    print(f"ReduceLROnPlateau: lr {old:.3g} -> {new:.3g}")
            self._cooldown_left = self.cooldown
            self._wait = 0


class VisualDL(Callback):
    """callbacks.py VisualDL parity. The visualdl wheel (its binary log
    format + web UI) is not in this image, so scalars are written as
    newline-JSON records under ``log_dir`` — the same data stream, a
    portable format."""

    def __init__(self, log_dir="vdl_log"):
        super().__init__()
        self.log_dir = log_dir
        self._last_step = 0
        self._eval_count = 0

    def _write(self, tag, step, logs):
        import json
        import os

        os.makedirs(self.log_dir, exist_ok=True)
        path = os.path.join(self.log_dir, "vdlrecords.jsonl")
        metrics = {k: (float(v[0]) if isinstance(v, (list, tuple)) else
                       float(v))
                   for k, v in (logs or {}).items()
                   if isinstance(v, (int, float)) or
                   (isinstance(v, (list, tuple)) and v and
                    isinstance(v[0], (int, float)))}
        if not metrics:
            return
        with open(path, "a") as f:
            f.write(json.dumps({"tag": tag, "step": step,
                                **metrics}) + "\n")

    def on_train_batch_end(self, step, logs=None):
        self._last_step = step
        self._write("train", step, logs)

    def on_eval_end(self, logs=None):
        self._eval_count += 1
        self._write("eval", self._eval_count, logs)


class StepTimer(Callback):
    """Train-loop telemetry into the process-wide metrics registry
    (paddle_tpu.observability): per-step wall time histogram, samples/s
    and tokens/s gauges, and device-memory gauges — the same registry
    the serving ``/metrics`` endpoint renders, so train and serve
    telemetry read out of one place. When ``FLAGS_log_memory_stats`` is
    set, each step also logs live/peak device bytes through the
    rank-aware logger (the observability StepTimer's flag wiring).

    ``tokens_per_sample`` (e.g. the sequence length) turns the
    batch-size samples/s reading into tokens/s; ``snapshot_dir`` appends
    a rank-aware JSONL registry snapshot every ``snapshot_freq`` steps.

    ``incident_dir`` arms the incident forensics layer for the training
    run: the process-wide flight recorder turns on (per-step
    ``train.step`` events in the black box) and the IncidentReporter is
    activated at that directory — a crash anywhere under ``fit()``
    writes a rank-suffixed bundle (event ring, spans, metrics snapshot,
    thread stacks; see docs/SERVING.md "Incident forensics").

    When request-scoped tracing is enabled
    (``paddle_tpu.observability.tracing``), each epoch opens a
    ``train.epoch`` span that parents the core timer's per-batch
    ``train.step`` spans — train loops land on the same chrome-trace
    timeline as serving requests.
    """

    def __init__(self, tokens_per_sample=None, snapshot_dir=None,
                 snapshot_freq=100, logger=None, incident_dir=None):
        super().__init__()
        from ..observability import StepTimer as _CoreTimer

        self.tokens_per_sample = tokens_per_sample
        self.snapshot_freq = max(1, int(snapshot_freq))
        self._timer = _CoreTimer(logger=logger)
        self._writer = None
        if snapshot_dir is not None:
            from ..observability import SnapshotWriter

            self._writer = SnapshotWriter(snapshot_dir, prefix="train")
        if incident_dir is not None:
            from ..observability import flightrecorder as _frec

            _frec.get_recorder().enable()
            _frec.get_reporter().activate(incident_dir)
        self._seen = 0
        self._epoch_span = None

    def on_epoch_begin(self, epoch, logs=None):
        from ..observability import tracing

        tracer = tracing.get_tracer()
        span = tracer.start_span(tracing.SPAN_TRAIN_EPOCH,
                                 attrs={"epoch": int(epoch)})
        if span:
            # made current so the per-batch train.step spans nest under
            # it (fit runs epochs on one thread)
            tracer._push(span)
            self._epoch_span = span

    def on_epoch_end(self, epoch, logs=None):
        span, self._epoch_span = self._epoch_span, None
        if span is not None:
            from ..observability import tracing

            tracing.get_tracer()._pop(span)
            span.end()

    def on_train_batch_begin(self, step, logs=None):
        self._timer.begin()

    def on_train_batch_end(self, step, logs=None):
        n = int((logs or {}).get("batch_size") or 0) or None
        toks = (n * int(self.tokens_per_sample)
                if n and self.tokens_per_sample else None)
        self._timer.end(n_samples=n, n_tokens=toks)
        self._seen += 1
        if self._writer is not None and self._seen % self.snapshot_freq == 0:
            self._writer.write(step=step)

    def on_train_end(self, logs=None):
        if self._writer is not None and self._seen:
            self._writer.write(step=self._seen)


class WandbCallback(Callback):
    """callbacks.py WandbCallback surface: the wandb SDK (a network
    service client) is not in this image — constructing raises with
    guidance rather than silently not logging."""

    def __init__(self, *args, **kwargs):
        raise ImportError(
            "WandbCallback needs the `wandb` SDK, which is not available "
            "in this image; use VisualDL (local JSONL scalars) or a "
            "custom Callback instead")


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     verbose=1, save_freq=1, save_dir=None, metrics=None,
                     mode="train"):
    cbs = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbs) and verbose:
        cbs = [ProgBarLogger(verbose=verbose)] + cbs
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbs):
        cbs.append(ModelCheckpoint(save_freq, save_dir))
    lst = CallbackList(cbs)
    lst.set_model(model)
    lst.set_params({"epochs": epochs, "steps": steps, "verbose": verbose,
                    "metrics": metrics or [], "save_dir": save_dir})
    return lst
