"""paddle.profiler parity.

Reference: three-part profiler (SURVEY.md §5) — host RecordEvent spans
(paddle/phi/api/profiler/event_tracing.h), device tracer (CUPTI), merged
chrome-trace export (chrometracing_logger.cc); Python surface
python/paddle/profiler/profiler.py:358 (Profiler with scheduler state
machine), :227 (export_chrome_tracing), timer.py (ips benchmark).

TPU-native: host spans are recorded by a pure-Python recorder (the
RecordEvent API is preserved); the device side delegates to jax.profiler
(XPlane/perfetto), started/stopped by the same scheduler. Both land in the
same output dir.
"""
from .profiler import (
    Profiler, ProfilerState, ProfilerTarget, RecordEvent, SummaryView,
    export_chrome_tracing, export_protobuf, load_profiler_result,
    make_scheduler)
from .timer import benchmark
from .profiler_statistic import SortedKeys

__all__ = [
    "Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
    "SummaryView", "SortedKeys", "export_chrome_tracing", "export_protobuf",
    "load_profiler_result", "make_scheduler", "benchmark",
]
