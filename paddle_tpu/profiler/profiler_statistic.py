"""Host-span statistics tables.

Reference: python/paddle/profiler/profiler_statistic.py (SortedKeys, the
summary table printers consumed by Profiler.summary).
"""
from __future__ import annotations

from collections import defaultdict
from enum import Enum


class SortedKeys(Enum):
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


_UNIT = {"s": 1e-6, "ms": 1e-3, "us": 1.0}


def host_summary(events, time_unit="ms") -> str:
    """Aggregate (name → calls, total, avg, max, min) over recorded spans."""
    scale = _UNIT.get(time_unit, 1e-3)
    agg = defaultdict(list)
    for (name, typ, start, end, tid) in events:
        agg[name].append((end - start) * scale)
    rows = [(n, len(d), sum(d), sum(d) / len(d), max(d), min(d))
            for n, d in sorted(agg.items(), key=lambda kv: -sum(kv[1]))]
    header = (f"{'Name':<40}{'Calls':>8}{'Total(' + time_unit + ')':>14}"
              f"{'Avg':>12}{'Max':>12}{'Min':>12}")
    lines = [header, "-" * len(header)]
    for n, c, tot, avg, mx, mn in rows:
        lines.append(f"{n[:39]:<40}{c:>8}{tot:>14.4f}{avg:>12.4f}"
                     f"{mx:>12.4f}{mn:>12.4f}")
    return "\n".join(lines)
