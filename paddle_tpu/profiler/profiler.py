"""Profiler core: RecordEvent spans, scheduler state machine, chrome trace.

Reference: python/paddle/profiler/profiler.py (Profiler :358, make_scheduler
:129, export_chrome_tracing :227, ProfilerState :89, ProfilerTarget :110);
host recorder paddle/phi/api/profiler/host_event_recorder.h; chrome export
paddle/fluid/platform/profiler/chrometracing_logger.cc.
"""
from __future__ import annotations

import json
import os
import threading
import time
from enum import Enum
from typing import Callable, Iterable, Optional


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class SummaryView(Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


class _HostEventRecorder:
    """host_event_recorder.h parity: thread-local span stacks, one global
    sink; spans carry (name, event_type, start_us, end_us, tid)."""

    def __init__(self):
        self._events = []
        self._lock = threading.Lock()
        self._enabled = False

    def start(self):
        with self._lock:
            self._events = []
            self._enabled = True

    def stop(self):
        with self._lock:
            self._enabled = False

    def record(self, name, typ, start_us, end_us):
        if not self._enabled:
            return
        ev = (name, typ, start_us, end_us, threading.get_ident())
        with self._lock:
            self._events.append(ev)

    def events(self):
        with self._lock:
            return list(self._events)


_recorder = _HostEventRecorder()


class RecordEvent:
    """User span (event_tracing.h RecordEvent parity): context manager or
    explicit begin()/end()."""

    def __init__(self, name: str, event_type: str = "UserDefined"):
        self.name = name
        self.event_type = event_type
        self._start = None

    def begin(self):
        self._start = time.perf_counter_ns() // 1000

    def end(self):
        if self._start is None:
            return
        _recorder.record(self.name, self.event_type, self._start,
                         time.perf_counter_ns() // 1000)
        self._start = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def make_scheduler(*, closed: int, ready: int, record: int,
                   repeat: int = 0, skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """profiler.py:129 parity: step → state, cycling
    [closed, ready, record(last step RECORD_AND_RETURN)] repeat times."""
    span = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat > 0 and s >= repeat * span:
            return ProfilerState.CLOSED
        pos = s % span
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == span - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def _default_state_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD


def export_chrome_tracing(dir_name: str,
                          worker_name: Optional[str] = None) -> Callable:
    """profiler.py:227 parity: on_trace_ready callback writing
    chrome://tracing JSON into dir_name."""

    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(
            dir_name, f"{name}_time_{int(time.time() * 1000)}.paddle_trace.json")
        prof._export_chrome(path)
        prof._last_export = path

    return handler


def export_protobuf(dir_name: str, worker_name: Optional[str] = None) -> Callable:
    """API parity; the TPU build's device traces are XPlane protos written
    by jax.profiler into the same dir."""
    return export_chrome_tracing(dir_name, worker_name)


def load_profiler_result(filename: str):
    with open(filename) as f:
        return json.load(f)


class Profiler:
    """profiler.py:358 parity. targets/scheduler/on_trace_ready keep their
    meaning; device tracing is jax.profiler (XPlane) when a trace dir is
    known and the platform supports it."""

    def __init__(self, *, targets: Optional[Iterable[ProfilerTarget]] = None,
                 scheduler=None, on_trace_ready: Optional[Callable] = None,
                 record_shapes: bool = False, profile_memory: bool = False,
                 with_flops: bool = False, timer_only: bool = False,
                 emit_nvtx: bool = False, custom_device_types=None):
        self.targets = list(targets) if targets else [ProfilerTarget.CPU]
        if scheduler is None:
            self._scheduler = _default_state_scheduler
        elif isinstance(scheduler, (tuple, list)):
            start, end = scheduler
            self._scheduler = make_scheduler(
                closed=max(start, 0), ready=0, record=end - start, repeat=1)
        else:
            self._scheduler = scheduler
        self.on_trace_ready = on_trace_ready or export_chrome_tracing(
            "./profiler_log/")
        self.timer_only = timer_only
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._jax_tracing = False
        self._trace_dir = None
        self._last_export = None
        from .timer import benchmark as _bm

        self._benchmark = _bm()

    # -- device (jax) tracer ------------------------------------------------
    def _device_start(self):
        if self.timer_only or self._jax_tracing:
            return
        try:
            import jax

            self._trace_dir = getattr(self.on_trace_ready, "_dir", None) or \
                "./profiler_log/"
            os.makedirs(self._trace_dir, exist_ok=True)
            jax.profiler.start_trace(self._trace_dir)
            self._jax_tracing = True
        except Exception as e:  # pragma: no cover - device tracer unavailable
            # host timers still work, but the requested device trace is
            # silently missing otherwise — the flight recorder's compile
            # and step timelines depend on knowing the tracer is absent
            self._logger().warning(
                "profiler: device trace unavailable, host timers only "
                "(%s: %s)", type(e).__name__, e)
            self._jax_tracing = False

    def _device_stop(self):
        if not self._jax_tracing:
            return
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:  # pragma: no cover
            # a failed stop means the trace file may be truncated — say
            # so instead of letting the operator trust a partial profile
            self._logger().warning(
                "profiler: stop_trace failed, device trace may be "
                "truncated (%s: %s)", type(e).__name__, e)
        self._jax_tracing = False

    @staticmethod
    def _logger():
        from ..distributed.log_utils import get_logger

        return get_logger(name="paddle_tpu.profiler")

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        self._benchmark.begin()
        if self.timer_only:
            return
        self.current_state = self._scheduler(self.step_num)
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            _recorder.start()
            self._device_start()

    def stop(self):
        self._benchmark.end()
        if self.timer_only:
            return
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._device_stop()
            _recorder.stop()
            if self.on_trace_ready:
                self.on_trace_ready(self)
        self.current_state = ProfilerState.CLOSED

    def step(self, num_samples: Optional[int] = None):
        self._benchmark.step(num_samples)
        if self.timer_only:
            self.step_num += 1
            return
        prev = self.current_state
        self.step_num += 1
        new = self._scheduler(self.step_num)
        if prev != new:
            if prev == ProfilerState.RECORD_AND_RETURN or (
                    prev in (ProfilerState.RECORD,) and new in (
                        ProfilerState.CLOSED, ProfilerState.READY)):
                self._device_stop()
                _recorder.stop()
                if self.on_trace_ready:
                    self.on_trace_ready(self)
            if new in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN) \
                    and prev not in (ProfilerState.RECORD,):
                _recorder.start()
                self._device_start()
        self.current_state = new

    def step_info(self, unit: Optional[str] = None) -> str:
        return self._benchmark.step_info(unit)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- export / summary ---------------------------------------------------
    def _export_chrome(self, path: str):
        events = _recorder.events()
        trace = {"traceEvents": [
            {"name": n, "cat": t, "ph": "X", "pid": os.getpid(), "tid": tid,
             "ts": start, "dur": end - start}
            for (n, t, start, end, tid) in events]}
        with open(path, "w") as f:
            json.dump(trace, f)

    def export(self, path: str, format: str = "json"):
        self._export_chrome(path)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms", views=None):
        from .profiler_statistic import host_summary

        print(host_summary(_recorder.events(), time_unit))
