"""Throughput timer (ips / reader-cost).

Reference: python/paddle/profiler/timer.py — Benchmark with reader/batch
cost averagers and get_ips_average (:332), surfaced via
Profiler.step_info (:735-style "reader_cost ... batch_cost ... ips ...").
"""
from __future__ import annotations

import time
from typing import Optional


class _Averager:
    def __init__(self):
        self.reset()

    def reset(self):
        self.total = 0.0
        self.count = 0

    def record(self, v, n=1):
        self.total += v
        self.count += n

    def average(self):
        return self.total / self.count if self.count else 0.0


class benchmark:
    """Reference timer.Benchmark parity (lowercase name matches
    paddle.profiler.benchmark usage via Profiler)."""

    def __init__(self):
        self.reader_cost = _Averager()
        self.batch_cost = _Averager()
        self.ips = _Averager()
        self._batch_start = None
        self._reader_mark = None
        self.last = {}

    def begin(self):
        self._batch_start = time.perf_counter()
        self._reader_mark = self._batch_start

    def before_reader(self):
        self._reader_mark = time.perf_counter()

    def after_reader(self):
        if self._reader_mark is not None:
            self.reader_cost.record(time.perf_counter() - self._reader_mark)

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._batch_start is not None:
            dt = now - self._batch_start
            self.batch_cost.record(dt)
            if num_samples:
                self.ips.record(num_samples, 1)
                self.last["ips"] = num_samples / dt if dt else 0.0
            self.last["batch_cost"] = dt
            # mirror into the unified registry so Profiler-timed loops
            # show up on /metrics and JSONL snapshots too
            from ..observability import catalog as _cat
            from ..observability import tracing as _tracing

            _cat.TRAIN_STEP_SECONDS.observe(dt)
            if "ips" in self.last:
                _cat.TRAIN_SAMPLES_PER_SEC.set(self.last["ips"])
            tracer = _tracing.get_tracer()
            if tracer.enabled:
                # the batch window as a train.step span (perf_counter
                # and perf_counter_ns share one clock) — Profiler-timed
                # loops land on the same timeline as serving spans
                tracer.add_span(
                    _tracing.SPAN_TRAIN_STEP,
                    int(self._batch_start * 1e9), int(now * 1e9),
                    attrs={"batch_cost": dt,
                           "samples": int(num_samples or 0)})
        self._batch_start = now

    def end(self):
        self._batch_start = None

    def step_info(self, unit: Optional[str] = None) -> str:
        avg_batch = self.batch_cost.average()
        ips = (self.ips.total / self.batch_cost.total
               if self.batch_cost.total else 0.0)
        u = unit or "samples"
        return (f"reader_cost: {self.reader_cost.average():.5f} s "
                f"batch_cost: {avg_batch:.5f} s ips: {ips:.3f} {u}/s")
