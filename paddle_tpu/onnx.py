"""paddle.onnx.export (python/paddle/onnx/export.py parity).

The reference shells out to the external paddle2onnx toolchain; this build
has no egress and no onnx package, so the exporter is implemented directly:
the static Program recorder (static/program.py) captures the layer's
dataflow graph of framework-level ops, and this module lowers that graph to
an ONNX ModelProto written with a minimal hand-rolled protobuf wire-format
writer (varint + length-delimited fields — all the encoding ONNX needs).

Covered op set (inference graphs): linear/matmul (+bias), elementwise
add/sub/mul/div, relu/sigmoid/tanh/exp/sqrt/abs/erf, softmax, gelu (Erf
decomposition), conv2d, adaptive_avg_pool2d(1) → GlobalAveragePool,
batch_norm (eval), reshape/flatten/transpose, mean → ReduceMean, cast,
dropout (eval = identity elision). Anything else raises with the op name —
never a silently wrong file. The TPU-native serving artifact remains
StableHLO (jit.save / save_inference_model).
"""
from __future__ import annotations

import struct

import numpy as np

__all__ = ["export"]


# ---------------------------------------------------------------------------
# protobuf wire-format writer
# ---------------------------------------------------------------------------

def _varint(n: int) -> bytes:
    out = b""
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        out += bytes([b | (0x80 if n else 0)])
        if not n:
            return out


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _f_varint(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(int(value))


def _f_bytes(field: int, data: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(data)) + data


def _f_str(field: int, s: str) -> bytes:
    return _f_bytes(field, s.encode())


def _f_float(field: int, v: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", v)


# ONNX TensorProto.DataType
_DTYPE = {"float32": 1, "uint8": 2, "int8": 3, "int16": 5, "int32": 6,
          "int64": 7, "bool": 9, "float16": 10, "float64": 11,
          "bfloat16": 16}


def _tensor_proto(name: str, arr: np.ndarray) -> bytes:
    dt = _DTYPE.get(str(arr.dtype))
    if dt is None:
        raise NotImplementedError(f"onnx export: dtype {arr.dtype}")
    if str(arr.dtype) == "bfloat16":
        raw = np.asarray(arr).view(np.uint16).tobytes()
    else:
        raw = np.ascontiguousarray(arr).tobytes()
    msg = b"".join(_f_varint(1, d) for d in arr.shape)
    msg += _f_varint(2, dt)
    msg += _f_str(8, name)
    msg += _f_bytes(9, raw)          # raw_data
    return msg


# AttributeProto bodies (type codes: FLOAT=1, INT=2, INTS=7)
def _attr_int(name, v):
    return _f_str(1, name) + _f_varint(3, v) + _f_varint(20, 2)


def _attr_float(name, v):
    return _f_str(1, name) + _f_float(2, v) + _f_varint(20, 1)


def _attr_ints(name, vs):
    body = _f_str(1, name)
    for v in vs:
        body += _f_varint(8, int(v))
    return body + _f_varint(20, 7)


def _attr_field(attr_body: bytes) -> bytes:
    return _f_bytes(5, attr_body)


def _node(op_type, inputs, outputs, attrs=b"", name=""):
    msg = b"".join(_f_str(1, i) for i in inputs)
    msg += b"".join(_f_str(2, o) for o in outputs)
    if name:
        msg += _f_str(3, name)
    msg += _f_str(4, op_type)
    msg += attrs                      # concatenated _attr_field() blocks
    return msg


def _value_info(name: str, shape, dtype: str) -> bytes:
    dims = b""
    for k, d in enumerate(shape):
        if d is None or (isinstance(d, int) and d < 0):
            dim = _f_str(2, f"dyn_{k}")        # dim_param: symbolic size
        else:
            dim = _f_varint(1, int(d))         # dim_value
        dims += _f_bytes(1, dim)
    tensor_type = _f_varint(1, _DTYPE.get(dtype, 1)) + _f_bytes(2, dims)
    type_proto = _f_bytes(1, tensor_type)
    return _f_str(1, name) + _f_bytes(2, type_proto)


# ---------------------------------------------------------------------------
# graph builder
# ---------------------------------------------------------------------------

class _GraphBuilder:
    def __init__(self):
        self.nodes = []          # serialized NodeProto bodies
        self.initializers = []   # serialized TensorProto bodies
        self.names = {}          # tensor id -> onnx name
        self._n = 0

    def fresh(self, base):
        self._n += 1
        return f"{base}_{self._n}"

    def input_name(self, tid, arr):
        """Name for a node input: existing graph tensor, else a new
        initializer holding the captured parameter/constant value."""
        if tid in self.names:
            return self.names[tid]
        name = self.fresh("param")
        self.initializers.append(_tensor_proto(name, np.asarray(arr)))
        self.names[tid] = name
        return name

    def emit(self, op_type, in_names, out_ids, attrs=b"", n_out=1):
        outs = [self.fresh(op_type.lower()) for _ in range(n_out)]
        self.nodes.append(_node(op_type, in_names, outs, attrs))
        for tid, name in zip(out_ids, outs):
            self.names[tid] = name
        return outs


def _pair(v):
    return [v, v] if isinstance(v, int) else list(v)


def _closure_vars(fn):
    """Attrs of a recorded op closure (freevar name -> cell value)."""
    if fn.__closure__ is None:
        return {}
    return dict(zip(fn.__code__.co_freevars,
                    [c.cell_contents for c in fn.__closure__]))


_ELEMENTWISE = {"add": "Add", "subtract": "Sub", "multiply": "Mul",
                "divide": "Div"}
_UNARY = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
          "exp": "Exp", "sqrt": "Sqrt", "abs": "Abs", "erf": "Erf",
          "floor": "Floor", "ceil": "Ceil"}


def _convert_node(g: _GraphBuilder, node, args, kwargs, in_names, arrs,
                  shapes):
    """Lower one recorded framework op to ONNX node(s). ``shapes`` maps
    tensor id -> shape for every graph tensor (from the Program's
    keepalive list) — used where an op's attrs are closed over."""
    op = node.name
    out_ids = node.out_ids

    if op in _ELEMENTWISE:
        g.emit(_ELEMENTWISE[op], in_names, out_ids)
    elif op in _UNARY:
        g.emit(_UNARY[op], in_names[:1], out_ids)
    elif op == "linear":
        mm = g.fresh("matmul")
        g.nodes.append(_node("MatMul", in_names[:2], [mm]))
        if len(in_names) > 2:
            g.emit("Add", [mm, in_names[2]], out_ids)
        else:
            g.names[out_ids[0]] = mm
    elif op == "matmul":
        cv = _closure_vars(node.fn)
        tx = cv.get("transpose_x", False)
        ty = cv.get("transpose_y", False)
        names = list(in_names)
        for k, flag in ((0, tx), (1, ty)):
            if flag:
                t = g.fresh("transpose")
                nd = arrs[k].ndim
                perm = list(range(nd - 2)) + [nd - 1, nd - 2]
                g.nodes.append(_node("Transpose", [names[k]], [t],
                                     _attr_field(_attr_ints("perm", perm))))
                names[k] = t
        g.emit("MatMul", names[:2], out_ids)
    elif op == "softmax":
        axis = _closure_vars(node.fn).get("axis", -1)
        g.emit("Softmax", in_names[:1], out_ids,
               _attr_field(_attr_int("axis", int(axis))))
    elif op == "gelu":
        x = in_names[0]
        dt = str(arrs[0].dtype)
        approx = _closure_vars(node.fn).get("approximate", False)

        def const(val):
            n = g.fresh("const")
            g.initializers.append(_tensor_proto(
                n, np.asarray(val).astype(dt)))
            return n

        if approx:
            # tanh form: 0.5 x (1 + tanh(sqrt(2/pi)(x + 0.044715 x^3)))
            x3 = g.fresh("mul")
            x2 = g.fresh("mul")
            g.nodes.append(_node("Mul", [x, x], [x2]))
            g.nodes.append(_node("Mul", [x2, x], [x3]))
            cx3 = g.fresh("mul")
            g.nodes.append(_node("Mul", [x3, const(0.044715)], [cx3]))
            inner = g.fresh("add")
            g.nodes.append(_node("Add", [x, cx3], [inner]))
            scaled = g.fresh("mul")
            g.nodes.append(_node(
                "Mul", [inner, const(np.sqrt(2.0 / np.pi))], [scaled]))
            th = g.fresh("tanh")
            g.nodes.append(_node("Tanh", [scaled], [th]))
            plus1 = g.fresh("add")
            g.nodes.append(_node("Add", [th, const(1.0)], [plus1]))
        else:
            # exact form: 0.5 x (1 + erf(x / sqrt(2)))
            scaled = g.fresh("mul")
            g.nodes.append(_node(
                "Mul", [x, const(1.0 / np.sqrt(2.0))], [scaled]))
            erf = g.fresh("erf")
            g.nodes.append(_node("Erf", [scaled], [erf]))
            plus1 = g.fresh("add")
            g.nodes.append(_node("Add", [erf, const(1.0)], [plus1]))
        xm = g.fresh("mul")
        g.nodes.append(_node("Mul", [x, plus1], [xm]))
        g.emit("Mul", [xm, const(0.5)], out_ids)
    elif op == "reshape":
        shape = _closure_vars(node.fn).get("shape")
        if shape is None:
            raise NotImplementedError("onnx export: reshape without a "
                                      "recoverable static shape")
        sh = g.fresh("shape_const")
        g.initializers.append(_tensor_proto(
            sh, np.asarray(list(shape), np.int64)))
        g.emit("Reshape", [in_names[0], sh], out_ids)
    elif op == "flatten":
        # paddle flatten is rank-preserving outside [start, stop]; ONNX
        # Flatten is always 2-D — lower as Reshape. Leading dims use the
        # 0-wildcard (copy from input) and the flattened run uses -1, so a
        # dynamic batch dim (traced at size 1) is NOT baked into the graph;
        # only dims after stop_axis keep their traced concrete sizes.
        oshape = shapes.get(out_ids[0])
        if oshape is None:
            raise NotImplementedError("onnx export: flatten output shape "
                                      "unknown")
        cv = _closure_vars(node.fn)
        ishape = shapes.get(node.in_ids[0]) if node.in_ids else None
        if cv.get("start_axis") is not None and ishape:
            nd = len(ishape)
            s = cv["start_axis"] % nd if nd else 0
            target = [0] * s + [-1] + [int(d) for d in oshape[s + 1:]]
        else:
            target = [int(d) for d in oshape]
        sh = g.fresh("shape_const")
        g.initializers.append(_tensor_proto(
            sh, np.asarray(target, np.int64)))
        g.emit("Reshape", [in_names[0], sh], out_ids)
    elif op == "transpose":
        perm = _closure_vars(node.fn).get("perm")
        if perm is None:
            raise NotImplementedError("onnx export: transpose without a "
                                      "recoverable perm")
        g.emit("Transpose", in_names[:1], out_ids,
               _attr_field(_attr_ints("perm", list(perm))))
    elif op == "conv":
        # attrs are closed over the recorded fn (nn/functional/conv.py
        # _conv); read them from the closure cells
        cv = _closure_vars(node.fn)
        if cv.get("channel_last"):
            raise NotImplementedError("onnx export: channel-last conv")
        n_sp = int(cv["n"])
        stride = list(cv["strides"])
        dilation = list(cv["dil"])
        padding = cv["padding"]
        groups = int(cv["groups"])
        from .nn.functional.conv import _conv_padding

        pad = _conv_padding(padding, n_sp, arrs[1].shape, dilation)
        if isinstance(pad, str):
            raise NotImplementedError("onnx export: string conv padding")
        begins = [p[0] for p in pad]
        ends = [p[1] for p in pad]
        attrs = (_attr_field(_attr_ints("strides", stride))
                 + _attr_field(_attr_ints("pads", begins + ends))
                 + _attr_field(_attr_ints("dilations", dilation))
                 + _attr_field(_attr_int("group", groups)))
        g.emit("Conv", in_names, out_ids, attrs)
    elif op == "adaptive_avg_pool":
        # attrs are closed over; the OUTPUT shape tells us whether this is
        # the global pool (the exportable case)
        oshape = shapes.get(out_ids[0])
        if oshape is None or any(d != 1 for d in oshape[2:]):
            raise NotImplementedError(
                "onnx export: adaptive_avg_pool only with output_size 1")
        g.emit("GlobalAveragePool", in_names[:1], out_ids)
    elif op == "batch_norm":
        # recorded input order: x, running_mean, running_var,
        # [weight], [bias] — presence read from the closure
        cv = _closure_vars(node.fn)
        eps = float(cv.get("epsilon", 1e-5))
        has_w = cv.get("weight") is not None
        has_b = cv.get("bias") is not None
        ch = arrs[0].shape[1]
        dt = str(arrs[0].dtype)
        k = 3
        if has_w:
            scale_name = in_names[k]
            k += 1
        else:
            scale_name = g.fresh("bn_scale")
            g.initializers.append(_tensor_proto(
                scale_name, np.ones(ch, dtype=dt)))
        if has_b:
            bias_name = in_names[k]
        else:
            bias_name = g.fresh("bn_bias")
            g.initializers.append(_tensor_proto(
                bias_name, np.zeros(ch, dtype=dt)))
        g.emit("BatchNormalization",
               [in_names[0], scale_name, bias_name, in_names[1],
                in_names[2]], out_ids,
               _attr_field(_attr_float("epsilon", eps)))
    elif op == "cast":
        dt = args[1] if len(args) > 1 else kwargs.get("dtype")
        g.emit("Cast", in_names[:1], out_ids,
               _attr_field(_attr_int("to", _DTYPE.get(str(dt), 1))))
    elif op == "embedding":
        # Gather over axis 0: weight rows indexed by ids
        # recorded as (ids, weight) → ONNX Gather(data=weight, indices=ids)
        g.emit("Gather", [in_names[1], in_names[0]], out_ids,
               _attr_field(_attr_int("axis", 0)))
    elif op == "rms_norm":
        # decomposition: x / sqrt(mean(x^2) + eps) [* w]
        cv = _closure_vars(node.fn)
        eps = float(cv.get("epsilon", 1e-6))
        x_name = in_names[0]
        dt = str(arrs[0].dtype)
        sq = g.fresh("mul")
        g.nodes.append(_node("Mul", [x_name, x_name], [sq]))
        mean = g.fresh("reducemean")
        g.nodes.append(_node(
            "ReduceMean", [sq], [mean],
            _attr_field(_attr_ints("axes", [-1]))
            + _attr_field(_attr_int("keepdims", 1))))
        eps_c = g.fresh("const")
        g.initializers.append(_tensor_proto(eps_c,
                                            np.asarray(eps).astype(dt)))
        pe = g.fresh("add")
        g.nodes.append(_node("Add", [mean, eps_c], [pe]))
        rt = g.fresh("sqrt")
        g.nodes.append(_node("Sqrt", [pe], [rt]))
        normed = g.fresh("div")
        g.nodes.append(_node("Div", [x_name, rt], [normed]))
        if len(in_names) > 1:
            g.emit("Mul", [normed, in_names[1]], out_ids)
        else:
            g.names[out_ids[0]] = normed
    elif op == "dropout":
        cv = _closure_vars(node.fn)
        p = cv.get("p")
        if p is not None:
            # downscale_in_infer eval path records a real a*(1-p) scaling
            dt = str(arrs[0].dtype)
            c = g.fresh("const")
            g.initializers.append(_tensor_proto(
                c, np.asarray(1.0 - float(p)).astype(dt)))
            g.emit("Mul", [in_names[0], c], out_ids)
        else:
            # upscale_in_train at eval: identity — alias through
            for oid in out_ids:
                g.names[oid] = in_names[0]
    elif op == "mean":
        axis = args[1] if len(args) > 1 else kwargs.get("axis")
        keep = args[2] if len(args) > 2 else kwargs.get("keepdim", False)
        attrs = _attr_field(_attr_int("keepdims", 1 if keep else 0))
        if axis is not None:
            ax = axis if isinstance(axis, (list, tuple)) else [axis]
            attrs += _attr_field(_attr_ints("axes", list(ax)))
        g.emit("ReduceMean", in_names[:1], out_ids, attrs)
    else:
        raise NotImplementedError(
            f"onnx export: op {op!r} has no ONNX lowering yet (supported: "
            "linear/matmul, elementwise, activations, softmax, gelu, "
            "conv2d, batch_norm, adaptive_avg_pool2d(1), reshape/flatten/"
            "transpose, mean, cast, dropout)")


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Trace ``layer`` (eval mode) through the static Program recorder and
    write ``{path}.onnx``. Returns the written file path."""
    import jax.tree_util as jtu

    from . import static as pstatic
    from .static.program import Program, program_guard
    from .tensor_class import Tensor

    if input_spec is None:
        raise ValueError("onnx.export requires input_spec (shapes/dtypes)")

    was_training = getattr(layer, "training", False)
    if hasattr(layer, "eval"):
        layer.eval()
    prog = Program()
    g = _GraphBuilder()
    feed_infos = []
    try:
        with program_guard(prog):
            feeds = []
            for i, spec in enumerate(input_spec):
                if isinstance(spec, Tensor):
                    shape = tuple(spec.shape)
                    dtype = str(np.asarray(spec.numpy()).dtype)
                else:  # InputSpec-like
                    shape = tuple(spec.shape)
                    dtype = str(np.dtype(spec.dtype))
                name = getattr(spec, "name", None) or f"x{i}"
                t = pstatic.data(name, [d if d not in (None, -1) else 1
                                        for d in shape], dtype)
                g.names[id(t)] = name
                feed_infos.append((name, shape, dtype))
                feeds.append(t)
            out = layer(*feeds)
    finally:
        if was_training and hasattr(layer, "train"):
            layer.train()

    outs = out if isinstance(out, (list, tuple)) else [out]
    out_ids = [id(o) for o in outs]

    shapes = {id(t): tuple(t.shape) for t in prog._keepalive
              if isinstance(t, Tensor)}
    for node in prog.nodes:
        stored = list(node.leaves)
        in_names, arrs = [], []
        for pos, tid in zip(node.tensor_pos, node.in_ids):
            arr = stored[pos]
            in_names.append(g.input_name(tid, arr))
            arrs.append(np.asarray(arr))
        args, kwargs = jtu.tree_unflatten(node.treedef, stored)
        _convert_node(g, node, args, kwargs, in_names, arrs, shapes)

    graph = b"".join(_f_bytes(1, n) for n in g.nodes)
    graph += _f_str(2, type(layer).__name__)
    graph += b"".join(_f_bytes(5, t) for t in g.initializers)
    for name, shape, dtype in feed_infos:
        graph += _f_bytes(11, _value_info(name, shape, dtype))
    for k, oid in enumerate(out_ids):
        if oid not in g.names:
            raise RuntimeError("onnx export: model output was not produced "
                               "by any recorded op")
        o = outs[k]
        graph += _f_bytes(12, _value_info(
            g.names[oid], tuple(o.shape), str(np.asarray(o.numpy()).dtype)))

    opset = _f_str(1, "") + _f_varint(2, int(opset_version))
    model = (_f_varint(1, 8)                      # ir_version
             + _f_str(2, "paddle_tpu")            # producer_name
             + _f_str(3, "0.1.0")                 # producer_version
             + _f_bytes(7, graph)
             + _f_bytes(8, opset))                # opset_import
    out_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(model)
    return out_path
