"""paddle.onnx parity surface.

The reference delegates paddle.onnx.export to the external paddle2onnx
package (python/paddle/onnx/export.py); this build has no egress to fetch
it, and the TPU-native deployment artifact is StableHLO
(static.save_inference_model / jit.save). export() raises with that
guidance rather than silently writing a wrong format.
"""
from __future__ import annotations


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "ONNX export requires the external paddle2onnx toolchain (the "
        "reference shells out to it too). On the TPU build, export a "
        "deployable artifact with paddle.static.save_inference_model "
        "(StableHLO via jax.export) or paddle.jit.save instead.")
