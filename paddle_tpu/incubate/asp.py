"""paddle.incubate.asp parity (python/paddle/incubate/asp/): automatic
2:4 structured sparsity — prune_model computes n:m magnitude masks,
decorate() wraps an optimizer so masks are re-applied after every step
(the reference's OptimizerWithSparsityGuarantee).

TPU note: XLA has no sparse-tensor-core path, so the value here is the
workflow parity (mask computation, guaranteed sparsity through training)
and model-size reduction at export; the masked matmuls stay dense on the
MXU.
"""
from __future__ import annotations

import numpy as np

__all__ = ["decorate", "prune_model", "set_excluded_layers",
           "reset_excluded_layers", "calculate_density",
           "add_supported_layer"]

_EXTRA_SUPPORTED: list = []


def add_supported_layer(layer, pruning_func=None):
    """incubate.asp.add_supported_layer: register an extra layer TYPE (or
    name) whose .weight prune_model should mask."""
    _EXTRA_SUPPORTED.append((layer, pruning_func))

_EXCLUDED: set = set()
# id(param) -> (weakref(param), mask): weakrefs let pruned models be
# garbage-collected; dead entries are swept on access
_MASKS: dict = {}


def set_excluded_layers(param_names, main_program=None):
    """Skip these parameter names in prune_model/decorate."""
    _EXCLUDED.update(param_names)


def reset_excluded_layers(main_program=None):
    _EXCLUDED.clear()


def calculate_density(x) -> float:
    """Fraction of nonzeros in a tensor/ndarray."""
    from ..tensor_class import unwrap

    a = np.asarray(unwrap(x) if hasattr(x, "_array") else x)
    return float((a != 0).sum() / max(a.size, 1))


def _nm_mask(w: np.ndarray, n: int, m: int) -> np.ndarray:
    """Keep the n largest-magnitude entries in every group of m along the
    input dim (mask_1d algorithm — the reference's default)."""
    orig = w.shape
    flat = w.reshape(-1)
    pad = (-flat.size) % m
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, flat.dtype)])
    groups = np.abs(flat.reshape(-1, m))
    keep = np.argsort(-groups, axis=1)[:, :n]
    mask = np.zeros_like(groups)
    np.put_along_axis(mask, keep, 1.0, axis=1)
    mask = mask.reshape(-1)
    if pad:
        mask = mask[:-pad]
    return mask.reshape(orig)


def _custom_pruner(layer):
    for t, fn in _EXTRA_SUPPORTED:
        if fn is None:
            continue
        if (isinstance(t, type) and isinstance(layer, t)) or \
                (isinstance(t, str) and type(layer).__name__ == t):
            return fn
    return None


def _prunable(model):
    from .. import nn

    extra_types = tuple(t for t, _ in _EXTRA_SUPPORTED
                        if isinstance(t, type))
    extra_names = {t for t, _ in _EXTRA_SUPPORTED if isinstance(t, str)}
    for layer in model.sublayers(include_self=True):
        w = getattr(layer, "weight", None)
        if w is None or not hasattr(w, "_array"):
            continue
        if len(w.shape) < 2:
            continue
        if getattr(w, "name", None) in _EXCLUDED:
            continue
        if not isinstance(layer, (nn.Linear, nn.Conv1D, nn.Conv2D,
                                  nn.Conv3D) + extra_types) and \
                type(layer).__name__ not in extra_names:
            continue
        yield layer, w


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Compute and apply n:m sparsity masks to every supported layer's
    weight. Returns {param_id: mask}."""
    import jax.numpy as jnp

    out = {}
    for layer, w in _prunable(model):
        pruner = _custom_pruner(layer)
        if pruner is not None:
            # registered custom pruning function computes the mask
            mask = np.asarray(pruner(np.asarray(w._array), n, m))
        else:
            mask = _nm_mask(np.asarray(w._array), n, m)
        jmask = jnp.asarray(mask, w._array.dtype)
        w._array = w._array * jmask
        if with_mask:
            import weakref

            _MASKS[id(w)] = (weakref.ref(w), jmask)
        out[id(w)] = jmask
    return out


def decorate(optimizer):
    """Wrap optimizer.step so pruned weights stay pruned through training
    (OptimizerWithSparsityGuarantee parity). Masks are scoped to THIS
    optimizer's parameter list — pruning a second model never leaks into
    another decorated optimizer."""
    own_ids = {id(p) for p in (optimizer._parameter_list or [])}

    class _ASPOptimizer:
        def __init__(self, inner):
            self._inner = inner

        def step(self, *a, **k):
            out = self._inner.step(*a, **k)
            for pid in list(_MASKS):
                ref, mask = _MASKS[pid]
                w = ref()
                if w is None:
                    del _MASKS[pid]     # pruned model was freed
                    continue
                if own_ids and pid not in own_ids:
                    continue
                w._array = w._array * mask
            return out

        def __getattr__(self, name):
            return getattr(self._inner, name)

    return _ASPOptimizer(optimizer)
