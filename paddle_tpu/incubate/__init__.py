"""paddle.incubate parity (python/paddle/incubate/): preview/fused APIs.

The fused functional surface maps onto the Pallas kernels and XLA-fused
compositions this framework already ships (SURVEY.md §2.8 incubate row:
fused transformer/attention/MoE, memory-efficient attention).
"""
from . import nn  # noqa: F401


def softmax_mask_fuse(x, mask, name=None):
    import paddle_tpu as paddle

    return paddle.nn.functional.softmax(x + mask, axis=-1)


def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None,
                    name=None):
    """Old name of geometric.send_u_recv."""
    from ..geometric import send_u_recv

    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)
