"""paddle.incubate parity (python/paddle/incubate/): preview/fused APIs.

The fused functional surface maps onto the Pallas kernels and XLA-fused
compositions this framework already ships (SURVEY.md §2.8 incubate row:
fused transformer/attention/MoE, memory-efficient attention).
"""
from . import nn  # noqa: F401


def softmax_mask_fuse(x, mask, name=None):
    import paddle_tpu as paddle

    return paddle.nn.functional.softmax(x + mask, axis=-1)


def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None,
                    name=None):
    """Old name of geometric.send_u_recv."""
    from ..geometric import send_u_recv

    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def accuracy_check(x, y, fn_name="accuracy_check", rtol=1e-5, atol=1e-8,
                   equal_nan=False):
    """Cross-run tensor comparison (the reference's ``accuracy_check`` op,
    ops.yaml; CINN accuracy_check_pass role): raises with the max
    absolute/relative difference when ``x`` and ``y`` diverge."""
    import numpy as np

    from ..tensor_class import unwrap

    a = np.asarray(unwrap(x))
    b = np.asarray(unwrap(y))
    if a.shape != b.shape:
        raise AssertionError(
            f"[{fn_name}] shape mismatch: {a.shape} vs {b.shape}")
    if np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan):
        return True
    diff = np.abs(a.astype(np.float64) - b.astype(np.float64))
    denom = np.maximum(np.abs(b.astype(np.float64)), 1e-12)
    idx = np.unravel_index(np.argmax(diff), diff.shape)
    raise AssertionError(
        f"[{fn_name}] tensors differ: max_abs_diff={diff.max():.6g} "
        f"max_rel_diff={(diff / denom).max():.6g} at index {tuple(int(i) for i in idx)} "
        f"(rtol={rtol}, atol={atol})")
