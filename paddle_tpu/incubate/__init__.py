"""paddle.incubate parity (python/paddle/incubate/): preview/fused APIs.

The fused functional surface maps onto the Pallas kernels and XLA-fused
compositions this framework already ships (SURVEY.md §2.8 incubate row:
fused transformer/attention/MoE, memory-efficient attention).
"""
from . import nn  # noqa: F401


def softmax_mask_fuse(x, mask, name=None):
    import paddle_tpu as paddle

    return paddle.nn.functional.softmax(x + mask, axis=-1)


def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None,
                    name=None):
    """Old name of geometric.send_u_recv."""
    from ..geometric import send_u_recv

    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def accuracy_check(x, y, fn_name="accuracy_check", rtol=1e-5, atol=1e-8,
                   equal_nan=False):
    """Cross-run tensor comparison (the reference's ``accuracy_check`` op,
    ops.yaml; CINN accuracy_check_pass role): raises with the max
    absolute/relative difference when ``x`` and ``y`` diverge."""
    import numpy as np

    from ..tensor_class import unwrap

    a = np.asarray(unwrap(x))
    b = np.asarray(unwrap(y))
    if a.shape != b.shape:
        raise AssertionError(
            f"[{fn_name}] shape mismatch: {a.shape} vs {b.shape}")
    if np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan):
        return True
    diff = np.abs(a.astype(np.float64) - b.astype(np.float64))
    denom = np.maximum(np.abs(b.astype(np.float64)), 1e-12)
    idx = np.unravel_index(np.argmax(diff), diff.shape)
    raise AssertionError(
        f"[{fn_name}] tensors differ: max_abs_diff={diff.max():.6g} "
        f"max_rel_diff={(diff / denom).max():.6g} at index {tuple(int(i) for i in idx)} "
        f"(rtol={rtol}, atol={atol})")


def softmax_mask_fuse_upper_triangle(x, name=None):
    """incubate.softmax_mask_fuse_upper_triangle (fused_softmax_mask_
    upper_triangle op): causal softmax — upper triangle masked to -inf,
    fused by XLA into one kernel."""
    import jax
    import jax.numpy as jnp

    from ..ops.registry import apply

    def fn(a):
        s = a.shape[-1]
        rows = jnp.arange(a.shape[-2])[:, None]
        cols = jnp.arange(s)[None, :]
        neg = jnp.asarray(-1e9, a.dtype)
        return jax.nn.softmax(jnp.where(cols <= rows, a, neg), -1)

    return apply("softmax_mask_fuse_upper_triangle", fn, x)


def identity_loss(x, reduction="none", name=None):
    """incubate.identity_loss (ops.yaml `identity_loss`)."""
    from ..ops.registry import apply
    import jax.numpy as jnp

    # reference op semantics (ops.yaml identity_loss): 0=sum, 1=mean, 2=none
    red = {"sum": 0, "mean": 1, "none": 2}.get(reduction, reduction)

    def fn(a):
        if red == 0:
            return a.sum()
        if red == 1:
            return a.mean()
        return a

    return apply("identity_loss", fn, x)


# geometric aliases kept under their legacy incubate names
def segment_sum(data, segment_ids, name=None):
    from ..geometric import segment_sum as f

    return f(data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    from ..geometric import segment_mean as f

    return f(data, segment_ids)


def segment_max(data, segment_ids, name=None):
    from ..geometric import segment_max as f

    return f(data, segment_ids)


def segment_min(data, segment_ids, name=None):
    from ..geometric import segment_min as f

    return f(data, segment_ids)


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    from ..geometric import reindex_graph

    return reindex_graph(x, neighbors, count, value_buffer, index_buffer)


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    from ..geometric import sample_neighbors

    return sample_neighbors(row, colptr, input_nodes, sample_size, eids,
                            return_eids, perm_buffer)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """incubate.graph_khop_sampler (graph_khop_sampler op): multi-hop
    sampling. Returns (edge_src, edge_dst, sample_index, reindex_x):
    local-id edges over the union node set, the union's global ids, and
    the input nodes' local ids."""
    import numpy as np

    import jax.numpy as jnp

    from ..geometric import sample_neighbors
    from ..tensor_class import unwrap, wrap

    frontier_global = np.asarray(unwrap(input_nodes)).reshape(-1)
    mapping = {int(v): i for i, v in enumerate(frontier_global)}
    nodes = list(frontier_global)
    e_src, e_dst = [], []
    for size in sample_sizes:
        fr = wrap(jnp.asarray(frontier_global))
        nb, cnt = sample_neighbors(row, colptr, fr, sample_size=size)
        nb_np = np.asarray(unwrap(nb))
        cnt_np = np.asarray(unwrap(cnt))
        dst_global = np.repeat(frontier_global, cnt_np)
        for s, d in zip(nb_np, dst_global):
            si = int(s)
            if si not in mapping:
                mapping[si] = len(nodes)
                nodes.append(si)
            e_src.append(mapping[si])
            e_dst.append(mapping[int(d)])
        frontier_global = np.unique(nb_np)
    edge_src = wrap(jnp.asarray(np.asarray(e_src, np.int64)))
    edge_dst = wrap(jnp.asarray(np.asarray(e_dst, np.int64)))
    sample_index = wrap(jnp.asarray(np.asarray(nodes, np.int64)))
    reindex_x = wrap(jnp.asarray(np.arange(
        np.asarray(unwrap(input_nodes)).size, dtype=np.int64)))
    return edge_src, edge_dst, sample_index, reindex_x


class LookAhead:
    """incubate.LookAhead (incubate/optimizer/lookahead.py): k inner steps,
    then slow weights ← slow + alpha (fast − slow)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._step = 0
        self._slow = None

    def _params(self):
        return self.inner_optimizer._parameter_list or []

    def step(self):
        import jax.numpy as jnp

        from ..tensor_class import unwrap

        # capture the slow weights from the INITIAL parameters (before the
        # first inner step), matching the reference algorithm's phi_0
        if self._slow is None:
            self._slow = [unwrap(p).astype(jnp.float32)
                          for p in self._params()]
        self.inner_optimizer.step()
        self._step += 1
        if self._step % self.k == 0:
            for i, p in enumerate(self._params()):
                fast = unwrap(p).astype(jnp.float32)
                slow = self._slow[i] + self.alpha * (fast - self._slow[i])
                self._slow[i] = slow
                p._array = slow.astype(unwrap(p).dtype)

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def minimize(self, loss):
        loss.backward()
        self.step()
        self.clear_grad()


class ModelAverage:
    """incubate.ModelAverage (incubate/optimizer/modelaverage.py): running
    average of parameters with apply()/restore() swap."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._params = list(parameters) if parameters else []
        self._rate = average_window_rate
        self._min_w = min_average_window
        self._max_w = max_average_window
        self._sums = None
        self._count = 0
        self._backup = None

    def step(self):
        import jax.numpy as jnp

        from ..tensor_class import unwrap

        if self._sums is None:
            self._sums = [jnp.zeros(tuple(p.shape), jnp.float32)
                          for p in self._params]
        # reference semantics: accumulate the sum, cap the window at
        # max(min_average_window, count*rate) by restarting the sum
        window = max(self._min_w,
                     min(self._max_w, int(self._count * self._rate) + 1))
        if self._count and self._count % window == 0 and \
                self._count >= self._max_w:
            self._sums = [jnp.zeros_like(s) for s in self._sums]
            self._count = 0
        self._sums = [s + unwrap(p).astype(jnp.float32)
                      for s, p in zip(self._sums, self._params)]
        self._count += 1

    def apply(self, executor=None, need_restore=True):
        """Swap averaged weights in (context-manager style use is fine)."""
        from ..tensor_class import unwrap

        if self._sums is None or self._count == 0:
            return self
        self._backup = [unwrap(p) for p in self._params]
        for p, s in zip(self._params, self._sums):
            p._array = (s / self._count).astype(p._array.dtype)
        return self

    def restore(self, executor=None):
        if self._backup is not None:
            for p, b in zip(self._params, self._backup):
                p._array = b
            self._backup = None

    def __enter__(self):
        return self.apply()

    def __exit__(self, *exc):
        self.restore()
        return False


class _InferenceNamespace:
    """incubate.jit.inference decorator parity: marks a layer/function for
    inference compilation. TPU-native: routes through jit.to_static (every
    call compiles via XLA — there is no separate TensorRT-style engine)."""

    @staticmethod
    def __call__(function=None, **kwargs):
        import paddle_tpu as paddle

        if function is None:
            return lambda f: paddle.jit.to_static(f)
        return paddle.jit.to_static(function)


inference = _InferenceNamespace()


class _IncubateJit:
    """paddle.incubate.jit namespace (reference path of the inference
    decorator: python/paddle/incubate/jit/inference_decorator.py)."""

    inference = inference


jit = _IncubateJit()


from . import asp  # noqa: E402,F401
from . import autograd  # noqa: E402,F401
from . import optimizer  # noqa: E402,F401


class DistributedFusedLamb:
    """incubate.DistributedFusedLamb (incubate/optimizer/distributed_fused_lamb.py).

    TPU-native collapse: the reference fuses Lamb's per-param ops into flat
    buffers and shards optimizer states across ranks by hand; under
    GSPMD + jit.train_step the SAME fusion happens in XLA (one compiled
    update over all params) and states shard with the ZeRO placement
    rewrites — so this class IS Lamb wired through the functional path,
    with the reference's constructor surface."""

    def __new__(cls, learning_rate=0.001, lamb_weight_decay=0.01,
                beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                grad_clip=None, exclude_from_weight_decay_fn=None,
                clip_after_allreduce=True, is_grad_scaled_by_nranks=True,
                use_master_param_norm=True, gradient_accumulation_steps=1,
                use_master_acc_grad=True, nproc_per_node=None, name=None,
                **kwargs):
        from ..optimizer import Lamb

        return Lamb(learning_rate=learning_rate,
                    lamb_weight_decay=lamb_weight_decay, beta1=beta1,
                    beta2=beta2, epsilon=epsilon, parameters=parameters,
                    grad_clip=grad_clip,
                    exclude_from_weight_decay_fn=exclude_from_weight_decay_fn)
