"""paddle.incubate.autograd parity (python/paddle/incubate/autograd/):
functional vjp/jvp, lazy Jacobian/Hessian objects, forward-mode grad, and
the prim-mode toggles.

TPU-native: jax's composable transforms ARE the prim system — jvp/vjp are
primitive-level autodiff with full fusion, so enable_prim/disable_prim
toggle a flag that records the preference but changes nothing (the prim
path is always on; documented, not silent: get_prim_status reports it).
"""
from __future__ import annotations

__all__ = ["vjp", "jvp", "Jacobian", "Hessian", "enable_prim",
           "disable_prim", "forward_grad", "grad"]

from ...autograd.functional import _LazyMatrix, hessian as _hessian, \
    jacobian as _jacobian
from ...autograd.tape import grad as _tape_grad
from ...tensor_class import Tensor, unwrap, wrap


def _flat_call(func, inputs):
    import jax.numpy as jnp

    def fn(*arrs):
        ten = [wrap(a, stop_gradient=False) for a in arrs]
        out = func(*ten)
        outs = out if isinstance(out, (list, tuple)) else [out]
        return [unwrap(o) for o in outs]

    return fn


def vjp(func, xs, v=None):
    """incubate.autograd.vjp: returns (outputs, vjp_result) for cotangent
    v (defaults to ones)."""
    import jax
    import jax.numpy as jnp

    inputs = xs if isinstance(xs, (list, tuple)) else [xs]
    arrs = [unwrap(x) for x in inputs]
    outs, vjp_fn = jax.vjp(lambda *a: tuple(_flat_call(func, inputs)(*a)),
                           *arrs)
    if v is None:
        cots = tuple(jnp.ones_like(o) for o in outs)
    else:
        vv = v if isinstance(v, (list, tuple)) else [v]
        cots = tuple(unwrap(t) for t in vv)
    grads = vjp_fn(cots)
    outs_w = [wrap(o) for o in outs]
    grads_w = [wrap(g) for g in grads]
    if not isinstance(xs, (list, tuple)):
        grads_w = grads_w[0]
    return (outs_w if len(outs_w) > 1 else outs_w[0]), grads_w


def jvp(func, xs, v=None):
    """incubate.autograd.jvp: forward-mode — (outputs, jvp_result) for
    tangent v (defaults to ones)."""
    import jax
    import jax.numpy as jnp

    inputs = xs if isinstance(xs, (list, tuple)) else [xs]
    arrs = [unwrap(x) for x in inputs]
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in arrs)
    else:
        vv = v if isinstance(v, (list, tuple)) else [v]
        tangents = tuple(unwrap(t) for t in vv)
    outs, tans = jax.jvp(lambda *a: tuple(_flat_call(func, inputs)(*a)),
                         tuple(arrs), tangents)
    outs_w = [wrap(o) for o in outs]
    tans_w = [wrap(t) for t in tans]
    return ((outs_w if len(outs_w) > 1 else outs_w[0]),
            (tans_w if len(tans_w) > 1 else tans_w[0]))


def Jacobian(func, xs, is_batched=False):
    """incubate.autograd.Jacobian: lazily-sliceable d(func)/d(xs)."""
    return _jacobian(func, xs)


def Hessian(func, xs, is_batched=False):
    return _hessian(func, xs)


def forward_grad(outputs, inputs, grad_inputs=None):
    """Forward-mode gradients of recorded outputs wrt inputs. Re-derives
    through jvp of the tape slice via paddle.grad transpose (forward-over-
    reverse), which matches the reference's prim forward_grad results."""
    # d out = J @ v; compute via double-vjp: jvp(f)(v) = vjp(vjp(f))(v)
    outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    import jax.numpy as jnp

    v = grad_inputs
    if v is None:
        v = [wrap(jnp.ones_like(unwrap(i))) for i in ins]
    v = v if isinstance(v, (list, tuple)) else [v]
    # cotangent trick: <J v, w> = <v, J^T w>; using tape grad twice
    dummies = [wrap(jnp.zeros_like(unwrap(o)), stop_gradient=False)
               for o in outs]
    g = _tape_grad(outs, ins, grad_outputs=dummies, retain_graph=True,
                   create_graph=True, allow_unused=True)
    usable = [(gi, vi) for gi, vi in zip(g, v) if gi is not None]
    inner = None
    for gi, vi in usable:
        term = (gi * vi).sum()
        inner = term if inner is None else inner + term
    if inner is None:
        return [None for _ in outs] if isinstance(outputs, (list, tuple)) \
            else None
    res = _tape_grad([inner], dummies, retain_graph=True, allow_unused=True)
    return res if isinstance(outputs, (list, tuple)) else res[0]


def grad(outputs, inputs, grad_outputs=None):
    """incubate.autograd.grad: reverse-mode (prim path) — same contract as
    paddle.grad."""
    return _tape_grad(outputs, inputs, grad_outputs=grad_outputs,
                      retain_graph=True, allow_unused=True)


_PRIM = True  # jax primitives are always the execution substrate


def enable_prim():
    global _PRIM
    _PRIM = True


def disable_prim():
    """The prim lowering cannot actually be turned off (jax IS primitive
    autodiff); the flag records the request for get_prim_status parity."""
    global _PRIM
    _PRIM = False


def get_prim_status() -> bool:
    return _PRIM
