"""paddle.incubate.optimizer parity: LBFGS graduated to paddle.optimizer
in this build; re-exported here under its incubate name."""
from ...optimizer import LBFGS  # noqa: F401

from . import functional  # noqa: F401

__all__ = ["LBFGS"]
