"""paddle.incubate.optimizer.functional parity: functional BFGS/L-BFGS
minimizers (python/paddle/incubate/optimizer/functional/bfgs.py,
lbfgs.py). Pure functions: objective in, (converged, iters, x*, f*, g*)
out — the line-search loop runs host-side on concrete values (both
reference implementations use a while_loop the same way). The line search
is Armijo backtracking bounded by max_line_search_iters (a sufficient-
decrease subset of the reference's strong-Wolfe search).
"""
from __future__ import annotations

__all__ = ["minimize_bfgs", "minimize_lbfgs"]


def _minimize(objective_func, initial_position, history_size, max_iters,
              tolerance_grad, tolerance_change, initial_step_length, dtype,
              max_line_search_iters=50):
    import jax
    import jax.numpy as jnp

    from ....tensor_class import unwrap, wrap

    val_and_grad = jax.value_and_grad(
        lambda x: jnp.asarray(unwrap(objective_func(wrap(x)))).reshape(()))
    x = jnp.asarray(unwrap(initial_position)).astype(dtype)
    f, g = val_and_grad(x)
    s_hist, y_hist = [], []
    n_iter = 0
    converged = False
    for n_iter in range(1, max_iters + 1):
        if float(jnp.abs(g).max()) <= tolerance_grad:
            converged = True
            break
        # two-loop recursion (BFGS keeps full history = same recursion)
        q = g.reshape(-1)
        alphas = []
        for s, y in zip(reversed(s_hist), reversed(y_hist)):
            rho = 1.0 / float(jnp.dot(y, s))
            a = rho * float(jnp.dot(s, q))
            alphas.append((a, rho, s, y))
            q = q - a * y
        if y_hist:
            gamma = float(jnp.dot(s_hist[-1], y_hist[-1])
                          / jnp.maximum(jnp.dot(y_hist[-1], y_hist[-1]),
                                        1e-12))
            q = q * gamma
        for a, rho, s, y in reversed(alphas):
            b = rho * float(jnp.dot(y, q))
            q = q + (a - b) * s
        direction = -q.reshape(x.shape)
        # Armijo backtracking
        t = initial_step_length
        gd = float(jnp.vdot(g, direction))
        accepted = False
        for _ in range(max_line_search_iters):
            x_new = x + t * direction
            f_new, g_new = val_and_grad(x_new)
            if float(f_new) <= float(f) + 1e-4 * t * gd:
                accepted = True
                break
            t *= 0.5
        if not accepted:
            break
        s_vec = (x_new - x).reshape(-1)
        y_vec = (g_new - g).reshape(-1)
        if float(jnp.dot(s_vec, y_vec)) > 1e-10:
            s_hist.append(s_vec)
            y_hist.append(y_vec)
            if history_size and len(s_hist) > history_size:
                s_hist.pop(0)
                y_hist.pop(0)
        if float(jnp.abs(x_new - x).max()) <= tolerance_change:
            x, f, g = x_new, f_new, g_new
            converged = True
            break
        x, f, g = x_new, f_new, g_new
    return (wrap(jnp.asarray(converged)), wrap(jnp.asarray(n_iter)),
            wrap(x), wrap(f), wrap(g))


def minimize_bfgs(objective_func, initial_position, max_iters=50,
                  tolerance_grad=1e-7, tolerance_change=1e-9,
                  initial_inverse_hessian_estimate=None,
                  line_search_fn="strong_wolfe", max_line_search_iters=50,
                  initial_step_length=1.0, dtype="float32", name=None):
    """paddle.incubate.optimizer.functional.minimize_bfgs parity (full
    history — no window cap)."""
    return _minimize(objective_func, initial_position, history_size=0,
                     max_iters=max_iters, tolerance_grad=tolerance_grad,
                     tolerance_change=tolerance_change,
                     initial_step_length=initial_step_length, dtype=dtype,
                     max_line_search_iters=max_line_search_iters)


def minimize_lbfgs(objective_func, initial_position, history_size=100,
                   max_iters=50, tolerance_grad=1e-7, tolerance_change=1e-9,
                   initial_inverse_hessian_estimate=None,
                   line_search_fn="strong_wolfe", max_line_search_iters=50,
                   initial_step_length=1.0, dtype="float32", name=None):
    return _minimize(objective_func, initial_position,
                     history_size=history_size, max_iters=max_iters,
                     tolerance_grad=tolerance_grad,
                     tolerance_change=tolerance_change,
                     initial_step_length=initial_step_length, dtype=dtype,
                     max_line_search_iters=max_line_search_iters)
