"""paddle.incubate.nn.functional parity: fused functional ops.

Each maps to a Pallas kernel (ops/pallas/) or an XLA-fused composition —
the role of paddle/phi/kernels/fusion/ (SURVEY.md §2.2 fused kernels).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...ops.registry import apply
from ...tensor_class import unwrap


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kw):
    """fusion/gpu rms_norm parity → Pallas rms_norm kernel."""
    from ...ops.pallas import fused_norm

    out = apply("fused_rms_norm",
                lambda a, w: fused_norm.rms_norm(a, w, epsilon), x, norm_weight)
    if norm_bias is not None:
        out = out + norm_bias
    return out


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, **kw):
    def fn(a, w, b):
        mean = a.mean(-1, keepdims=True)
        var = ((a - mean) ** 2).mean(-1, keepdims=True)
        return (a - mean) * jax.lax.rsqrt(var + epsilon) * w + b

    return apply("fused_layer_norm", fn, x, norm_weight, norm_bias)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """incubate fused_linear parity: one matmul+bias (XLA fuses the add)."""

    def fn(a, w, *b):
        wv = w.T if transpose_weight else w
        out = a @ wv
        return out + b[0] if b else out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply("fused_linear", fn, *args)


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu"):
    def fn(a, w, b):
        a = a.T if trans_x else a
        w = w.T if trans_y else w
        out = a @ w + b
        return {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
                "none": lambda v: v}[activation](out)

    return apply("fused_linear_activation", fn, x, y, bias)


def fused_bias_act(x, bias=None, act_method="gelu", **kw):
    def fn(a, *b):
        v = a + b[0] if b else a
        return {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
                "swiglu": lambda t: jax.nn.silu(t[..., :t.shape[-1] // 2])
                * t[..., t.shape[-1] // 2:]}[act_method](v)

    args = (x,) + ((bias,) if bias is not None else ())
    return apply("fused_bias_act", fn, *args)


def swiglu(x, y=None, name=None):
    """phi swiglu fusion parity: silu(x) * y (or split-x form)."""

    if y is not None:
        return apply("swiglu", lambda a, b: jax.nn.silu(a) * b, x, y)
    return apply("swiglu",
                 lambda a: jax.nn.silu(a[..., :a.shape[-1] // 2])
                 * a[..., a.shape[-1] // 2:], x)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True):
    """fused_rope fusion parity → Pallas fused_rope kernel. q/k/v are
    [B, S, H, D]. When sin/cos are omitted they are computed from the
    default theta=10000 table (reference fused_rope kernel behaviour);
    position_ids gathers per-token rows from the tables (decode path).
    Only the neox (rotate-half) layout is implemented — the GPT-J
    interleaved style raises."""
    if not use_neox_rotary_style:
        raise NotImplementedError(
            "use_neox_rotary_style=False (interleaved rotary) is not "
            "implemented; the neox rotate-half layout is")
    from ...ops.pallas import fused_norm

    seq = q.shape[1]
    head_dim = q.shape[-1]
    if (sin is None) != (cos is None):
        raise ValueError("pass both sin and cos, or neither")
    if sin is None:
        table_len = seq
        if position_ids is not None:
            pid_arr = unwrap(position_ids)
            if isinstance(pid_arr, jax.core.Tracer):
                raise ValueError(
                    "fused_rotary_position_embedding with position_ids and "
                    "no sin/cos needs a concrete max position under jit — "
                    "pass sin/cos tables explicitly")
            table_len = max(seq, int(jax.device_get(pid_arr).max()) + 1)
        pos = jnp.arange(table_len, dtype=jnp.float32)
        inv = 1.0 / (10000.0 ** (jnp.arange(0, head_dim, 2, jnp.float32)
                                 / head_dim))
        freqs = jnp.outer(pos, inv)
        emb = jnp.concatenate([freqs, freqs], axis=-1)
        cos_t, sin_t = jnp.cos(emb), jnp.sin(emb)
    else:
        cos_t = unwrap(cos).reshape(-1, head_dim)
        sin_t = unwrap(sin).reshape(-1, head_dim)

    def rope(t):
        if t is None:
            return None

        def fn(a, c, s, *pid):
            if pid:
                c = c[pid[0]]  # [B, S, D] per-token gather
                s = s[pid[0]]
                half = a.shape[-1] // 2
                a1, a2 = a[..., :half], a[..., half:]
                cb, sb = c[:, :, None, :], s[:, :, None, :]
                rot = jnp.concatenate([-a2, a1], axis=-1)
                return a * cb + rot * sb
            return fused_norm.fused_rope(a, c[:a.shape[1]], s[:a.shape[1]])

        args = (t, cos_t, sin_t) + ((position_ids,)
                                    if position_ids is not None else ())
        return apply("fused_rope", fn, *args)

    # v passes through: rotary covers q/k only (reference kernel semantics)
    return rope(q), rope(k), v


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                               pre_ln_scale=None, pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None, attn_mask=None,
                               dropout_rate=0.0, attn_dropout_rate=0.0,
                               ln_epsilon=1e-5, training=True, mode='upscale_in_train',
                               ring_id=-1, add_residual=True, num_heads=None,
                               transpose_qkv_wb=False, name=None):
    """fused_attention kernel parity (phi fusion/fused_attention): pre-LN →
    qkv proj → SDPA (flash path when available) → out proj → residual."""
    import paddle_tpu as paddle
    from ...nn.functional.attention import scaled_dot_product_attention

    residual = x
    if pre_layer_norm and pre_ln_scale is not None:
        x = fused_layer_norm(x, pre_ln_scale, pre_ln_bias, pre_ln_epsilon)
    qkvw = unwrap(qkv_weight)
    if transpose_qkv_wb:
        # weight [embed, 3*embed] form
        embed = qkvw.shape[0]
        h = num_heads
        qkv = paddle.matmul(x, qkv_weight)
        if qkv_bias is not None:
            qkv = qkv + qkv_bias
        b, s = qkv.shape[0], qkv.shape[1]
        qkv = qkv.reshape([b, s, 3, h, embed // h])
    else:
        # reference layout: [3, n_heads, head_dim, embed]
        three, h, hd, embed = qkvw.shape
        qkv = apply("qkv_proj",
                    lambda a, w: jnp.einsum("bse,thde->bsthd", a, w),
                    x, qkv_weight)
        if qkv_bias is not None:
            qkv = qkv + qkv_bias.reshape([3, h, hd])
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    out = scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                       dropout_p=attn_dropout_rate,
                                       training=training)
    b, s = out.shape[0], out.shape[1]
    out = out.reshape([b, s, -1])
    out = paddle.matmul(out, linear_weight)
    if linear_bias is not None:
        out = out + linear_bias
    if add_residual:
        out = residual + out
    if not pre_layer_norm and ln_scale is not None:
        out = fused_layer_norm(out, ln_scale, ln_bias, ln_epsilon)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu", ln1_epsilon=1e-5,
                      ln2_epsilon=1e-5, pre_layer_norm=False, training=True,
                      mode='upscale_in_train', ring_id=-1, name=None):
    """fused_feedforward kernel parity."""
    import paddle_tpu as paddle

    residual = x
    if pre_layer_norm and ln1_scale is not None:
        x = fused_layer_norm(x, ln1_scale, ln1_bias, ln1_epsilon)
    out = fused_linear(x, linear1_weight, linear1_bias)
    out = getattr(paddle.nn.functional, activation)(out)
    out = fused_linear(out, linear2_weight, linear2_bias)
    out = residual + out
    if not pre_layer_norm and ln2_scale is not None:
        out = fused_layer_norm(out, ln2_scale, ln2_bias, ln2_epsilon)
    return out


def fused_moe(x, gate_weight, expert_weights1, expert_biases1,
              expert_weights2, expert_biases2, quant_method="None",
              moe_topk=2, norm_topk_prob=True):
    """cutlass fused_moe kernel parity → grouped-GEMM MoE
    (distributed/moe.py GroupedMLP path)."""
    from ...distributed.moe import MoELayer  # surface parity note

    raise NotImplementedError(
        "use paddle_tpu.distributed.moe.MoELayer(GroupedMLP) — the TPU "
        "grouped-GEMM MoE with EP sharding; a stateless functional wrapper "
        "is tracked for a later round")


def memory_efficient_attention(query, key, value, attn_bias=None, p=0.0,
                               scale=None, training=True):
    """incubate.nn.memory_efficient_attention (xformers-style API;
    reference incubate/nn/memory_efficient_attention/). On TPU the
    memory-efficient algorithm IS flash attention — the Pallas splash
    kernel streams KV blocks so the S×S score matrix never materializes;
    the XLA fallback is an SDPA composite. Layout [B, S, H, D]."""
    from ...nn.functional.attention import flash_attention

    if attn_bias is None:
        q = query
        if scale is not None:
            # flash applies 1/sqrt(d) internally; fold a custom scale in
            d = unwrap(query).shape[-1]
            q = query * (scale * (d ** 0.5))
        out, _ = flash_attention(q, key, value, dropout=p, causal=False,
                                 training=training)
        return out

    # biased attention can't ride the bias-free splash kernel: run the
    # SDPA composite with the additive bias (and the same dropout policy)
    from ...framework import random as _random

    drop_key = _random.next_key() if (p > 0.0 and training) else None

    def fn(q, k, v, b):
        d = q.shape[-1]
        s = scale if scale is not None else 1.0 / (d ** 0.5)
        qh = jnp.moveaxis(q, 2, 1)
        kh = jnp.moveaxis(k, 2, 1)
        vh = jnp.moveaxis(v, 2, 1)
        scores = (qh @ jnp.swapaxes(kh, -1, -2)) * s + b
        probs = jax.nn.softmax(scores, -1)
        if drop_key is not None:
            keep = jax.random.bernoulli(drop_key, 1.0 - p, probs.shape)
            probs = probs * keep / (1.0 - p)
        return jnp.moveaxis(probs @ vh, 1, 2)

    return apply("memory_efficient_attention", fn, query, key, value,
                 attn_bias)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """incubate.nn.functional.fused_matmul_bias: matmul+bias in one op
    (XLA fuses the epilogue onto the MXU). Delegates to fused_linear —
    one epilogue implementation to maintain — adding the transpose_x
    handling that fused_linear lacks."""
    if transpose_x:
        from ...ops.manipulation import swapaxes

        x = swapaxes(x, -1, -2)
    return fused_linear(x, y, bias, transpose_weight=transpose_y)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True, mode=
        "upscale_in_train", name=None):
    """Functional face of FusedBiasDropoutResidualLayerNorm:
    layer_norm(residual + dropout(x + bias))."""
    import paddle_tpu as paddle

    h = x if bias is None else x + bias
    h = paddle.nn.functional.dropout(h, dropout_rate, training=training,
                                     mode=mode)
    h = residual + h
    d = h.shape[-1]
    return paddle.nn.functional.layer_norm(h, [d], weight=ln_scale,
                                           bias=ln_bias, epsilon=ln_epsilon)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """incubate.nn.functional.fused_dropout_add: dropout(x) + y."""
    import paddle_tpu as paddle

    return paddle.nn.functional.dropout(x, p, training=training,
                                        mode=mode) + y


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights, qkv_biases,
                            linear_weights, linear_biases, ffn_ln_scales,
                            ffn_ln_biases, ffn1_weights, ffn1_biases,
                            ffn2_weights, ffn2_biases, attn_mask=None,
                            caches=None, epsilon=1e-5, num_heads=None,
                            normalize_before=True, dropout_rate=0.0,
                            training=False, activation="gelu", **kwargs):
    """incubate.nn.functional.fused_multi_transformer: a serving-style
    stack of transformer blocks given flat per-layer weight lists (the
    fused_multi_transformer op's calling convention). Pre-LN or post-LN;
    attn_mask runs the masked SDPA path; incremental KV caches are not
    implemented here (use paddle.Model.generate / generation.py, which
    owns the jitted cache machinery) and raise loudly."""
    import paddle_tpu as paddle

    if caches is not None:
        raise NotImplementedError(
            "fused_multi_transformer: incremental caches are served by "
            "generation.py's jitted static-KV decode; call that path")
    if num_heads is None:
        raise ValueError(
            "fused_multi_transformer: num_heads is required (the flat "
            "[hidden, 3*hidden] qkv layout cannot disambiguate heads)")
    F = paddle.nn.functional
    h = x
    n_layers = len(qkv_weights)
    for i in range(n_layers):
        # attention block
        a = F.layer_norm(h, [h.shape[-1]], weight=ln_scales[i],
                         bias=ln_biases[i], epsilon=epsilon) \
            if normalize_before else h
        qkv = fused_matmul_bias(a, qkv_weights[i], qkv_biases[i])
        B, S, three_hd = unwrap(qkv).shape
        nh = num_heads
        hd = three_hd // (3 * nh)
        qkv5 = qkv.reshape([B, S, 3, nh, hd])
        q, k, v = qkv5[:, :, 0], qkv5[:, :, 1], qkv5[:, :, 2]
        if attn_mask is not None:
            attn = memory_efficient_attention(q, k, v, attn_bias=attn_mask,
                                              p=dropout_rate,
                                              training=training)
        else:
            attn, _ = F.flash_attention(q, k, v, causal=True,
                                        dropout=dropout_rate,
                                        training=training)
        attn = attn.reshape([B, S, nh * hd])
        res = h + fused_matmul_bias(attn, linear_weights[i],
                                    linear_biases[i])
        h = res if normalize_before else F.layer_norm(
            res, [res.shape[-1]], weight=ln_scales[i], bias=ln_biases[i],
            epsilon=epsilon)
        # ffn block
        f = F.layer_norm(h, [h.shape[-1]], weight=ffn_ln_scales[i],
                         bias=ffn_ln_biases[i], epsilon=epsilon) \
            if normalize_before else h
        f = fused_matmul_bias(f, ffn1_weights[i], ffn1_biases[i])
        f = getattr(F, activation)(f)
        res = h + fused_matmul_bias(f, ffn2_weights[i], ffn2_biases[i])
        h = res if normalize_before else F.layer_norm(
            res, [res.shape[-1]], weight=ffn_ln_scales[i],
            bias=ffn_ln_biases[i], epsilon=epsilon)
    return h


def variable_length_memory_efficient_attention(
        query, key, value, seq_lens, kv_seq_lens, mask=None, scale=None,
        causal=False, pre_cache_length=0):
    """incubate.nn.functional.variable_length_memory_efficient_attention:
    per-sample lengths [B] over padded [B, H, S, D] inputs — length masking
    composed with the SDPA/flash path."""
    import paddle_tpu as paddle

    import numpy as np

    from ...tensor_class import Tensor

    q, k, v = unwrap(query), unwrap(key), unwrap(value)
    B, H, S, D = q.shape

    def lens_of(t):
        return unwrap(t) if isinstance(t, Tensor) \
            else jnp.asarray(np.asarray(t))

    kl = lens_of(kv_seq_lens)
    ql = lens_of(seq_lens)
    s = scale if scale is not None else 1.0 / (D ** 0.5)
    scores = (q @ jnp.swapaxes(k, -1, -2)) * s
    neg = jnp.asarray(-1e9, scores.dtype)
    key_ok = jnp.arange(S)[None, :] < kl.reshape(-1, 1)   # [B, S_k]
    scores = jnp.where(key_ok[:, None, None, :], scores, neg)
    if causal:
        tri = jnp.tril(jnp.ones((S, S), bool))
        scores = jnp.where(tri[None, None], scores, neg)
    if mask is not None:
        scores = scores + unwrap(mask)
    out = jax.nn.softmax(scores, -1) @ v
    # padded QUERY rows produce zeros (reference varlen semantics)
    q_ok = (jnp.arange(S)[None, :] < ql.reshape(-1, 1))   # [B, S_q]
    from ...tensor_class import wrap

    return wrap(out * q_ok[:, None, :, None].astype(out.dtype))





def blha_get_max_len(seq_lens_encoder, seq_lens_decoder, batch_size,
                     name=None):
    """incubate blha_get_max_len parity (blha_get_max_len.py:26): the max
    encoder/decoder lengths the block-attention serving step needs for its
    grid sizing. Returns (max_enc_len, max_dec_len) as scalar tensors."""
    from ...tensor_class import unwrap, wrap

    enc = jnp.max(unwrap(seq_lens_encoder))
    dec = jnp.max(unwrap(seq_lens_decoder))
    return wrap(enc.reshape(1)), wrap(dec.reshape(1))


def masked_multihead_attention(x, cache_kv=None, bias=None, src_mask=None,
                               cum_offsets=None, sequence_lengths=None,
                               rotary_tensor=None, beam_cache_offset=None,
                               qkv_out_scale=None, out_shift=None,
                               out_smooth=None, seq_len=1, rotary_emb_dims=0,
                               use_neox_rotary_style=False,
                               compute_dtype="default", out_scale=-1,
                               quant_round_type=1, quant_max_bound=127.0,
                               quant_min_bound=-127.0):
    """incubate masked_multihead_attention parity
    (masked_multihead_attention.py:51 over the CUDA fused decode kernel):
    ONE decode step per row against the [2, B, H, max_len, D] inline
    cache. The core contract — fused qkv input [B, 3*H*D] (+optional
    [3, H, D] bias), per-row write positions, additive src_mask, cache
    updated in place — is implemented; the CUDA-side quant/rotary/beam
    extras raise (the TPU serving path does RoPE in the model and
    quantizes weights, not activations)."""
    from ...tensor_class import unwrap, wrap

    for arg, name_ in ((rotary_tensor, "rotary_tensor"),
                       (beam_cache_offset, "beam_cache_offset"),
                       (qkv_out_scale, "qkv_out_scale"),
                       (out_shift, "out_shift"), (out_smooth, "out_smooth"),
                       (cum_offsets, "cum_offsets")):
        if arg is not None:
            raise NotImplementedError(
                f"masked_multihead_attention: {name_} is a CUDA-kernel "
                "extra; the TPU serving path applies RoPE in the model "
                "and quantizes weights (nn.quant), not activations")
    if out_scale != -1:
        raise NotImplementedError(
            "masked_multihead_attention: activation quant (out_scale) is "
            "not supported; use nn.quant weight-only serving")
    if cache_kv is None:
        raise ValueError("masked_multihead_attention needs cache_kv "
                         "[2, B, H, max_len, D]")
    ck = unwrap(cache_kv)
    _, B, H, T, D = ck.shape
    qkv = unwrap(x).reshape(B, 3, H, D)
    if bias is not None:
        qkv = qkv + unwrap(bias)[None]
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]          # [B, H, D]
    if sequence_lengths is not None:
        pos = unwrap(sequence_lengths).reshape(B).astype(jnp.int32)
    else:
        pos = jnp.zeros((B,), jnp.int32)
    rows = jnp.arange(B)
    k_cache = ck[0].at[rows, :, pos].set(k.astype(ck.dtype))
    v_cache = ck[1].at[rows, :, pos].set(v.astype(ck.dtype))
    t_idx = jnp.arange(T)
    valid = t_idx[None, :] <= pos[:, None]              # [B, T]
    scores = jnp.einsum("bhd,bhtd->bht", q.astype(jnp.float32),
                        k_cache.astype(jnp.float32)) / jnp.sqrt(
                            jnp.asarray(D, jnp.float32))
    scores = jnp.where(valid[:, None, :], scores, -jnp.inf)
    if src_mask is not None:
        sm = unwrap(src_mask).astype(jnp.float32)
        scores = scores + sm.reshape(B, 1, -1)[:, :, :T]
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bht,bhtd->bhd", probs,
                     v_cache.astype(jnp.float32)).astype(unwrap(x).dtype)
    new_cache = jnp.stack([k_cache, v_cache])
    from ...tensor_class import Tensor as _T

    if isinstance(cache_kv, _T):
        # honor the reference's in-place mutation contract: callers that
        # pass the same cache Tensor every step (discarding the return)
        # must see the update
        cache_kv._array = new_cache
        return wrap(out.reshape(B, H * D)), cache_kv
    return wrap(out.reshape(B, H * D)), wrap(new_cache)


def block_multihead_attention(*args, **kwargs):
    """The reference's CUDA paged serving mega-kernel
    (block_multihead_attention.py:33 over
    block_multi_head_attention_kernel.cu). Its role — mixed prefill/
    decode over block tables inside a continuous-batching server — is
    filled TPU-natively by ``paddle_tpu.serving.ContinuousBatchEngine``
    (admission scatter + one fused step) over
    ``generation.paged_cached_attention`` / ``ops.pallas.append_attention``;
    the 20-tensor CUDA calling convention itself is not reproduced."""
    raise NotImplementedError(
        "block_multihead_attention's serving role is provided by "
        "paddle_tpu.serving.ContinuousBatchEngine (paged KV + continuous "
        "batching) and generation.paged_cached_attention; drive those "
        "instead of the CUDA kernel's calling convention")
