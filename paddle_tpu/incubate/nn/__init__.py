"""paddle.incubate.nn parity: fused layers + functional."""
from . import functional  # noqa: F401
from .layers import (  # noqa: F401
    FusedFeedForward, FusedLinear, FusedMultiHeadAttention,
    FusedDropoutAdd, FusedBiasDropoutResidualLayerNorm,
    FusedTransformerEncoderLayer, FusedMultiTransformer)
