"""Fused layer classes (incubate/nn/layer/fused_transformer.py parity)."""
from __future__ import annotations

import numpy as np

from ... import nn
from ...nn.layer import Layer
from . import functional as F


class FusedLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self.transpose_weight = transpose_weight
        shape = ([out_features, in_features] if transpose_weight
                 else [in_features, out_features])
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = (self.create_parameter([out_features], attr=bias_attr,
                                           is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, x):
        return F.fused_linear(x, self.weight, self.bias,
                              self.transpose_weight)


class FusedMultiHeadAttention(Layer):
    """fused_transformer.py FusedMultiHeadAttention parity."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False, qkv_weight_attr=None,
                 qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None,
                 pre_ln_bias_attr=None, ln_scale_attr=None, ln_bias_attr=None,
                 epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.epsilon = epsilon
        self.qkv_weight = self.create_parameter(
            [3, num_heads, self.head_dim, embed_dim], attr=qkv_weight_attr)
        self.qkv_bias = self.create_parameter(
            [3, num_heads, self.head_dim], attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter(
            [embed_dim, embed_dim], attr=linear_weight_attr)
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=linear_bias_attr, is_bias=True)
        from ...nn.initializer_core import Constant

        self.pre_ln_scale = self.create_parameter(
            [embed_dim], default_initializer=Constant(1.0))
        self.pre_ln_bias = self.create_parameter(
            [embed_dim], is_bias=True, default_initializer=Constant(0.0))
        self.ln_scale = self.create_parameter(
            [embed_dim], default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter(
            [embed_dim], is_bias=True, default_initializer=Constant(0.0))

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        return F.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            pre_ln_epsilon=self.epsilon, qkv_bias=self.qkv_bias,
            linear_bias=self.linear_bias, attn_mask=attn_mask,
            dropout_rate=self.dropout_rate,
            attn_dropout_rate=self.attn_dropout_rate,
            ln_epsilon=self.epsilon, training=self.training,
            num_heads=self.num_heads)


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        from ...nn.initializer_core import Constant

        self.normalize_before = normalize_before
        self.activation = activation
        self.epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], attr=linear1_weight_attr)
        self.linear1_bias = self.create_parameter(
            [dim_feedforward], attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], attr=linear2_weight_attr)
        self.linear2_bias = self.create_parameter(
            [d_model], attr=linear2_bias_attr, is_bias=True)
        self.ln1_scale = self.create_parameter(
            [d_model], default_initializer=Constant(1.0))
        self.ln1_bias = self.create_parameter(
            [d_model], is_bias=True, default_initializer=Constant(0.0))
        self.ln2_scale = self.create_parameter(
            [d_model], default_initializer=Constant(1.0))
        self.ln2_bias = self.create_parameter(
            [d_model], is_bias=True, default_initializer=Constant(0.0))

    def forward(self, src, cache=None):
        return F.fused_feedforward(
            src, self.linear1_weight, self.linear2_weight, self.linear1_bias,
            self.linear2_bias, self.ln1_scale, self.ln1_bias, self.ln2_scale,
            self.ln2_bias, activation=self.activation,
            ln1_epsilon=self.epsilon, ln2_epsilon=self.epsilon,
            pre_layer_norm=self.normalize_before, training=self.training)


class FusedDropoutAdd(Layer):
    """incubate.nn.FusedDropoutAdd (fused_dropout_add op): dropout(x) + y
    in one fused pass (XLA fuses the mask-scale-add chain)."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        out = nn.functional.dropout(x, self.p, training=self.training,
                                    mode=self.mode)
        return out + y

    def extra_repr(self):
        return f"p={self.p}, mode={self.mode}"


class FusedBiasDropoutResidualLayerNorm(Layer):
    """incubate.nn.FusedBiasDropoutResidualLayerNorm
    (fused_bias_dropout_residual_layer_norm op):
    layer_norm(residual + dropout(x + bias))."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        from ...nn.initializer_core import Constant

        self.embed_dim = embed_dim
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter(
            [embed_dim], is_bias=True, default_initializer=Constant(0.0))

    def forward(self, x, residual):
        h = nn.functional.dropout(x + self.linear_bias, self.dropout_rate,
                                  training=self.training)
        return nn.functional.layer_norm(
            residual + h, [self.embed_dim], weight=self.ln_scale,
            bias=self.ln_bias, epsilon=self.epsilon)


class FusedTransformerEncoderLayer(Layer):
    """incubate.nn.FusedTransformerEncoderLayer: fused attention + FFN
    blocks (fused_transformer.py)."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate if attn_dropout_rate
            is not None else dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation,
            act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)


class FusedMultiTransformer(Layer):
    """incubate.nn.FusedMultiTransformer (fused_multi_transformer op): a
    whole stack of fused pre-LN transformer blocks — the serving-path
    block used by the reference's inference engine."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 ln_scale_attrs=None, ln_bias_attrs=None, num_layers=-1,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        if num_layers <= 0:
            num_layers = 1
        self.layers = nn.LayerList([
            FusedTransformerEncoderLayer(
                embed_dim, num_heads, dim_feedforward,
                dropout_rate=dropout_rate, activation=activation,
                normalize_before=normalize_before)
            for _ in range(num_layers)])

    def forward(self, src, attn_mask=None, caches=None, **kwargs):
        out = src
        for layer in self.layers:
            out = layer(out, src_mask=attn_mask)
        return out
