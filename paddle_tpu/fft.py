"""paddle.fft parity (python/paddle/fft.py) over jnp.fft — every public
transform in the reference's surface."""
from __future__ import annotations

import jax.numpy as jnp

from .ops.registry import apply

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
           "fft2", "ifft2", "rfft2", "irfft2",
           "fftn", "ifftn", "rfftn", "irfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _wrap1(name, fn):
    def op(x, n=None, axis=-1, norm="backward", name_arg=None):
        return apply(name, lambda a: fn(a, n=n, axis=axis, norm=norm), x)

    op.__name__ = name
    return op


def _wrapn(name, fn, saxes=(-2, -1)):
    def op(x, s=None, axes=saxes, norm="backward", name_arg=None):
        return apply(name, lambda a: fn(a, s=s, axes=axes, norm=norm), x)

    op.__name__ = name
    return op


fft = _wrap1("fft", jnp.fft.fft)
ifft = _wrap1("ifft", jnp.fft.ifft)
rfft = _wrap1("rfft", jnp.fft.rfft)
irfft = _wrap1("irfft", jnp.fft.irfft)
hfft = _wrap1("hfft", jnp.fft.hfft)
ihfft = _wrap1("ihfft", jnp.fft.ihfft)
fft2 = _wrapn("fft2", jnp.fft.fft2)
ifft2 = _wrapn("ifft2", jnp.fft.ifft2)
rfft2 = _wrapn("rfft2", jnp.fft.rfft2)
irfft2 = _wrapn("irfft2", jnp.fft.irfft2)
fftn = _wrapn("fftn", jnp.fft.fftn, None)
ifftn = _wrapn("ifftn", jnp.fft.ifftn, None)
rfftn = _wrapn("rfftn", jnp.fft.rfftn, None)
irfftn = _wrapn("irfftn", jnp.fft.irfftn, None)


def fftfreq(n, d=1.0, dtype=None, name=None):
    import paddle_tpu as paddle

    return paddle.to_tensor(jnp.fft.fftfreq(n, d).astype(dtype or "float32"))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    import paddle_tpu as paddle

    return paddle.to_tensor(jnp.fft.rfftfreq(n, d).astype(dtype or "float32"))


def fftshift(x, axes=None, name=None):
    return apply("fftshift", lambda a: jnp.fft.fftshift(a, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    return apply("ifftshift", lambda a: jnp.fft.ifftshift(a, axes=axes), x)
