"""paddle.fft parity (python/paddle/fft.py) over jnp.fft — every public
transform in the reference's surface."""
from __future__ import annotations

import jax.numpy as jnp

from .ops.registry import apply

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
           "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
           "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _wrap1(name, fn):
    def op(x, n=None, axis=-1, norm="backward", name_arg=None):
        return apply(name, lambda a: fn(a, n=n, axis=axis, norm=norm), x)

    op.__name__ = name
    return op


def _wrapn(name, fn, saxes=(-2, -1)):
    def op(x, s=None, axes=saxes, norm="backward", name_arg=None):
        return apply(name, lambda a: fn(a, s=s, axes=axes, norm=norm), x)

    op.__name__ = name
    return op


fft = _wrap1("fft", jnp.fft.fft)
ifft = _wrap1("ifft", jnp.fft.ifft)
rfft = _wrap1("rfft", jnp.fft.rfft)
irfft = _wrap1("irfft", jnp.fft.irfft)
hfft = _wrap1("hfft", jnp.fft.hfft)
ihfft = _wrap1("ihfft", jnp.fft.ihfft)
fft2 = _wrapn("fft2", jnp.fft.fft2)
ifft2 = _wrapn("ifft2", jnp.fft.ifft2)
rfft2 = _wrapn("rfft2", jnp.fft.rfft2)
irfft2 = _wrapn("irfft2", jnp.fft.irfft2)
fftn = _wrapn("fftn", jnp.fft.fftn, None)
ifftn = _wrapn("ifftn", jnp.fft.ifftn, None)
rfftn = _wrapn("rfftn", jnp.fft.rfftn, None)
irfftn = _wrapn("irfftn", jnp.fft.irfftn, None)


def fftfreq(n, d=1.0, dtype=None, name=None):
    import paddle_tpu as paddle

    return paddle.to_tensor(jnp.fft.fftfreq(n, d).astype(dtype or "float32"))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    import paddle_tpu as paddle

    return paddle.to_tensor(jnp.fft.rfftfreq(n, d).astype(dtype or "float32"))


def fftshift(x, axes=None, name=None):
    return apply("fftshift", lambda a: jnp.fft.fftshift(a, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    return apply("ifftshift", lambda a: jnp.fft.ifftshift(a, axes=axes), x)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    """paddle.fft.hfft2 (fft.py hfft2 = fftn_c2r over 2 axes)."""
    return hfftn(x, s, axes, norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s, axes, norm)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    """paddle.fft.hfftn (fft.py fftn_c2r forward=True): full complex FFT
    over the leading axes, Hermitian c2r transform over the last axis."""
    def fn(a):
        if axes is not None:
            ax = tuple(axes)
        elif s is not None:
            ax = tuple(range(-len(s), 0))  # s pairs with the LAST len(s) axes
        else:
            ax = tuple(range(-a.ndim, 0))
        lead, last = ax[:-1], ax[-1]
        out = a
        if lead:
            s_lead = None if s is None else list(s[:-1])
            out = jnp.fft.fftn(out, s=s_lead, axes=lead, norm=norm)
        n_last = None if s is None else s[-1]
        return jnp.fft.hfft(out, n=n_last, axis=last, norm=norm)

    return apply("hfftn", fn, x)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    """paddle.fft.ihfftn (fftn_r2c forward=False): inverse of hfftn —
    ihfft over the last axis, then inverse complex FFT over the rest."""
    def fn(a):
        if axes is not None:
            ax = tuple(axes)
        elif s is not None:
            ax = tuple(range(-len(s), 0))
        else:
            ax = tuple(range(-a.ndim, 0))
        lead, last = ax[:-1], ax[-1]
        n_last = None if s is None else s[-1]
        out = jnp.fft.ihfft(a, n=n_last, axis=last, norm=norm)
        if lead:
            s_lead = None if s is None else list(s[:-1])
            out = jnp.fft.ifftn(out, s=s_lead, axes=lead, norm=norm)
        return out

    return apply("ihfftn", fn, x)
