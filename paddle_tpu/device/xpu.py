"""paddle.device.xpu parity: XPU-named probe served by the TPU runtime."""


def synchronize(device=None):
    from ..framework.device import synchronize as _s

    return _s()
