"""paddle.device as an importable package (python/paddle/device/__init__.py).

The implementation lives in framework/device.py; this package re-exports it
so both access styles work: ``paddle.device.X`` and
``import paddle.device.cuda``.
"""
from ..framework.device import *  # noqa: F401,F403
from ..framework.device import (  # noqa: F401  (names not caught by *)
    Stream, Event, current_stream, set_stream, stream_guard, synchronize,
    device_count, memory_allocated, max_memory_allocated, memory_reserved,
    max_memory_reserved, empty_cache, get_cudnn_version, XPUPlace, IPUPlace,
    is_compiled_with_ipu, is_compiled_with_rocm, is_compiled_with_cinn,
    is_compiled_with_distribute, is_compiled_with_custom_device,
    get_all_device_type, get_all_custom_device_type, get_available_device,
    get_available_custom_device, set_device, get_device,
    is_compiled_with_cuda, is_compiled_with_tpu, CPUPlace, TPUPlace,
    CUDAPlace, CUDAPinnedPlace)
from . import cuda  # noqa: F401
from . import xpu  # noqa: F401
