"""paddle.device.cuda as an importable module — delegates to the shared
accelerator namespace (framework/device.py::_CudaNamespace)."""
from ..framework.device import cuda as _ns

Stream = _ns.Stream
Event = _ns.Event
current_stream = _ns.current_stream
synchronize = _ns.synchronize
device_count = _ns.device_count
empty_cache = _ns.empty_cache
stream_guard = _ns.stream_guard
memory_allocated = _ns.memory_allocated
max_memory_allocated = _ns.max_memory_allocated
memory_reserved = _ns.memory_reserved
max_memory_reserved = _ns.max_memory_reserved
get_device_properties = _ns.get_device_properties
get_device_name = _ns.get_device_name
get_device_capability = _ns.get_device_capability
