"""On-demand native build: g++ → cached shared library → ctypes.

Parity note: the reference ships compiled C++ in its wheel; this build
compiles its (small) native core at first use — same pattern as
paddle.utils.cpp_extension's JIT path (python/paddle/utils/cpp_extension/).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_SRC_DIR = os.path.join(os.path.dirname(__file__), "csrc")
_SOURCES = ["tcp_store.cpp", "shm_queue.cpp"]
# -lrt: shm_open/shm_unlink live in librt before glibc 2.34 (the symbol
# is in libc proper afterwards, where the flag is a harmless no-op) —
# without it the .so builds fine and then fails at dlopen with
# "undefined symbol: shm_open" on older glibc
_LINK_FLAGS = ["-lrt"]
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None


def _cache_dir() -> str:
    d = os.environ.get("PADDLE_TPU_CACHE",
                       os.path.expanduser("~/.cache/paddle_tpu"))
    os.makedirs(d, exist_ok=True)
    return d


def _src_digest() -> str:
    h = hashlib.sha256()
    for s in _SOURCES:
        with open(os.path.join(_SRC_DIR, s), "rb") as f:
            h.update(f.read())
    # link flags are part of the identity: a cached .so built WITHOUT
    # -lrt would otherwise shadow the fixed build forever
    h.update(" ".join(_LINK_FLAGS).encode())
    return h.hexdigest()[:16]


def build_native(verbose: bool = False) -> str:
    """Compile the native core if needed; returns the .so path."""
    so = os.path.join(_cache_dir(), f"libpaddle_tpu_core_{_src_digest()}.so")
    if os.path.exists(so):
        return so
    srcs = [os.path.join(_SRC_DIR, s) for s in _SOURCES]
    tmp = so + f".build.{os.getpid()}"
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           "-o", tmp, *srcs, *_LINK_FLAGS]
    try:
        subprocess.run(cmd, check=True, capture_output=not verbose)
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        raise RuntimeError(
            f"native core build failed ({' '.join(cmd)}): {e}") from e
    os.replace(tmp, so)
    return so


def load_native() -> ctypes.CDLL:
    """Build (if needed) and load the native core library."""
    global _LIB
    with _LOCK:
        if _LIB is None:
            lib = ctypes.CDLL(build_native())  # pdlint: disable=thread-blocking-under-lock -- deliberate: the one-time native cc build runs under the load lock so concurrent importers wait for ONE compile instead of racing N
            # TCP store
            lib.pd_store_server_start.restype = ctypes.c_void_p
            lib.pd_store_server_start.argtypes = [ctypes.c_int]
            lib.pd_store_server_port.restype = ctypes.c_int
            lib.pd_store_server_port.argtypes = [ctypes.c_void_p]
            lib.pd_store_server_stop.argtypes = [ctypes.c_void_p]
            lib.pd_store_client_connect.restype = ctypes.c_void_p
            lib.pd_store_client_connect.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_double]
            lib.pd_store_client_set.restype = ctypes.c_int
            lib.pd_store_client_set.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
                ctypes.c_char_p, ctypes.c_uint32]
            lib.pd_store_client_get.restype = ctypes.c_int
            lib.pd_store_client_get.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
                ctypes.POINTER(ctypes.c_uint32), ctypes.c_double]
            lib.pd_store_client_add.restype = ctypes.c_longlong
            lib.pd_store_client_add.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
                ctypes.c_longlong]
            lib.pd_store_client_del.restype = ctypes.c_int
            lib.pd_store_client_del.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
            lib.pd_store_client_close.argtypes = [ctypes.c_void_p]
            lib.pd_store_free.argtypes = [ctypes.POINTER(ctypes.c_char)]
            # shm queue
            lib.pd_shmq_create.restype = ctypes.c_void_p
            lib.pd_shmq_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
            lib.pd_shmq_open.restype = ctypes.c_void_p
            lib.pd_shmq_open.argtypes = [ctypes.c_char_p]
            lib.pd_shmq_push.restype = ctypes.c_int
            lib.pd_shmq_push.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
                ctypes.c_double]
            lib.pd_shmq_pop.restype = ctypes.c_int64
            lib.pd_shmq_pop.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
                ctypes.c_double]
            lib.pd_shmq_count.restype = ctypes.c_uint64
            lib.pd_shmq_count.argtypes = [ctypes.c_void_p]
            lib.pd_shmq_close_writers.argtypes = [ctypes.c_void_p]
            lib.pd_shmq_free.argtypes = [ctypes.POINTER(ctypes.c_char)]
            lib.pd_shmq_close.argtypes = [ctypes.c_void_p]
            _LIB = lib
    return _LIB
