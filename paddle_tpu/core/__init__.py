"""Native runtime core (C++), loaded via ctypes.

The reference's runtime around the compute path is C++ (SURVEY.md §2.1/2.5:
store/rendezvous tcp_store.cc, dataloader shm transport); this package
holds the TPU build's C++ equivalents, compiled on demand with the
in-image g++ and cached under ~/.cache/paddle_tpu (the role of the
reference's prebuilt .so in the wheel).
"""
from .build import load_native  # noqa: F401
