// TCP key-value store for rank rendezvous.
//
// Reference parity: paddle/phi/core/distributed/store/tcp_store.{h,cc}
// (MasterDaemon + TCPStore client: SET/GET/ADD/WAIT/CHECK commands over a
// length-prefixed socket protocol) — re-designed, not translated: one
// poll()-driven daemon thread, a blocking-with-timeout client, and a C ABI
// consumed from Python via ctypes (the reference binds through pybind).
//
// Wire format (little-endian):
//   request : u8 op | u32 klen | key | (SET: u32 vlen | val) (ADD: i64)
//   reply   : GET/WAIT -> u8 found [| u32 vlen | val]
//             SET      -> u8 ok
//             ADD      -> i64 new_value
#include <arpa/inet.h>
#include <errno.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

enum Op : uint8_t { OP_SET = 1, OP_GET = 2, OP_ADD = 3, OP_DEL = 4 };

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) {
      if (r < 0 && (errno == EINTR)) continue;
      // EAGAIN/EWOULDBLOCK = SO_RCVTIMEO expired: treat as failure so a
      // stalled peer can't block the caller forever
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

void set_op_timeout(int fd, double seconds) {
  timeval tv{};
  tv.tv_sec = static_cast<long>(seconds);
  tv.tv_usec = static_cast<long>((seconds - tv.tv_sec) * 1e6);
  ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

class Server {
 public:
  explicit Server(int port) : port_(port) {}

  bool start() {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return false;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0)
      return false;
    if (::listen(listen_fd_, 128) < 0) return false;
    if (port_ == 0) {  // ephemeral: report the real port
      socklen_t len = sizeof(addr);
      ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
      port_ = ntohs(addr.sin_port);
    }
    running_.store(true);
    thread_ = std::thread([this] { loop(); });
    return true;
  }

  void stop() {
    running_.store(false);
    if (listen_fd_ >= 0) ::shutdown(listen_fd_, SHUT_RDWR);
    if (thread_.joinable()) thread_.join();
    if (listen_fd_ >= 0) ::close(listen_fd_);
    for (int fd : conns_) ::close(fd);
    conns_.clear();
  }

  int port() const { return port_; }

  ~Server() { stop(); }

 private:
  void loop() {
    while (running_.load()) {
      std::vector<pollfd> fds;
      fds.push_back({listen_fd_, POLLIN, 0});
      for (int fd : conns_) fds.push_back({fd, POLLIN, 0});
      int rc = ::poll(fds.data(), fds.size(), 100 /*ms*/);
      if (rc <= 0) continue;
      std::vector<int> alive;
      for (size_t i = 1; i < fds.size(); i++) {
        int fd = fds[i].fd;
        if (fds[i].revents & (POLLERR | POLLHUP)) {
          ::close(fd);
          continue;
        }
        if (fds[i].revents & POLLIN) {
          if (!handle(fd)) {
            ::close(fd);
            continue;
          }
        }
        alive.push_back(fd);
      }
      if (fds[0].revents & POLLIN) {
        int conn = ::accept(listen_fd_, nullptr, nullptr);
        if (conn >= 0) {
          int one = 1;
          ::setsockopt(conn, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          // bound per-request reads so one stalled/partial-writing peer
          // cannot wedge the single daemon thread (ADVICE.md round 1)
          set_op_timeout(conn, 30.0);
          alive.push_back(conn);
        }
      }
      conns_ = std::move(alive);
    }
  }

  bool handle(int fd) {
    uint8_t op;
    uint32_t klen;
    if (!read_full(fd, &op, 1) || !read_full(fd, &klen, 4)) return false;
    if (klen > (1u << 20)) return false;
    std::string key(klen, '\0');
    if (!read_full(fd, key.data(), klen)) return false;
    switch (op) {
      case OP_SET: {
        uint32_t vlen;
        if (!read_full(fd, &vlen, 4)) return false;
        if (vlen > (1u << 30)) return false;
        std::string val(vlen, '\0');
        if (!read_full(fd, val.data(), vlen)) return false;
        {
          std::lock_guard<std::mutex> g(mu_);
          kv_[key] = std::move(val);
        }
        uint8_t ok = 1;
        return write_full(fd, &ok, 1);
      }
      case OP_GET: {
        std::string val;
        uint8_t found = 0;
        {
          std::lock_guard<std::mutex> g(mu_);
          auto it = kv_.find(key);
          if (it != kv_.end()) {
            found = 1;
            val = it->second;
          }
        }
        if (!write_full(fd, &found, 1)) return false;
        if (found) {
          uint32_t vlen = static_cast<uint32_t>(val.size());
          if (!write_full(fd, &vlen, 4)) return false;
          if (!write_full(fd, val.data(), vlen)) return false;
        }
        return true;
      }
      case OP_ADD: {
        int64_t delta;
        if (!read_full(fd, &delta, 8)) return false;
        int64_t nv;
        {
          std::lock_guard<std::mutex> g(mu_);
          int64_t cur = 0;
          auto it = kv_.find(key);
          if (it != kv_.end() && it->second.size() == 8)
            memcpy(&cur, it->second.data(), 8);
          nv = cur + delta;
          std::string val(8, '\0');
          memcpy(val.data(), &nv, 8);
          kv_[key] = std::move(val);
        }
        return write_full(fd, &nv, 8);
      }
      case OP_DEL: {
        {
          std::lock_guard<std::mutex> g(mu_);
          kv_.erase(key);
        }
        uint8_t ok = 1;
        return write_full(fd, &ok, 1);
      }
      default:
        return false;
    }
  }

  int port_;
  int listen_fd_ = -1;
  std::thread thread_;
  std::atomic<bool> running_{false};
  std::vector<int> conns_;
  std::mutex mu_;
  std::map<std::string, std::string> kv_;
};

class Client {
 public:
  bool connect_to(const char* host, int port, double timeout_s) {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::duration<double>(timeout_s);
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    char portstr[16];
    snprintf(portstr, sizeof(portstr), "%d", port);
    if (::getaddrinfo(host, portstr, &hints, &res) != 0 || !res) return false;
    // retry until the daemon is up (reference tcp_utils retry loop)
    while (std::chrono::steady_clock::now() < deadline) {
      fd_ = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
      if (fd_ >= 0 &&
          ::connect(fd_, res->ai_addr, res->ai_addrlen) == 0) {
        int one = 1;
        ::setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        // honor the Python-level timeout on every socket op, not just
        // connect: a dead daemon must surface as an error, not a hang
        default_timeout_ = timeout_s > 0 ? timeout_s : 30.0;
        set_op_timeout(fd_, default_timeout_);
        ::freeaddrinfo(res);
        return true;
      }
      if (fd_ >= 0) ::close(fd_);
      fd_ = -1;
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
    }
    ::freeaddrinfo(res);
    return false;
  }

  bool set(const char* key, uint32_t klen, const char* val, uint32_t vlen) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t op = OP_SET;
    if (!write_full(fd_, &op, 1) || !write_full(fd_, &klen, 4) ||
        !write_full(fd_, key, klen) || !write_full(fd_, &vlen, 4) ||
        !write_full(fd_, val, vlen))
      return false;
    uint8_t ok;
    return read_full(fd_, &ok, 1) && ok == 1;
  }

  // polls until the key exists or timeout; *out is malloc'd
  int get(const char* key, uint32_t klen, char** out, uint32_t* out_len,
          double timeout_s) {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::duration<double>(timeout_s);
    // honor the per-call timeout even against a STALLED (not dead) daemon:
    // bound each blocking recv by the call deadline, not the ctor default
    if (timeout_s > 0 && timeout_s < default_timeout_)
      set_op_timeout(fd_, timeout_s);
    struct Restore {
      Client* c;
      ~Restore() { set_op_timeout(c->fd_, c->default_timeout_); }
    } restore{this};
    while (true) {
      {
        std::lock_guard<std::mutex> g(mu_);
        uint8_t op = OP_GET;
        if (!write_full(fd_, &op, 1) || !write_full(fd_, &klen, 4) ||
            !write_full(fd_, key, klen))
          return -1;
        uint8_t found;
        if (!read_full(fd_, &found, 1)) return -1;
        if (found) {
          uint32_t vlen;
          if (!read_full(fd_, &vlen, 4)) return -1;
          char* buf = static_cast<char*>(malloc(vlen ? vlen : 1));
          if (!read_full(fd_, buf, vlen)) {
            free(buf);
            return -1;
          }
          *out = buf;
          *out_len = vlen;
          return 0;
        }
      }
      if (std::chrono::steady_clock::now() >= deadline) return 1;  // timeout
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
    }
  }

  int64_t add(const char* key, uint32_t klen, int64_t delta) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t op = OP_ADD;
    if (!write_full(fd_, &op, 1) || !write_full(fd_, &klen, 4) ||
        !write_full(fd_, key, klen) || !write_full(fd_, &delta, 8))
      return INT64_MIN;
    int64_t nv;
    if (!read_full(fd_, &nv, 8)) return INT64_MIN;
    return nv;
  }

  bool del(const char* key, uint32_t klen) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t op = OP_DEL;
    if (!write_full(fd_, &op, 1) || !write_full(fd_, &klen, 4) ||
        !write_full(fd_, key, klen))
      return false;
    uint8_t ok;
    return read_full(fd_, &ok, 1) && ok == 1;
  }

  ~Client() {
    if (fd_ >= 0) ::close(fd_);
  }

 private:
  int fd_ = -1;
  double default_timeout_ = 30.0;
  std::mutex mu_;  // one request in flight per client
};

}  // namespace

extern "C" {

void* pd_store_server_start(int port) {
  auto* s = new Server(port);
  if (!s->start()) {
    delete s;
    return nullptr;
  }
  return s;
}

int pd_store_server_port(void* h) { return static_cast<Server*>(h)->port(); }

void pd_store_server_stop(void* h) { delete static_cast<Server*>(h); }

void* pd_store_client_connect(const char* host, int port, double timeout_s) {
  auto* c = new Client();
  if (!c->connect_to(host, port, timeout_s)) {
    delete c;
    return nullptr;
  }
  return c;
}

int pd_store_client_set(void* h, const char* key, uint32_t klen,
                        const char* val, uint32_t vlen) {
  return static_cast<Client*>(h)->set(key, klen, val, vlen) ? 0 : -1;
}

int pd_store_client_get(void* h, const char* key, uint32_t klen, char** out,
                        uint32_t* out_len, double timeout_s) {
  return static_cast<Client*>(h)->get(key, klen, out, out_len, timeout_s);
}

long long pd_store_client_add(void* h, const char* key, uint32_t klen,
                              long long delta) {
  return static_cast<Client*>(h)->add(key, klen, delta);
}

int pd_store_client_del(void* h, const char* key, uint32_t klen) {
  return static_cast<Client*>(h)->del(key, klen) ? 0 : -1;
}

void pd_store_client_close(void* h) { delete static_cast<Client*>(h); }

void pd_store_free(char* p) { free(p); }

}  // extern "C"
