// Shared-memory ring queue for DataLoader worker → main-process transport.
//
// Reference parity: the reference DataLoader's shared-memory path
// (python/paddle/io/dataloader/worker.py + paddle/fluid's memory-mapped
// tensor transport): worker processes serialize batches into shm instead
// of piping pickles through multiprocessing queues. Re-designed as a
// single contiguous POSIX shm ring with process-shared mutex/condvars and
// a C ABI for ctypes.
//
// Layout: [Header | byte ring of capacity bytes]; messages are stored as
// u32 length + payload, wrapping at the ring edge (a message never splits:
// if it does not fit in the tail gap, a 0xFFFFFFFF wrap marker is written
// and the message starts at offset 0).
#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <stdio.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

constexpr uint32_t kWrapMarker = 0xFFFFFFFFu;

struct Header {
  pthread_mutex_t mu;
  pthread_cond_t not_empty;
  pthread_cond_t not_full;
  uint64_t capacity;   // ring bytes
  uint64_t head;       // read offset
  uint64_t tail;       // write offset
  uint64_t used;       // bytes in use (incl. length prefixes + wrap gaps)
  uint64_t count;      // queued messages
  uint32_t closed;     // producer-side close flag
};

struct Handle {
  Header* hdr;
  char* ring;
  size_t total;
  char name[256];
  bool owner;
};

void abs_deadline(double timeout_s, timespec* ts) {
  clock_gettime(CLOCK_REALTIME, ts);
  time_t sec = static_cast<time_t>(timeout_s);
  long nsec = static_cast<long>((timeout_s - sec) * 1e9);
  ts->tv_sec += sec;
  ts->tv_nsec += nsec;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

}  // namespace

extern "C" {

void* pd_shmq_create(const char* name, uint64_t capacity) {
  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  size_t total = sizeof(Header) + capacity;
  if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  auto* hdr = static_cast<Header*>(mem);
  memset(hdr, 0, sizeof(Header));
  hdr->capacity = capacity;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&hdr->not_empty, &ca);
  pthread_cond_init(&hdr->not_full, &ca);

  auto* h = new Handle();
  h->hdr = hdr;
  h->ring = static_cast<char*>(mem) + sizeof(Header);
  h->total = total;
  snprintf(h->name, sizeof(h->name), "%s", name);
  h->owner = true;
  return h;
}

void* pd_shmq_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, static_cast<size_t>(st.st_size),
                   PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* h = new Handle();
  h->hdr = static_cast<Header*>(mem);
  h->ring = static_cast<char*>(mem) + sizeof(Header);
  h->total = static_cast<size_t>(st.st_size);
  snprintf(h->name, sizeof(h->name), "%s", name);
  h->owner = false;
  return h;
}

static int lock_robust(Header* hdr) {
  int rc = pthread_mutex_lock(&hdr->mu);
  if (rc == EOWNERDEAD) {  // a worker died holding the lock — recover
    pthread_mutex_consistent(&hdr->mu);
    return 0;
  }
  return rc;
}

// 0 ok, 1 timeout, -1 error/too-big, -2 closed
int pd_shmq_push(void* vh, const char* data, uint64_t len, double timeout_s) {
  auto* h = static_cast<Handle*>(vh);
  Header* hdr = h->hdr;
  uint64_t need = len + 4;
  if (need + 4 > hdr->capacity) return -1;  // +4: potential wrap marker
  timespec ts;
  abs_deadline(timeout_s, &ts);
  if (lock_robust(hdr) != 0) return -1;
  // Wait until the message fits in ONE of the two legal placements — not
  // merely until total free bytes suffice (round-1 bug: a wrap-placed
  // message could overwrite unread data at the front of the ring):
  //   contiguous: gap bytes at tail are free (requires free_total >= need;
  //               when data wraps, the free region [tail, head) is exactly
  //               free_total)
  //   wrapped:    sacrifice the gap, write at 0 — needs head >= need and
  //               data must NOT already wrap (tail >= head)
  for (;;) {
    if (hdr->count == 0 && hdr->used == 0) {
      hdr->head = hdr->tail = 0;  // empty: normalize so any need <= cap fits
    }
    uint64_t gap_now = hdr->capacity - hdr->tail;
    uint64_t free_total = hdr->capacity - hdr->used;
    bool fits = (gap_now >= need)
                    ? (free_total >= need)
                    : (hdr->tail >= hdr->head && hdr->head >= need);
    if (fits) break;
    if (hdr->closed) {
      pthread_mutex_unlock(&hdr->mu);
      return -2;
    }
    if (pthread_cond_timedwait(&hdr->not_full, &hdr->mu, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&hdr->mu);
      return 1;
    }
  }
  uint64_t tail = hdr->tail;
  uint64_t gap = hdr->capacity - tail;
  if (gap < need) {  // cannot fit contiguously: wrap
    if (gap >= 4) {
      uint32_t marker = kWrapMarker;
      memcpy(h->ring + tail, &marker, 4);
    }
    hdr->used += gap;
    tail = 0;
  }
  uint32_t len32 = static_cast<uint32_t>(len);
  memcpy(h->ring + tail, &len32, 4);
  memcpy(h->ring + tail + 4, data, len);
  hdr->tail = (tail + need) % hdr->capacity;
  hdr->used += need;
  hdr->count += 1;
  pthread_cond_signal(&hdr->not_empty);
  pthread_mutex_unlock(&hdr->mu);
  return 0;
}

// >=0: message length (copied into *out, malloc'd); -1 error; -2 timeout;
// -3 closed-and-drained
int64_t pd_shmq_pop(void* vh, char** out, double timeout_s) {
  auto* h = static_cast<Handle*>(vh);
  Header* hdr = h->hdr;
  timespec ts;
  abs_deadline(timeout_s, &ts);
  if (lock_robust(hdr) != 0) return -1;
  while (hdr->count == 0) {
    if (hdr->closed) {
      pthread_mutex_unlock(&hdr->mu);
      return -3;
    }
    if (pthread_cond_timedwait(&hdr->not_empty, &hdr->mu, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&hdr->mu);
      return -2;
    }
  }
  uint64_t head = hdr->head;
  uint32_t len32;
  if (hdr->capacity - head >= 4) {
    memcpy(&len32, h->ring + head, 4);
    if (len32 == kWrapMarker) {
      hdr->used -= hdr->capacity - head;
      head = 0;
      memcpy(&len32, h->ring, 4);
    }
  } else {  // tail gap < 4 counted as wrap space
    hdr->used -= hdr->capacity - head;
    head = 0;
    memcpy(&len32, h->ring, 4);
  }
  char* buf = static_cast<char*>(malloc(len32 ? len32 : 1));
  memcpy(buf, h->ring + head + 4, len32);
  hdr->head = (head + len32 + 4) % hdr->capacity;
  hdr->used -= len32 + 4;
  hdr->count -= 1;
  // broadcast: producers wait on size-dependent fit conditions, so waking
  // just one could strand another whose (smaller) message now fits
  pthread_cond_broadcast(&hdr->not_full);
  pthread_mutex_unlock(&hdr->mu);
  *out = buf;
  return len32;
}

uint64_t pd_shmq_count(void* vh) {
  auto* h = static_cast<Handle*>(vh);
  if (lock_robust(h->hdr) != 0) return 0;
  uint64_t c = h->hdr->count;
  pthread_mutex_unlock(&h->hdr->mu);
  return c;
}

void pd_shmq_close_writers(void* vh) {
  auto* h = static_cast<Handle*>(vh);
  if (lock_robust(h->hdr) == 0) {
    h->hdr->closed = 1;
    pthread_cond_broadcast(&h->hdr->not_empty);
    pthread_cond_broadcast(&h->hdr->not_full);
    pthread_mutex_unlock(&h->hdr->mu);
  }
}

void pd_shmq_free(char* p) { free(p); }

void pd_shmq_close(void* vh) {
  auto* h = static_cast<Handle*>(vh);
  munmap(h->hdr, h->total);
  if (h->owner) shm_unlink(h->name);
  delete h;
}

}  // extern "C"
