"""paddle.quantization parity (python/paddle/quantization/): QuantConfig +
QAT (fake-quant training) and PTQ (observe → convert).

TPU note: fake-quant is pure elementwise math, so under jit XLA fuses it
into the surrounding matmuls; int8 *execution* is a serving-stack concern
(tracked gap), simulation semantics match the reference's QAT/PTQ.
"""
from __future__ import annotations

import math
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from .. import nn
from ..nn.layer import Layer
from ..ops.registry import apply
from ..tensor_class import Tensor, unwrap, wrap

__all__ = ["QuantConfig", "QAT", "PTQ", "BaseQuanter", "BaseObserver",
           "FakeQuanterWithAbsMaxObserver",
            "AbsMaxObserver", "QuanterFactory", "quanter"]


def _fake_quant(x, scale, bits=8):
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-9)
    return jnp.clip(jnp.round(x / s * qmax), -qmax - 1, qmax) * s / qmax


class QuanterFactory:
    def __init__(self, cls, **kwargs):
        self._cls = cls
        self._kwargs = kwargs

    def instance(self, layer=None):
        return self._cls(**self._kwargs)


def quanter(name):  # decorator parity (quantization/factory.py)
    def deco(cls):
        return cls

    return deco


class BaseQuanter(Layer):
    """quantization/base_quanter.py parity: abstract quanter — forward
    fake-quantizes, ``scales()``/``zero_points()`` expose the learned
    quantization params."""

    def forward(self, x):  # pragma: no cover - abstract
        raise NotImplementedError

    def scales(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def zero_points(self):
        return None  # symmetric quantization throughout this build


class BaseObserver(BaseQuanter):
    """quantization/base_observer.py parity: calibration-time observer —
    forward passes through while tracking statistics."""


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """QAT activation/weight quanter (fake_quanter.py parity): moving
    average abs-max scale + straight-through-estimator rounding."""

    def __init__(self, moving_rate=0.9, bit_length=8, dtype="float32",
                 name=None):
        super().__init__()
        self.moving_rate = moving_rate
        self.bit_length = bit_length
        self._scale = None

    def forward(self, x):
        import jax.core as _jc

        m = jnp.max(jnp.abs(unwrap(x)))
        if isinstance(m, _jc.Tracer):
            raise RuntimeError(
                "FakeQuanterWithAbsMaxObserver updates its moving-average "
                "scale eagerly and cannot run under jax.jit/to_static "
                "tracing; run QAT in eager mode (same restriction family "
                "as _check_nan_inf), or export after calibration.")
        absmax = float(m)
        if self._scale is None:
            self._scale = absmax
        elif self.training:
            self._scale = (self.moving_rate * self._scale
                           + (1 - self.moving_rate) * absmax)
        scale, bits = self._scale, self.bit_length

        def fn(a):
            q = _fake_quant(a, jnp.asarray(scale, a.dtype), bits)
            # straight-through estimator: identity gradient
            return a + jax.lax.stop_gradient(q - a)

        import jax

        return apply("fake_quant", fn, x)

    def scales(self):
        return self._scale


class AbsMaxObserver(BaseObserver):
    """PTQ observer (observers/abs_max.py parity): track abs-max, no
    quantization during calibration."""

    def __init__(self, quant_bits=8, name=None):
        super().__init__()
        self.quant_bits = quant_bits
        self._absmax = 0.0

    def forward(self, x):
        self._absmax = max(self._absmax, float(jnp.max(jnp.abs(unwrap(x)))))
        return x

    def scales(self):
        return self._absmax


class QuantConfig:
    """config.py parity: which quanters apply to activations/weights, with
    per-layer overrides."""

    def __init__(self, activation: Optional[QuanterFactory],
                 weight: Optional[QuanterFactory]):
        self.activation = activation
        self.weight = weight
        self._layer_configs = []

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        self._layer_configs.append((layers, activation, weight))

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) else [layer_type]
        self._layer_configs.append((types, activation, weight))

    def _factories_for(self, layer):
        for targets, act, wt in self._layer_configs:
            for t in targets:
                if layer is t or (isinstance(t, type) and isinstance(layer, t)):
                    return act or self.activation, wt or self.weight
        return self.activation, self.weight


class QuantedLinear(Layer):
    """Quantized stand-in for nn.Linear (nn/quant/qat/linear.py parity)."""

    def __init__(self, inner: "nn.Linear", act_quanter, weight_quanter):
        super().__init__()
        self.inner = inner
        self.activation_quanter = act_quanter
        self.weight_quanter = weight_quanter

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.inner.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        import paddle_tpu as paddle

        out = paddle.matmul(x, w)
        if self.inner.bias is not None:
            out = out + self.inner.bias
        return out


class QuantedConv2D(Layer):
    def __init__(self, inner: "nn.Conv2D", act_quanter, weight_quanter):
        super().__init__()
        self.inner = inner
        self.activation_quanter = act_quanter
        self.weight_quanter = weight_quanter

    def forward(self, x):
        from ..nn.functional import conv as F_conv

        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.inner.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        i = self.inner
        return F_conv.conv2d(x, w, i.bias, i._stride, i._padding, i._dilation,
                             i._groups, i._data_format)


_QUANTABLE = {}


def _swap(model: Layer, config: QuantConfig, observer_only: bool):
    for name, sub in list(model._sub_layers.items()):
        if isinstance(sub, nn.Linear):
            act_f, wt_f = config._factories_for(sub)
            model._sub_layers[name] = QuantedLinear(
                sub, act_f.instance(sub) if act_f else None,
                wt_f.instance(sub) if wt_f and not observer_only else None)
        elif isinstance(sub, nn.Conv2D):
            act_f, wt_f = config._factories_for(sub)
            model._sub_layers[name] = QuantedConv2D(
                sub, act_f.instance(sub) if act_f else None,
                wt_f.instance(sub) if wt_f and not observer_only else None)
        else:
            _swap(sub, config, observer_only)
    return model


class QAT:
    """qat.py parity: model → fake-quant model for quant-aware training."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace=False):
        import copy

        target = model if inplace else copy.deepcopy(model)
        return _swap(target, self.config, observer_only=False)


class PTQ:
    """ptq.py parity: insert observers, calibrate, convert."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace=False):
        import copy

        target = model if inplace else copy.deepcopy(model)
        return _swap(target, self.config, observer_only=True)

    def convert(self, model: Layer, inplace=False):
        """Bake observed scales: weights round-trip through int8 grid."""
        import copy

        target = model if inplace else copy.deepcopy(model)
        for _, sub in target.named_sublayers(include_self=True):
            if isinstance(sub, (QuantedLinear, QuantedConv2D)):
                w = sub.inner.weight
                absmax = float(jnp.max(jnp.abs(unwrap(w))))
                q = _fake_quant(unwrap(w), jnp.asarray(absmax, "float32"))
                w.set_value(np.asarray(q))
        return target
