"""TensorArray: dynamic list-of-tensors surface.

Reference parity: paddle/phi/core/tensor_array.h (the C++ type) and
python/paddle/tensor/array.py (array_length/array_read/array_write/
create_array).

TPU-native design: in eager mode a TensorArray IS a Python list of Tensors
(exactly what the reference does in dygraph — array.py:43 returns len(array)
for lists). Inside jit-traced code, a Python list of traced values plays the
same role; loops that need a traced-length array should use lax.scan with a
stacked tensor instead, which is the XLA-idiomatic replacement for the
while_loop+TensorArray pattern of the static graph.
"""
from __future__ import annotations

from typing import List, Optional

from .tensor_class import Tensor


class TensorArray(list):
    """A list subtype so user isinstance checks on list keep working."""

    def __repr__(self):
        return f"TensorArray(len={len(self)})"


def create_array(dtype: str = "float32", initialized_list: Optional[list] = None):
    """paddle.tensor.create_array parity (array.py:309)."""
    arr = TensorArray()
    if initialized_list is not None:
        for v in initialized_list:
            if not isinstance(v, Tensor):
                raise TypeError(
                    "All values in `initialized_list` should be Tensor, but "
                    f"received {type(v).__name__}.")
            arr.append(v)
    return arr


def array_length(array) -> int:
    """paddle.tensor.array_length parity (array.py:43)."""
    if not isinstance(array, list):
        raise TypeError(
            "array should be a python list (TensorArray in the reference), "
            f"got {type(array).__name__}")
    return len(array)


def _index_of(i) -> int:
    if isinstance(i, Tensor):
        if i._array.size != 1:
            raise ValueError("array index must be a scalar")
        return int(i._array)
    return int(i)


def array_read(array, i):
    """paddle.tensor.array_read parity (array.py:110)."""
    idx = _index_of(i)
    if not isinstance(array, list):
        raise TypeError("array should be a python list")
    if idx >= len(array):
        raise IndexError(f"array index {idx} out of range ({len(array)})")
    return array[idx]


def array_write(x, i, array=None):
    """paddle.tensor.array_write parity (array.py:206): write ``x`` at
    position ``i``, growing the array if i == len(array)."""
    idx = _index_of(i)
    if array is None:
        array = create_array()
    if idx > len(array):
        raise IndexError(
            f"array index {idx} skips positions (len={len(array)})")
    if idx == len(array):
        array.append(x)
    else:
        array[idx] = x
    return array
