"""paddle.regularizer parity (python/paddle/regularizer.py): L1Decay /
L2Decay weight regularizers. Optimizers consume them through the
``weight_decay`` argument; the functional path applies them inside
``Optimizer.apply_gradients``.
"""
from __future__ import annotations

__all__ = ["WeightDecayRegularizer", "L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def grad_term(self, param):
        """The d(penalty)/d(param) term added to the gradient."""
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self.coeff})"


class L1Decay(WeightDecayRegularizer):
    """L1 penalty coeff*|w| → subgradient coeff*sign(w)."""

    def grad_term(self, param):
        import jax.numpy as jnp

        return self.coeff * jnp.sign(param)


class L2Decay(WeightDecayRegularizer):
    """L2 penalty 0.5*coeff*w^2 → gradient coeff*w."""

    def grad_term(self, param):
        return self.coeff * param
