"""StringTensor parity (paddle/phi/core/string_tensor.h + the strings
kernel set paddle/phi/kernels/strings/: empty, copy, lower, upper — the
reference exposes no Python API for these; this module IS the usable
surface).

TPU-native: strings never touch the device — they are host-side numpy
object arrays (XLA has no string dtype). The op set matches the
reference kernels 1:1, including the unicode/ascii split of
strings_lower_upper_kernel.h.
"""
from __future__ import annotations

import numpy as np

__all__ = ["StringTensor", "empty", "empty_like", "copy", "lower", "upper"]


class StringTensor:
    """Host-side tensor of variable-length UTF-8 strings
    (phi::StringTensor analog: shape + pstring storage)."""

    def __init__(self, data, name=None):
        arr = np.asarray(data, dtype=object)
        # normalize every element to str (pstring semantics)
        self._array = np.vectorize(lambda s: "" if s is None else str(s),
                                   otypes=[object])(arr) \
            if arr.size else arr
        self.name = name

    @property
    def shape(self):
        return list(self._array.shape)

    @property
    def ndim(self):
        return self._array.ndim

    def numel(self):
        return int(self._array.size)

    def numpy(self):
        return self._array

    def tolist(self):
        return self._array.tolist()

    def __getitem__(self, idx):
        out = self._array[idx]
        if isinstance(out, np.ndarray):
            return StringTensor(out)
        return out

    def __eq__(self, other):
        other = other._array if isinstance(other, StringTensor) else other
        return np.asarray(self._array == other)

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, data={self._array!r})"


def empty(shape, name=None) -> StringTensor:
    """strings_empty_kernel.cc parity: empty strings of the given shape."""
    return StringTensor(np.full(tuple(shape), "", dtype=object))


def empty_like(x: StringTensor, name=None) -> StringTensor:
    return empty(x.shape)


def copy(x: StringTensor) -> StringTensor:
    """strings_copy_kernel parity."""
    return StringTensor(x._array.copy())


def _case_map(x: StringTensor, fn, use_utf8_encoding: bool) -> StringTensor:
    if use_utf8_encoding:
        # unicode-aware path (unicode.h case mapping = python str casing)
        mapped = np.vectorize(fn, otypes=[object])(x._array) \
            if x._array.size else x._array.copy()
    else:
        # ascii-only path (case_utils.h): leave non-ascii bytes untouched
        def ascii_case(s):
            return "".join(fn(c) if ord(c) < 128 else c for c in s)

        mapped = np.vectorize(ascii_case, otypes=[object])(x._array) \
            if x._array.size else x._array.copy()
    return StringTensor(mapped)


def lower(x: StringTensor, use_utf8_encoding: bool = False) -> StringTensor:
    """strings_lower_upper_kernel.h StringLowerKernel parity."""
    return _case_map(x, str.lower, use_utf8_encoding)


def upper(x: StringTensor, use_utf8_encoding: bool = False) -> StringTensor:
    """strings_lower_upper_kernel.h StringUpperKernel parity."""
    return _case_map(x, str.upper, use_utf8_encoding)
