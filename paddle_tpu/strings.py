"""StringTensor parity (paddle/phi/core/string_tensor.h + the strings
kernel set paddle/phi/kernels/strings/: empty, copy, lower, upper — the
reference exposes no Python API for these; this module IS the usable
surface).

TPU-native: strings never touch the device — they are host-side numpy
object arrays (XLA has no string dtype). The op set matches the
reference kernels 1:1, including the unicode/ascii split of
strings_lower_upper_kernel.h.
"""
from __future__ import annotations

import numpy as np

__all__ = ["StringTensor", "empty", "empty_like", "copy", "lower", "upper"]


class StringTensor:
    """Host-side tensor of variable-length UTF-8 strings
    (phi::StringTensor analog: shape + pstring storage)."""

    def __init__(self, data, name=None):
        arr = np.asarray(data, dtype=object)
        # normalize every element to str (pstring semantics)
        self._array = np.vectorize(lambda s: "" if s is None else str(s),
                                   otypes=[object])(arr) \
            if arr.size else arr
        self.name = name

    @property
    def shape(self):
        return list(self._array.shape)

    @property
    def ndim(self):
        return self._array.ndim

    def numel(self):
        return int(self._array.size)

    def numpy(self):
        return self._array

    def tolist(self):
        return self._array.tolist()

    def __getitem__(self, idx):
        out = self._array[idx]
        if isinstance(out, np.ndarray):
            return StringTensor(out)
        return out

    def __eq__(self, other):
        other = other._array if isinstance(other, StringTensor) else other
        return np.asarray(self._array == other)

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, data={self._array!r})"


def empty(shape, name=None) -> StringTensor:
    """strings_empty_kernel.cc parity: empty strings of the given shape."""
    return StringTensor(np.full(tuple(shape), "", dtype=object))


def empty_like(x: StringTensor, name=None) -> StringTensor:
    return empty(x.shape)


def copy(x: StringTensor) -> StringTensor:
    """strings_copy_kernel parity."""
    return StringTensor(x._array.copy())


def _case_map(x: StringTensor, fn, use_utf8_encoding: bool) -> StringTensor:
    if use_utf8_encoding:
        # unicode-aware path (unicode.h case mapping = python str casing)
        mapped = np.vectorize(fn, otypes=[object])(x._array) \
            if x._array.size else x._array.copy()
    else:
        # ascii-only path (case_utils.h): leave non-ascii bytes untouched
        def ascii_case(s):
            return "".join(fn(c) if ord(c) < 128 else c for c in s)

        mapped = np.vectorize(ascii_case, otypes=[object])(x._array) \
            if x._array.size else x._array.copy()
    return StringTensor(mapped)


def lower(x: StringTensor, use_utf8_encoding: bool = False) -> StringTensor:
    """strings_lower_upper_kernel.h StringLowerKernel parity."""
    return _case_map(x, str.lower, use_utf8_encoding)


def upper(x: StringTensor, use_utf8_encoding: bool = False) -> StringTensor:
    """strings_lower_upper_kernel.h StringUpperKernel parity."""
    return _case_map(x, str.upper, use_utf8_encoding)


# ---------------------------------------------------------------------------
# tokenizer-adjacent surface (beyond the reference's 4 kernels — VERDICT r4
# item 10): batched host-side text ops a preprocessing pipeline needs before
# ids hit the device. All elementwise over the object array.
# ---------------------------------------------------------------------------

def _map(x: StringTensor, fn) -> StringTensor:
    return StringTensor(_vec(x, fn, object))


def _vec(x: StringTensor, fn, otype):
    """Elementwise fn over the object array with an empty-shape guard
    (np.vectorize cannot infer otypes from zero elements)."""
    arr = x._array
    if not arr.size:
        return (arr.copy() if otype is object
                else np.zeros(arr.shape, otype))
    return np.vectorize(fn, otypes=[otype])(arr)


def _zip_map(x: StringTensor, y: StringTensor, fn) -> StringTensor:
    if y.shape != x.shape:
        raise ValueError(f"shape mismatch: {x.shape} vs {y.shape}")
    out = np.empty(x._array.shape, dtype=object)
    for idx in np.ndindex(out.shape):
        out[idx] = fn(x._array[idx], y._array[idx])
    return StringTensor(out)


def strip(x: StringTensor, chars=None) -> StringTensor:
    return _map(x, lambda s: s.strip(chars))


def lstrip(x: StringTensor, chars=None) -> StringTensor:
    return _map(x, lambda s: s.lstrip(chars))


def rstrip(x: StringTensor, chars=None) -> StringTensor:
    return _map(x, lambda s: s.rstrip(chars))


def length(x: StringTensor):
    """Per-element character counts as an int32 numpy array."""
    return _vec(x, len, np.int32)


def split(x: StringTensor, sep=None, maxsplit: int = -1):
    """Per-element str.split. Returns a same-shaped object array whose
    entries are LISTS of pieces (ragged — lengths differ per element)."""
    arr = x._array
    out = np.empty(arr.shape, dtype=object)
    for idx in np.ndindex(arr.shape):
        out[idx] = arr[idx].split(sep, maxsplit)
    return out


def join(x: StringTensor, sep: str = "") -> str:
    """Join every element (C-order) with ``sep``."""
    return sep.join(x._array.reshape(-1).tolist())


def concat(x: StringTensor, y, name=None) -> StringTensor:
    """Elementwise concatenation with a StringTensor or a scalar str."""
    if isinstance(y, StringTensor):
        return _zip_map(x, y, lambda a, b: a + b)
    return _map(x, lambda s: s + str(y))


def regex_replace(x: StringTensor, pattern: str, repl: str,
                  count: int = 0) -> StringTensor:
    import re

    rx = re.compile(pattern)
    return _map(x, lambda s: rx.sub(repl, s, count=count))


def startswith(x: StringTensor, prefix: str):
    return _vec(x, lambda s: s.startswith(prefix), bool)


def endswith(x: StringTensor, suffix: str):
    return _vec(x, lambda s: s.endswith(suffix), bool)


def whitespace_tokenize(x: StringTensor, lowercase: bool = False):
    """The canonical pre-tokenizer: strip + (optional) lowercase +
    whitespace split. Returns a same-shaped object array of token lists."""
    y = strip(lower(x, use_utf8_encoding=True) if lowercase else x)
    return split(y)


__all__ += ["strip", "lstrip", "rstrip", "length", "split", "join",
            "concat", "regex_replace", "startswith", "endswith",
            "whitespace_tokenize"]
