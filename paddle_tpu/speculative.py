"""Speculative decoding: a small draft model proposes ``draft_k`` tokens,
the target model verifies them in ONE chunked forward, and the longest
target-greedy-consistent prefix (plus the target's bonus token) is accepted
— per round the target runs once for up to ``draft_k + 1`` emitted tokens
instead of once per token.

Role anchor: the speculative/draft-model decode path of the reference
platform's LLM serving stack (the same serving tier as
paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu; the
reference ecosystem ships it in its llm inference recipes). TPU-native
design: both the draft proposal loop (a ``lax.scan`` of greedy steps) and
the chunked verify are single jitted computations with donated KV buffers;
rollback after a rejected suffix is just resetting the cache's scalar
``pos`` — the dense serving cache (generation.cached_attention) masks
columns ``> pos``, so stale entries beyond the accepted prefix are inert
and get overwritten by later writes.

Greedy-exactness contract: the emitted sequence is IDENTICAL to
``target.generate(..., do_sample=False)`` — speculation changes latency,
never output (the test asserts token equality).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .autograd import tape as _tape
from .generation import (_get_prefill_step, _memoized_step, _split_caches,
                         _unwrap_caches)
from .nn.layer import functional_weights as _functional_weights
from .tensor_class import unwrap, wrap


def _spec_accept_hist(engine: str):
    """The shared acceptance histogram (serving_spec_accepted_tokens):
    every speculative path — solo, MTP self-draft, and the serving
    engine — publishes accepted-draft counts through the SAME registry
    family, so acceptance health reads off one /metrics series instead
    of caller-only stats dicts."""
    from .observability import catalog as _metrics

    return _metrics.SERVING_SPEC_ACCEPTED.labels(engine=engine)


def _ngram_next(hist: np.ndarray, max_ngram: int):
    """One prompt-lookup step: the token that followed the MOST RECENT
    earlier occurrence of ``hist``'s trailing n-gram (n = ``max_ngram``
    down to 1), or None when nothing repeats."""
    L = int(hist.size)
    if L < 2:
        return None
    for n in range(min(int(max_ngram), L - 1), 0, -1):
        pat = hist[L - n:]
        # windows starting before the trailing n-gram itself: a match at
        # start s < L - n guarantees a continuation token exists
        view = np.lib.stride_tricks.sliding_window_view(hist, n)
        hits = np.nonzero((view[: L - n] == pat).all(axis=1))[0]
        if hits.size:
            return int(hist[int(hits[-1]) + n])  # most recent wins
    return None


def ngram_propose(history, k: int, max_ngram: int = 3) -> np.ndarray:
    """Prompt-lookup draft proposal (n-gram drafter — no second model):
    ITERATED single-token lookups — each proposed token is appended to a
    working copy of the history before the next lookup, so the proposal
    is the drafter's own autoregressive continuation (a periodic stream
    extends past the raw history's end instead of truncating at it).
    ``c[0]`` predicts the NEXT position, ``c[j]`` the one j after it.
    Returns an int32 array of length <= k (empty when the history is too
    short or nothing repeats — the caller pads; padding can only be
    "accepted" when it coincidentally equals the target's greedy choice,
    so junk proposals never change output, only acceptance rate).

    Pure host work on the request's token history — the drafter runs
    between engine dispatches and never touches the device."""
    work = np.asarray(history).reshape(-1)
    out = []
    for _ in range(int(k)):
        nxt = _ngram_next(work, max_ngram)
        if nxt is None:
            break
        out.append(nxt)
        work = np.append(work, nxt)
    return np.asarray(out, np.int32)


class _ProposeStep:
    """Draft proposal: feed ``seed`` (1 or 2 catch-up tokens), then scan
    ``k-1`` greedy single-token steps — one jitted dispatch for all ``k``
    proposals, donated draft KV buffers."""

    def __init__(self, model, max_len, k, seed_len):
        self._model = model

        def pure(state, seed, bufs, aux):
            caches = [{**b, **a} for b, a in zip(bufs, aux)]
            with _functional_weights(model, state), _tape.no_grad():
                hidden, caches = model.llama.forward_cached(
                    wrap(seed), caches, rope_len=max_len)
                h_last = unwrap(hidden)[:, -1:]
                first = jnp.argmax(
                    unwrap(model.lm_head_logits(wrap(h_last)))[:, -1, :],
                    axis=-1).astype(jnp.int32)

                def body(carry, _):
                    tok, caches = carry
                    hidden, caches = model.llama.forward_cached(
                        wrap(tok[:, None]), caches, rope_len=max_len)
                    nxt = jnp.argmax(
                        unwrap(model.lm_head_logits(hidden))[:, -1, :],
                        axis=-1).astype(jnp.int32)
                    return (nxt, caches), nxt

                if k > 1:
                    (_, caches), rest = jax.lax.scan(
                        body, (first, caches), None, length=k - 1)
                    toks = jnp.concatenate([first[None], rest], axis=0)
                else:
                    toks = first[None]
            nb, na = _split_caches(_unwrap_caches(caches))
            return toks.T, nb, na  # [B, k]

        self._jitted = jax.jit(pure, donate_argnums=(2,))
        self._state = dict(model.functional_state())

    def __call__(self, seed, caches):
        bufs, aux = _split_caches(caches)
        toks, nb, na = self._jitted(self._state, seed, bufs, aux)
        return toks, [{**b, **a} for b, a in zip(nb, na)]


class _VerifyStep:
    """Target verify: one chunked forward over [last, d_1..d_k]; returns
    the target's greedy token at every chunk position."""

    def __init__(self, model, max_len, chunk_len):
        self._model = model

        def pure(state, chunk, bufs, aux):
            caches = [{**b, **a} for b, a in zip(bufs, aux)]
            with _functional_weights(model, state), _tape.no_grad():
                hidden, caches = model.llama.forward_cached(
                    wrap(chunk), caches, rope_len=max_len)
                logits = unwrap(model.lm_head_logits(hidden))
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            nb, na = _split_caches(_unwrap_caches(caches))
            return greedy, nb, na  # [B, chunk_len]

        self._jitted = jax.jit(pure, donate_argnums=(2,))
        self._state = dict(model.functional_state())

    def __call__(self, chunk, caches):
        bufs, aux = _split_caches(caches)
        greedy, nb, na = self._jitted(self._state, chunk, bufs, aux)
        return greedy, [{**b, **a} for b, a in zip(nb, na)]


def _set_pos(caches, pos):
    for c in caches:
        c["pos"] = jnp.asarray(pos, jnp.int32)
    return caches


def _prefill(model, ids, max_len):
    """Whole-prompt prefill (generation's one-shot jitted step); returns
    (greedy_next, caches)."""
    step = _get_prefill_step(model, max_len, ragged=False)
    lengths = jnp.full((ids.shape[0],), ids.shape[1], jnp.int32)
    last, caches = step(ids, lengths, None)
    return jnp.argmax(last, axis=-1).astype(jnp.int32), caches


def _normalize_request(input_ids):
    """Shared batch-1 request normalization: returns (ids [1,P] np,
    out_dtype); raises on batched input (the dense cache keeps one scalar
    write position)."""
    ids = np.asarray(unwrap(input_ids) if hasattr(input_ids, "shape")
                     else input_ids)
    out_dtype = ids.dtype
    if ids.ndim == 1:
        ids = ids[None]
    if ids.shape[0] != 1:
        raise ValueError(
            "speculative decoding is per-request (batch 1); run rows "
            "separately or use model.generate for batched decode")
    return ids, out_dtype


def _finish(emitted, max_new_tokens, eos_token_id, out_dtype):
    """Shared emit epilogue: truncate to the budget, cut at eos, wrap in
    the request dtype."""
    emitted = emitted[:max_new_tokens]
    if eos_token_id is not None and eos_token_id in emitted:
        emitted = emitted[: emitted.index(eos_token_id) + 1]
    return wrap(jnp.asarray(np.asarray(emitted, out_dtype)[None]))


def speculative_generate(target, draft, input_ids, max_new_tokens=20,
                         draft_k=4, eos_token_id=None, return_stats=False):
    """Greedy speculative decode of ``input_ids`` [1, P] → [1, P + new].

    Batch size 1 (per-request serving): the dense cache keeps ONE scalar
    write position, and rows accepting different prefix lengths would need
    per-row rollback. Output is exactly ``target.generate`` greedy.

    ``return_stats=True`` returns ``(out, stats)`` with the same contract
    as :func:`mtp_speculative_generate`: ``rounds`` (verify dispatches),
    ``hits`` (draft tokens the target accepted), ``acceptance`` (hits /
    (rounds * draft_k) — the fraction of proposed tokens that landed).
    Acceptance is ALSO published per round through the metrics registry
    (``serving_spec_accepted_tokens``, engine="solo") whether or not the
    caller asks for stats.
    """
    ids, out_dtype = _normalize_request(input_ids)
    B, P = ids.shape
    k = int(draft_k)
    if k < 1:
        raise ValueError(f"draft_k must be >= 1, got {draft_k}")
    max_len = P + max_new_tokens + k + 2
    for name, m in (("target", target), ("draft", draft)):
        limit = m.config.max_position_embeddings
        if max_len > limit:
            raise ValueError(
                f"speculative_generate: prompt+new(+{k + 2} speculation "
                f"slack) = {max_len} exceeds the {name} model's "
                f"max_position_embeddings {limit}")
    ids = jnp.asarray(ids, jnp.int32)

    t0, tgt_caches = _prefill(target, ids, max_len)
    _, dft_caches = _prefill(draft, ids, max_len)
    tgt_pos, dft_pos = P, P

    emitted = [int(t0[0])]  # pdlint: disable=host-sync -- the prefill's one deliberate first-token fetch
    last = emitted[0]
    catchup = []  # accepted tokens not yet written to the draft cache
    rounds = hits = 0       # draft-acceptance observability
    accept_hist = _spec_accept_hist("solo")

    def propose_step(seed_len):
        return _memoized_step(
            draft, "_spec_propose_steps", (max_len, k, seed_len),
            lambda: _ProposeStep(draft, max_len, k, seed_len), maxsize=8)

    verify_step = _memoized_step(
        target, "_spec_verify_steps", (max_len, k + 1),
        lambda: _VerifyStep(target, max_len, k + 1), maxsize=8)

    while len(emitted) < max_new_tokens and \
            (eos_token_id is None or emitted[-1] != eos_token_id):
        seed = jnp.asarray([catchup + [last]], jnp.int32)   # [1, 1|2]
        dft_caches = _set_pos(dft_caches, dft_pos)
        proposals, dft_caches = propose_step(seed.shape[1])(seed, dft_caches)
        props = [int(x) for x in np.asarray(proposals[0])]   # d_1..d_k  # pdlint: disable=host-sync -- the round's deliberate draft fetch (host builds the verify chunk from it)

        chunk = jnp.asarray([[last] + props], jnp.int32)     # [1, k+1]
        tgt_caches = _set_pos(tgt_caches, tgt_pos)
        greedy, tgt_caches = verify_step(chunk, tgt_caches)
        g = [int(x) for x in np.asarray(greedy[0])]          # g_0..g_k  # pdlint: disable=host-sync -- the round's deliberate verify fetch (acceptance is host control flow)

        m = 0
        while m < k and props[m] == g[m]:
            m += 1
        accepted = props[:m] + [g[m]]                        # ≤ k+1 tokens
        rounds += 1
        hits += m
        accept_hist.observe(m)

        # context now ends ...last, d_1..d_m, g_m; g_m is the new `last`
        ctx_len_old = tgt_pos + 1        # context length BEFORE this round
        tgt_pos = ctx_len_old + m        # target holds ctx + d_1..d_m
        if m == k:                       # draft never wrote d_k's entry
            dft_pos = ctx_len_old + (k - 1)
            catchup = [props[-1]]
        else:                            # d_1..d_m all in the draft cache
            dft_pos = ctx_len_old + m
            catchup = []
        last = accepted[-1]
        emitted.extend(accepted)
        if eos_token_id is not None and eos_token_id in accepted:
            break

    # same convention as model.generate: only the NEW tokens, input dtype
    out = _finish(emitted, max_new_tokens, eos_token_id, out_dtype)
    if return_stats:
        return out, {"rounds": rounds, "hits": hits,
                     "acceptance": (hits / (rounds * k)) if rounds else 0.0}
    return out


class _MTPRoundStep:
    """One MTP self-speculative round as ONE jitted dispatch (the
    mtp_speculative_generate docstring's promised follow-up off the eager
    host loop): extend the MTP latent stream with the previous round's
    completed (hidden, token) pairs and draft one token, then run the
    2-token cached verify [pending, draft] on the main model — draft,
    verify, and both cache updates in a single device program with the
    big cache buffers donated. Keyed on ``n_pairs`` (1 after a miss, 2
    after a hit — the only two carry shapes), memoized per model via
    _memoized_step exactly like the propose/verify steps above."""

    def __init__(self, model, max_len, n_pairs):
        self._model = model
        mtp = model.mtp_layers[0]

        def pure(state, h_tail, toks, bufs, aux, mbufs, maux):
            caches = [{**b, **a} for b, a in zip(bufs, aux)]
            mtp_cache = {**mbufs[0], **maux[0]}
            with _functional_weights(model, state), _tape.no_grad():
                cos, sin = model.llama._rope(max_len)
                emb = model.llama.embed_tokens(wrap(toks)).astype(
                    model.config.dtype)
                x = mtp.fuse(wrap(h_tail), emb)
                h_m, mtp_cache = mtp.block(x, cos, sin, kv_cache=mtp_cache)
                draft = jnp.argmax(unwrap(model.lm_head_logits(
                    mtp.norm(h_m[:, -1:])))[0, 0]).astype(jnp.int32)
                verify = jnp.stack([toks[0, -1], draft])[None, :]  # [1, 2]
                normed2, pre2, caches = model.llama.forward_cached(
                    wrap(verify), caches, rope_len=max_len,
                    return_prenorm=True)
                logits2 = unwrap(model.lm_head_logits(normed2))
            g0 = jnp.argmax(logits2[0, 0]).astype(jnp.int32)
            g1 = jnp.argmax(logits2[0, 1]).astype(jnp.int32)
            nb, na = _split_caches(_unwrap_caches(caches))
            mb, ma = _split_caches(_unwrap_caches([mtp_cache]))
            return jnp.stack([g0, g1, draft]), unwrap(pre2), nb, na, mb, ma

        self._jitted = jax.jit(pure, donate_argnums=(3, 5))
        self._state = dict(model.functional_state())

    def __call__(self, h_tail, toks, caches, mtp_caches):
        bufs, aux = _split_caches(_unwrap_caches(caches))
        mb, ma = _split_caches(_unwrap_caches(mtp_caches))
        g, pre2, nb, na, mb2, ma2 = self._jitted(
            self._state, h_tail, toks, bufs, aux, mb, ma)
        return (g, pre2, [{**b, **a} for b, a in zip(nb, na)],
                [{**b, **a} for b, a in zip(mb2, ma2)])


def mtp_speculative_generate(model, input_ids, max_new_tokens=20,
                             eos_token_id=None, return_stats=False):
    """Self-speculative greedy decode for DeepSeek models trained with
    multi-token prediction (``num_nextn_predict_layers >= 1``): the FIRST
    MTP depth drafts one token per round from the main model's PRE-norm
    hidden stream (the MTP block keeps its own latent cache over the
    shifted sequence, exactly the pairing it was trained on), and a
    2-token cached verify accepts or corrects (arXiv:2412.19437 §2.2
    inference usage — the "free" extra token per forward).

    Output is EXACTLY ``model.generate`` greedy — the draft only changes
    how many tokens each main-model forward retires. Batch 1 (the dense
    cache keeps one write position; see speculative_generate). Each round
    is ONE jitted dispatch (:class:`_MTPRoundStep`, memoized via
    _memoized_step and keyed on the 1- or 2-pair carry shape); rollback
    after a miss is a host-side cache ``pos`` reset, like
    speculative_generate's. Acceptance is published per round through the
    metrics registry (``serving_spec_accepted_tokens``, engine="mtp")."""
    from .generation import _empty_caches

    mtp_layers = getattr(model, "mtp_layers", None)
    if not mtp_layers:
        raise ValueError(
            "mtp_speculative_generate needs a model built with "
            "num_nextn_predict_layers >= 1 (the MTP draft module)")
    mtp = mtp_layers[0]
    ids, out_dtype = _normalize_request(input_ids)
    B, P = ids.shape
    max_len = P + max_new_tokens + 3
    if max_len > model.config.max_position_embeddings:
        raise ValueError(
            f"prompt+new(+3 speculation slack) = {max_len} exceeds "
            f"max_position_embeddings "
            f"{model.config.max_position_embeddings}")
    ids_j = jnp.asarray(ids, jnp.int32)
    dt = (jnp.dtype(model.config.dtype)
          if isinstance(model.config.dtype, str) else model.config.dtype)
    accept_hist = _spec_accept_hist("mtp")

    def emb(tokens_2d):
        # .astype: same compute dtype the MTP block trained on
        return model.llama.embed_tokens(
            wrap(jnp.asarray(tokens_2d, jnp.int32))).astype(
                model.config.dtype)

    with _tape.no_grad():
        cos, sin = model.llama._rope(max_len)
        # main prefill (pre-norm stream kept for the MTP pairing)
        caches = _empty_caches(model, 1, max_len)
        normed, pre, caches = model.llama.forward_cached(
            wrap(ids_j), caches, rope_len=max_len, return_prenorm=True)
        t1 = int(jnp.argmax(  # the prefill's one deliberate first-token fetch
            unwrap(model.lm_head_logits(normed[:, -1:]))[0, 0]))

        # MTP stream cache: seed with pairs (h_i, t_{i+1}) for the prompt
        mtp_cache = dict(model.llama.empty_cache_layer(1, max_len, dt),
                         pos=0, prefill=True)
        if P > 1:
            x = mtp.fuse(pre[:, : P - 1], emb(ids[:, 1:]))
            _, mtp_cache = mtp.block(x, cos, sin, kv_cache=mtp_cache)
        # rounds are jitted from here on: the static "prefill" marker
        # must not enter the traced aux (bool(tracer) raises), and
        # positions are tracked host-side and stamped before each call
        mtp_cache.pop("prefill", None)
        pos_main, pos_mtp = P, max(P - 1, 0)

        emitted = [t1]
        rounds = hits = 0          # draft-acceptance observability
        h_tail = unwrap(pre)[:, -1:]   # pre-norm hidden(s) pairing toks
        toks = [t1]                # tokens pairing h_tail rows
        while len(emitted) < max_new_tokens and (
                eos_token_id is None or emitted[-1] != eos_token_id):
            n = len(toks)
            step = _memoized_step(
                model, "_mtp_round_steps", (max_len, n),
                lambda: _MTPRoundStep(model, max_len, n), maxsize=8)
            caches = _set_pos(caches, pos_main)
            mtp_cache["pos"] = jnp.asarray(pos_mtp, jnp.int32)
            g_arr, pre2, caches, mcs = step(
                h_tail, jnp.asarray([toks], jnp.int32), caches,
                [mtp_cache])
            mtp_cache = mcs[0]
            g = np.asarray(g_arr)  # pdlint: disable=host-sync -- the round's ONE deliberate fetch: [g0, g1, draft] drive host acceptance control flow
            g0, g1, draft = int(g[0]), int(g[1]), int(g[2])
            rounds += 1
            pos_mtp += n           # the MTP stream grew by the n pairs
            if draft == g0:        # draft hit: two tokens from one forward
                hits += 1
                emitted.extend([draft, g1])
                pos_main += 2
                h_tail, toks = pre2, [draft, g1]
            else:                  # miss: the draft's cache entry is
                emitted.append(g0)  # stale — the host pos rewind parks it
                pos_main += 1
                h_tail, toks = pre2[:, :1], [g0]
            accept_hist.observe(1 if draft == g0 else 0)
            if eos_token_id is not None and eos_token_id in emitted[-2:]:
                break              # eos inside a hit pair stops the loop

    out = _finish(emitted, max_new_tokens, eos_token_id, out_dtype)
    if return_stats:
        # acceptance rate is THE speculative health metric: each hit
        # retired 2 tokens from one main forward
        return out, {"rounds": rounds, "hits": hits,
                     "acceptance": (hits / rounds) if rounds else 0.0}
    return out
