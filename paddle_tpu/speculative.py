"""Speculative decoding: a small draft model proposes ``draft_k`` tokens,
the target model verifies them in ONE chunked forward, and the longest
target-greedy-consistent prefix (plus the target's bonus token) is accepted
— per round the target runs once for up to ``draft_k + 1`` emitted tokens
instead of once per token.

Role anchor: the speculative/draft-model decode path of the reference
platform's LLM serving stack (the same serving tier as
paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu; the
reference ecosystem ships it in its llm inference recipes). TPU-native
design: both the draft proposal loop (a ``lax.scan`` of greedy steps) and
the chunked verify are single jitted computations with donated KV buffers;
rollback after a rejected suffix is just resetting the cache's scalar
``pos`` — the dense serving cache (generation.cached_attention) masks
columns ``> pos``, so stale entries beyond the accepted prefix are inert
and get overwritten by later writes.

Greedy-exactness contract: the emitted sequence is IDENTICAL to
``target.generate(..., do_sample=False)`` — speculation changes latency,
never output (the test asserts token equality).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .autograd import tape as _tape
from .generation import (_get_prefill_step, _memoized_step, _split_caches,
                         _unwrap_caches)
from .nn.layer import functional_weights as _functional_weights
from .tensor_class import unwrap, wrap


class _ProposeStep:
    """Draft proposal: feed ``seed`` (1 or 2 catch-up tokens), then scan
    ``k-1`` greedy single-token steps — one jitted dispatch for all ``k``
    proposals, donated draft KV buffers."""

    def __init__(self, model, max_len, k, seed_len):
        self._model = model

        def pure(state, seed, bufs, aux):
            caches = [{**b, **a} for b, a in zip(bufs, aux)]
            with _functional_weights(model, state), _tape.no_grad():
                hidden, caches = model.llama.forward_cached(
                    wrap(seed), caches, rope_len=max_len)
                h_last = unwrap(hidden)[:, -1:]
                first = jnp.argmax(
                    unwrap(model.lm_head_logits(wrap(h_last)))[:, -1, :],
                    axis=-1).astype(jnp.int32)

                def body(carry, _):
                    tok, caches = carry
                    hidden, caches = model.llama.forward_cached(
                        wrap(tok[:, None]), caches, rope_len=max_len)
                    nxt = jnp.argmax(
                        unwrap(model.lm_head_logits(hidden))[:, -1, :],
                        axis=-1).astype(jnp.int32)
                    return (nxt, caches), nxt

                if k > 1:
                    (_, caches), rest = jax.lax.scan(
                        body, (first, caches), None, length=k - 1)
                    toks = jnp.concatenate([first[None], rest], axis=0)
                else:
                    toks = first[None]
            nb, na = _split_caches(_unwrap_caches(caches))
            return toks.T, nb, na  # [B, k]

        self._jitted = jax.jit(pure, donate_argnums=(2,))
        self._state = dict(model.functional_state())

    def __call__(self, seed, caches):
        bufs, aux = _split_caches(caches)
        toks, nb, na = self._jitted(self._state, seed, bufs, aux)
        return toks, [{**b, **a} for b, a in zip(nb, na)]


class _VerifyStep:
    """Target verify: one chunked forward over [last, d_1..d_k]; returns
    the target's greedy token at every chunk position."""

    def __init__(self, model, max_len, chunk_len):
        self._model = model

        def pure(state, chunk, bufs, aux):
            caches = [{**b, **a} for b, a in zip(bufs, aux)]
            with _functional_weights(model, state), _tape.no_grad():
                hidden, caches = model.llama.forward_cached(
                    wrap(chunk), caches, rope_len=max_len)
                logits = unwrap(model.lm_head_logits(hidden))
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            nb, na = _split_caches(_unwrap_caches(caches))
            return greedy, nb, na  # [B, chunk_len]

        self._jitted = jax.jit(pure, donate_argnums=(2,))
        self._state = dict(model.functional_state())

    def __call__(self, chunk, caches):
        bufs, aux = _split_caches(caches)
        greedy, nb, na = self._jitted(self._state, chunk, bufs, aux)
        return greedy, [{**b, **a} for b, a in zip(nb, na)]


def _set_pos(caches, pos):
    for c in caches:
        c["pos"] = jnp.asarray(pos, jnp.int32)
    return caches


def _prefill(model, ids, max_len):
    """Whole-prompt prefill (generation's one-shot jitted step); returns
    (greedy_next, caches)."""
    step = _get_prefill_step(model, max_len, ragged=False)
    lengths = jnp.full((ids.shape[0],), ids.shape[1], jnp.int32)
    last, caches = step(ids, lengths, None)
    return jnp.argmax(last, axis=-1).astype(jnp.int32), caches


def _normalize_request(input_ids):
    """Shared batch-1 request normalization: returns (ids [1,P] np,
    out_dtype); raises on batched input (the dense cache keeps one scalar
    write position)."""
    ids = np.asarray(unwrap(input_ids) if hasattr(input_ids, "shape")
                     else input_ids)
    out_dtype = ids.dtype
    if ids.ndim == 1:
        ids = ids[None]
    if ids.shape[0] != 1:
        raise ValueError(
            "speculative decoding is per-request (batch 1); run rows "
            "separately or use model.generate for batched decode")
    return ids, out_dtype


def _finish(emitted, max_new_tokens, eos_token_id, out_dtype):
    """Shared emit epilogue: truncate to the budget, cut at eos, wrap in
    the request dtype."""
    emitted = emitted[:max_new_tokens]
    if eos_token_id is not None and eos_token_id in emitted:
        emitted = emitted[: emitted.index(eos_token_id) + 1]
    return wrap(jnp.asarray(np.asarray(emitted, out_dtype)[None]))


def speculative_generate(target, draft, input_ids, max_new_tokens=20,
                         draft_k=4, eos_token_id=None):
    """Greedy speculative decode of ``input_ids`` [1, P] → [1, P + new].

    Batch size 1 (per-request serving): the dense cache keeps ONE scalar
    write position, and rows accepting different prefix lengths would need
    per-row rollback. Output is exactly ``target.generate`` greedy.
    """
    ids, out_dtype = _normalize_request(input_ids)
    B, P = ids.shape
    k = int(draft_k)
    if k < 1:
        raise ValueError(f"draft_k must be >= 1, got {draft_k}")
    max_len = P + max_new_tokens + k + 2
    for name, m in (("target", target), ("draft", draft)):
        limit = m.config.max_position_embeddings
        if max_len > limit:
            raise ValueError(
                f"speculative_generate: prompt+new(+{k + 2} speculation "
                f"slack) = {max_len} exceeds the {name} model's "
                f"max_position_embeddings {limit}")
    ids = jnp.asarray(ids, jnp.int32)

    t0, tgt_caches = _prefill(target, ids, max_len)
    _, dft_caches = _prefill(draft, ids, max_len)
    tgt_pos, dft_pos = P, P

    emitted = [int(t0[0])]
    last = int(t0[0])
    catchup = []  # accepted tokens not yet written to the draft cache

    def propose_step(seed_len):
        return _memoized_step(
            draft, "_spec_propose_steps", (max_len, k, seed_len),
            lambda: _ProposeStep(draft, max_len, k, seed_len), maxsize=8)

    verify_step = _memoized_step(
        target, "_spec_verify_steps", (max_len, k + 1),
        lambda: _VerifyStep(target, max_len, k + 1), maxsize=8)

    while len(emitted) < max_new_tokens and \
            (eos_token_id is None or emitted[-1] != eos_token_id):
        seed = jnp.asarray([catchup + [last]], jnp.int32)   # [1, 1|2]
        dft_caches = _set_pos(dft_caches, dft_pos)
        proposals, dft_caches = propose_step(seed.shape[1])(seed, dft_caches)
        props = [int(x) for x in np.asarray(proposals[0])]   # d_1..d_k

        chunk = jnp.asarray([[last] + props], jnp.int32)     # [1, k+1]
        tgt_caches = _set_pos(tgt_caches, tgt_pos)
        greedy, tgt_caches = verify_step(chunk, tgt_caches)
        g = [int(x) for x in np.asarray(greedy[0])]          # g_0..g_k

        m = 0
        while m < k and props[m] == g[m]:
            m += 1
        accepted = props[:m] + [g[m]]                        # ≤ k+1 tokens

        # context now ends ...last, d_1..d_m, g_m; g_m is the new `last`
        ctx_len_old = tgt_pos + 1        # context length BEFORE this round
        tgt_pos = ctx_len_old + m        # target holds ctx + d_1..d_m
        if m == k:                       # draft never wrote d_k's entry
            dft_pos = ctx_len_old + (k - 1)
            catchup = [props[-1]]
        else:                            # d_1..d_m all in the draft cache
            dft_pos = ctx_len_old + m
            catchup = []
        last = accepted[-1]
        emitted.extend(accepted)
        if eos_token_id is not None and eos_token_id in accepted:
            break

    # same convention as model.generate: only the NEW tokens, input dtype
    return _finish(emitted, max_new_tokens, eos_token_id, out_dtype)


def mtp_speculative_generate(model, input_ids, max_new_tokens=20,
                             eos_token_id=None, return_stats=False):
    """Self-speculative greedy decode for DeepSeek models trained with
    multi-token prediction (``num_nextn_predict_layers >= 1``): the FIRST
    MTP depth drafts one token per round from the main model's PRE-norm
    hidden stream (the MTP block keeps its own latent cache over the
    shifted sequence, exactly the pairing it was trained on), and a
    2-token cached verify accepts or corrects (arXiv:2412.19437 §2.2
    inference usage — the "free" extra token per forward).

    Output is EXACTLY ``model.generate`` greedy — the draft only changes
    how many tokens each main-model forward retires. Batch 1 (the dense
    cache keeps one write position; see speculative_generate). This v1
    drives the rounds as a host loop of EAGER cached forwards — the
    correctness contract and stream bookkeeping live here; porting the
    rounds onto speculative_generate's memoized jitted steps is the
    performance follow-up and changes no semantics."""
    from .generation import _empty_caches

    mtp_layers = getattr(model, "mtp_layers", None)
    if not mtp_layers:
        raise ValueError(
            "mtp_speculative_generate needs a model built with "
            "num_nextn_predict_layers >= 1 (the MTP draft module)")
    mtp = mtp_layers[0]
    ids, out_dtype = _normalize_request(input_ids)
    B, P = ids.shape
    max_len = P + max_new_tokens + 3
    if max_len > model.config.max_position_embeddings:
        raise ValueError(
            f"prompt+new(+3 speculation slack) = {max_len} exceeds "
            f"max_position_embeddings "
            f"{model.config.max_position_embeddings}")
    ids_j = jnp.asarray(ids, jnp.int32)
    dt = (jnp.dtype(model.config.dtype)
          if isinstance(model.config.dtype, str) else model.config.dtype)

    def emb(tokens_2d):
        # .astype: same compute dtype the MTP block trained on
        return model.llama.embed_tokens(
            wrap(jnp.asarray(tokens_2d, jnp.int32))).astype(
                model.config.dtype)

    with _tape.no_grad():
        cos, sin = model.llama._rope(max_len)
        # main prefill (pre-norm stream kept for the MTP pairing)
        caches = _empty_caches(model, 1, max_len)
        normed, pre, caches = model.llama.forward_cached(
            wrap(ids_j), caches, rope_len=max_len, return_prenorm=True)
        t1 = int(jnp.argmax(
            unwrap(model.lm_head_logits(normed[:, -1:]))[0, 0]))

        # MTP stream cache: seed with pairs (h_i, t_{i+1}) for the prompt
        mtp_cache = dict(model.llama.empty_cache_layer(1, max_len, dt),
                         pos=0, prefill=True)
        if P > 1:
            x = mtp.fuse(pre[:, : P - 1], emb(ids[:, 1:]))
            _, mtp_cache = mtp.block(x, cos, sin, kv_cache=mtp_cache)

        emitted = [t1]
        rounds = hits = 0          # draft-acceptance observability
        pending = t1               # exact, not yet written to the cache
        h_tail = pre[:, -1:]       # pre-norm hidden(s) pairing the toks
        toks = [t1]                # tokens pairing h_tail rows
        while len(emitted) < max_new_tokens and (
                eos_token_id is None or emitted[-1] != eos_token_id):
            # 1. extend the MTP stream with the completed pairs, draft
            x = mtp.fuse(h_tail, emb([toks]))
            h_m, mtp_cache = mtp.block(x, cos, sin, kv_cache=mtp_cache)
            draft = int(jnp.argmax(unwrap(
                model.lm_head_logits(mtp.norm(h_m[:, -1:])))[0, 0]))
            # 2. one 2-token verify forward retires up to 2 tokens
            normed2, pre2, caches = model.llama.forward_cached(
                wrap(jnp.asarray([[pending, draft]], jnp.int32)), caches,
                rope_len=max_len, return_prenorm=True)
            logits2 = unwrap(model.lm_head_logits(normed2))
            g0 = int(jnp.argmax(logits2[0, 0]))
            g1 = int(jnp.argmax(logits2[0, 1]))
            rounds += 1
            if draft == g0:        # draft hit: two tokens from one forward
                hits += 1
                emitted.extend([draft, g1])
                pending = g1
                h_tail, toks = pre2, [draft, g1]
            else:                  # miss: rewind the draft's cache entry
                emitted.append(g0)
                pending = g0
                for c in caches:
                    c["pos"] = c["pos"] - 1
                h_tail, toks = pre2[:, :1], [g0]
            if eos_token_id is not None and eos_token_id in emitted[-2:]:
                break              # eos inside a hit pair stops the loop

    out = _finish(emitted, max_new_tokens, eos_token_id, out_dtype)
    if return_stats:
        # acceptance rate is THE speculative health metric: each hit
        # retired 2 tokens from one main forward
        return out, {"rounds": rounds, "hits": hits,
                     "acceptance": (hits / rounds) if rounds else 0.0}
    return out
