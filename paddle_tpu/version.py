"""paddle.version parity (generated python/paddle/version/__init__.py)."""
full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
commit = "paddle-tpu-native"
istaged = False
with_pip_cuda_libraries = "OFF"


def show():
    print(f"full_version: {full_version}")
    print(f"commit: {commit}")


def cuda():
    """CUDA version — False (not a CUDA build; the accelerator is TPU)."""
    return False


def cudnn():
    return False


def xpu():
    return False


def nccl():
    return 0


def tpu():
    """Non-reference extra: the accelerator this build targets."""
    return True
