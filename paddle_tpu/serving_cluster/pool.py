"""Worker pool: lease-backed membership + queue-depth-aware placement.

The router's view of the serving tier. Workers register into the pool the
way trainers join an elastic job — a TCPStore lease heartbeat
(``distributed/elastic.py``) plus a metadata record (address, role, kv
handoff channel) — and the pool watches both from the router process:

- **membership** is lease freshness (``ElasticManager.alive_ranks``): a
  worker whose heartbeat lapses is LOST, recorded as a
  ``router.worker_lost`` flight-recorder event, and its in-flight
  requests requeue at the router;
- **occupancy** is the worker's own ``/health`` surface (active slots +
  queue depth — the stats() snapshot both engines already publish),
  polled on the same cadence, plus a local ``pending`` count of
  placements this router has issued but not yet seen finish — the
  queue-depth-aware part of least-loaded placement that a stale poll
  alone would miss.
"""
from __future__ import annotations

import json
import random
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from ..analysis.threads.witness import make_lock
from ..chaos import inject as _chaos
from ..distributed.elastic import ElasticManager
from ..distributed.log_utils import get_logger
from ..observability import flightrecorder as _frec
from ..observability.catalog import ROUTER_WORKERS

__all__ = ["WorkerInfo", "WorkerPool", "jittered"]

# process-local jitter source for backoff/retry sleeps; seedable from
# tests (bounds are pinned there), never from production paths
_JITTER_RNG = random.Random()


def jittered(base_s: float, frac: float = 0.5,
             rng: Optional[random.Random] = None) -> float:
    """``base_s`` spread uniformly over ``[base*(1-frac), base*(1+frac)]``.
    Every busy-backoff and retry sleep routes through here: a fixed
    constant synchronizes the retries of every caller that backed off at
    the same mass-busy event, so they all stampede back in the same
    instant — jitter decorrelates the retry times."""
    lo = max(0.0, 1.0 - frac)
    return float(base_s) * ((rng or _JITTER_RNG).uniform(lo, 1.0 + frac))


class WorkerInfo:
    """One worker as the router sees it: identity (from the store
    metadata), liveness (from the lease), and load (from /health polls +
    local pending placements)."""

    __slots__ = ("replica_id", "role", "host", "port", "pid", "kv_channel",
                 "alive", "lease_age_s", "active", "queued", "pending",
                 "probe_ok", "marked_dead_at", "busy_until", "draining",
                 "finished", "probed_at", "drain_rate", "stats", "kv")

    def __init__(self, replica_id: int, meta: dict):
        self.replica_id = replica_id
        self.role = meta.get("role", "unified")
        self.host = meta.get("host", "127.0.0.1")
        self.port = int(meta.get("port", 0))
        self.pid = meta.get("pid")
        self.kv_channel = meta.get("kv_channel")
        self.alive = True
        self.lease_age_s: Optional[float] = None
        self.active = 0
        self.queued = 0
        self.pending = 0     # placements issued but not finished HERE
        self.probe_ok = False
        self.marked_dead_at: Optional[float] = None  # monotonic, router-side
        self.busy_until = 0.0  # admission backpressure (429) backoff
        self.draining = False  # drain in progress: placement excluded
        # drain-rate estimate off successive /health polls (finished
        # counter deltas over poll gaps) — feeds the router's computed
        # Retry-After when a worker's 429 carries no hint
        self.finished = 0
        self.probed_at: Optional[float] = None
        self.drain_rate: Optional[float] = None  # requests/s, EWMA
        # the worker's last full stats() snapshot off /health — what the
        # router's federation collector turns into per-replica
        # cluster_* time series (empty until the first probe)
        self.stats: dict = {}
        # the worker's published KV summary (prefix-hash index top,
        # headroom, hit ratio) off the store metadata — the
        # prefix-affinity / capacity feedstock; refreshed every poll
        self.kv = meta.get("kv")

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def score(self) -> int:
        """Placement score: lower is emptier. Active slots + the worker's
        own queue + this router's not-yet-visible placements."""
        return self.active + self.queued + self.pending

    def snapshot(self) -> dict:
        return {
            "replica_id": self.replica_id,
            "role": self.role,
            "url": self.url,
            "alive": self.alive,
            "lease_age_s": self.lease_age_s,
            "active": self.active,
            "queued": self.queued,
            "pending": self.pending,
            "probe_ok": self.probe_ok,
            "busy": self.busy_until > time.monotonic(),
            "draining": self.draining,
            "drain_rate": self.drain_rate,
            "kv": self.kv,
        }


class WorkerPool:
    """Membership + occupancy over an ElasticManager store view.

    The pool never heartbeats itself (the router holds no lease); it is
    the launcher-side watcher pattern of ``elastic.stale_ranks`` applied
    to serving: membership is what the store says, not what the last
    socket did.
    """

    def __init__(self, store=None, *, endpoint: Optional[str] = None,
                 world_size: int = 1, job_id: str = "serve",
                 ttl: float = 5.0, probe_timeout: float = 2.0,
                 on_worker_lost: Optional[Callable[[WorkerInfo, str],
                                                   None]] = None):
        self._mgr = ElasticManager(store=store, endpoint=endpoint,
                                   rank=-1, world_size=world_size,
                                   ttl=ttl, job_id=job_id)
        self.world_size = world_size
        self.ttl = float(ttl)
        self._probe_timeout = float(probe_timeout)
        self._on_worker_lost = on_worker_lost
        self._lock = make_lock("WorkerPool._lock")
        self._workers: Dict[int, WorkerInfo] = {}
        self._rr = 0  # least-loaded tie-break rotates
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- lifecycle -----------------------------------------------------
    def start(self, interval: Optional[float] = None) -> "WorkerPool":
        if self._thread is not None:
            return self
        interval = interval if interval is not None else self.ttl / 3.0

        def watch():
            while not self._stop.wait(interval):
                try:
                    self.refresh()
                except Exception as e:
                    # a pool that cannot refresh keeps serving its last
                    # view; the blindness is worth a line, not a crash
                    get_logger().warning(
                        "worker pool refresh failed (%s: %s); serving "
                        "the previous membership view",
                        type(e).__name__, e)

        self._thread = threading.Thread(target=watch, daemon=True,
                                        name="worker-pool-watch")
        self._thread.start()
        return self

    def close(self):
        self._stop.set()

    # ---- membership ----------------------------------------------------
    def refresh(self):
        """One poll: lease view from the store, then /health occupancy
        from each live worker. Store/network I/O runs OUTSIDE the lock;
        results apply under it."""
        alive = self._mgr.alive_ranks()
        joined: List[Tuple[int, dict]] = []
        refreshed: Dict[int, dict] = {}
        ages: Dict[int, Optional[float]] = {}
        with self._lock:
            known = dict(self._workers)
        for r in alive:
            ages[r] = self._mgr.lease_age(r)
            w = known.get(r)
            if w is None:
                meta = self._mgr.peer_metadata(r)
                if meta is not None:
                    joined.append((r, meta))
            else:
                # refetch metadata for every live rank each poll: a
                # dead-or-unprobeable worker with a fresh lease may be a
                # supervised RESTART of the same replica whose address
                # (port, pid, kv channel) is new — rejoining on the dead
                # incarnation's port would bounce placements into a
                # closed socket forever — and a HEALTHY worker
                # republishes its kv summary (prefix hashes + headroom)
                # on the lease cadence, which only this read can see
                meta = self._mgr.peer_metadata(r)
                if meta is not None:
                    refreshed[r] = meta
        lost: List[WorkerInfo] = []
        with self._lock:
            for r, meta in joined:
                if r in self._workers:
                    continue
                w = WorkerInfo(r, meta)
                w.lease_age_s = ages.get(r)
                self._workers[r] = w
                rec = _frec.RECORDER
                if rec.enabled:
                    rec.record(_frec.EV_ROUTER_WORKER_JOIN,
                               replica_id=r, role=w.role, url=w.url)
                get_logger().info("worker pool: replica %s (%s) joined at "
                                  "%s", r, w.role, w.url)
            for r, w in self._workers.items():
                if r in alive:
                    w.lease_age_s = ages.get(r)
                    meta = refreshed.get(r)
                    if meta is not None:
                        w.kv = meta.get("kv", w.kv)
                    if meta is not None and meta.get("pid") != w.pid:
                        # a different pid behind the same replica id:
                        # the supervisor respawned it — adopt the fresh
                        # incarnation's address wholesale
                        w.host = meta.get("host", w.host)
                        w.port = int(meta.get("port", w.port))
                        w.pid = meta.get("pid")
                        w.kv_channel = meta.get("kv_channel")
                        w.role = meta.get("role", w.role)
                    if not w.alive and self._beat_after_death(w):
                        # rejoin ONLY on a heartbeat newer than the
                        # moment the router observed the death: a freshly
                        # killed worker's lease stays "fresh" for up to
                        # ttl, and rejoining on that stale stamp would
                        # bounce requests into a dead socket until it
                        # lapses (connection blips DO re-stamp, so they
                        # rejoin within one heartbeat period)
                        w.alive = True
                        w.pending = 0
                        # a rejoin is a fresh incarnation: a drain that
                        # ended in lease release must not haunt it
                        w.draining = False
                elif w.alive:
                    self._mark_lost_locked(w, "lease")
                    lost.append(w)
            probe_targets = [(w.replica_id, w.url)
                             for w in self._workers.values() if w.alive]
        for w in lost:
            self._notify_lost(w, "lease")
        # occupancy probes (network) after the lock is released
        for rid, url in probe_targets:
            self._probe(rid, url)
        self.refresh_gauges()

    def _probe(self, replica_id: int, url: str):
        fault = _chaos.on("pool.probe", replica_id=replica_id)
        if fault is not None and fault.action == "probe_fail":
            health, ok = None, False
        else:
            try:
                with urllib.request.urlopen(
                        url + "/health", timeout=self._probe_timeout) as r:
                    health = json.loads(r.read())
                ok = True
            except Exception as e:
                get_logger().debug("worker pool: /health probe of replica "
                                   "%s failed (%s: %s)", replica_id,
                                   type(e).__name__, e)
                health, ok = None, False
        with self._lock:
            w = self._workers.get(replica_id)
            if w is None:
                return
            w.probe_ok = ok
            if ok:
                w.active = int(health.get("active", 0))
                w.queued = int(health.get("queued", 0))
                stats = health.get("stats") or {}
                w.stats = stats
                fin = stats.get("requests_finished")
                if fin is not None:
                    now = time.monotonic()
                    if (w.probed_at is not None and now > w.probed_at
                            and int(fin) >= w.finished):
                        inst = (int(fin) - w.finished) / (now - w.probed_at)
                        w.drain_rate = (inst if w.drain_rate is None
                                        else 0.5 * w.drain_rate
                                        + 0.5 * inst)
                    w.finished = int(fin)
                    w.probed_at = now
                # a worker draining itself (operator hit its /drain
                # directly) is honored the same as a router-initiated
                # drain: no new placements land on it
                if health.get("draining"):
                    w.draining = True

    def _beat_after_death(self, w: WorkerInfo) -> bool:
        """True when the worker's newest lease stamp postdates the moment
        it was marked dead (CLOCK_MONOTONIC is host-wide, so the worker's
        stamp and the router's clock compare directly — the same
        assumption elastic leases already make)."""
        if w.marked_dead_at is None or w.lease_age_s is None:
            return True
        return (time.monotonic() - w.lease_age_s) > w.marked_dead_at

    def _mark_lost_locked(self, w: WorkerInfo, reason: str):
        w.alive = False
        w.probe_ok = False
        w.pending = 0
        w.marked_dead_at = time.monotonic()
        rec = _frec.RECORDER
        if rec.enabled:
            rec.record(_frec.EV_ROUTER_WORKER_LOST,
                       replica_id=w.replica_id, reason=reason)
        get_logger().warning("worker pool: replica %s (%s) lost (%s)",
                             w.replica_id, w.role, reason)

    def _notify_lost(self, w: WorkerInfo, reason: str):
        if self._on_worker_lost is not None:
            try:
                self._on_worker_lost(w, reason)
            except Exception as e:
                get_logger().warning(
                    "worker pool: on_worker_lost callback failed "
                    "(%s: %s)", type(e).__name__, e)

    def mark_dead(self, replica_id: int, reason: str = "connection"):
        """Router-observed death (a placement's socket broke): take the
        worker out of rotation NOW — the lease takes up to ttl to lapse,
        and routing more requests into a dead socket wastes their retry
        budget. A fresh lease on a later refresh rejoins it."""
        lost = None
        with self._lock:
            w = self._workers.get(replica_id)
            if w is not None and w.alive:
                self._mark_lost_locked(w, reason)
                lost = w
        if lost is not None:
            self._notify_lost(lost, reason)

    # ---- placement -----------------------------------------------------
    def select(self, roles: Optional[Tuple[str, ...]] = None,
               exclude: Tuple[int, ...] = ()) -> Optional[WorkerInfo]:
        """Least-loaded live worker (optionally role-filtered), counting
        the placement into ``pending`` so concurrent placements spread;
        callers MUST ``release()`` the worker when the attempt ends."""
        now = time.monotonic()
        with self._lock:
            live = [w for w in self._workers.values()
                    if w.alive and not w.draining
                    and w.replica_id not in exclude
                    and w.busy_until <= now
                    and (roles is None or w.role in roles)]
            if not live:
                return None
            self._rr += 1
            rr = self._rr
            w = min(live, key=lambda w: (w.score(),
                                         (w.replica_id + rr)
                                         % (max(x.replica_id
                                                for x in live) + 1)))
            w.pending += 1
            return w

    def mark_busy(self, replica_id: int, backoff_s: float = 0.5):
        """Admission backpressure (a worker answered 429): take it out of
        SELECTION for ~``backoff_s`` without declaring it dead — its
        engine is healthy, just full. Contrast mark_dead: a busy worker
        keeps its lease, rejoins rotation by itself, and is never
        failed over to another replica's retry budget. The backoff is
        JITTERED (±50%): after a mass-busy event every router would
        otherwise re-admit the same worker at the same instant."""
        with self._lock:
            w = self._workers.get(replica_id)
            if w is not None:
                w.busy_until = time.monotonic() + jittered(backoff_s)

    def set_draining(self, replica_id: int, draining: bool = True):
        """Mark a worker draining (router-initiated drain): it stays
        alive and probed but receives no new placements; migration picks
        destinations through the same select(), which skips it."""
        with self._lock:
            w = self._workers.get(replica_id)
            if w is not None:
                w.draining = bool(draining)

    def get(self, replica_id: int) -> Optional[WorkerInfo]:
        """The WorkerInfo for a replica (None when unknown) — the pinned
        lookup a migration continuation uses to follow a stream to the
        destination the drain chose."""
        with self._lock:
            return self._workers.get(replica_id)

    def claim(self, w: WorkerInfo):
        """Count a placement onto a SPECIFIC worker into ``pending`` —
        the select()-side bump for callers that pinned their target (a
        migration continuation follows the stream to the destination the
        drain chose). Pair with release() like a select()."""
        with self._lock:
            w.pending += 1

    def release(self, w: WorkerInfo):
        with self._lock:
            if w.pending > 0:
                w.pending -= 1

    def has_role(self, role: str) -> bool:
        with self._lock:
            return any(w.alive and w.role == role
                       for w in self._workers.values())

    # ---- views ---------------------------------------------------------
    def workers(self) -> List[dict]:
        with self._lock:
            return [w.snapshot() for w in self._workers.values()]

    def worker_stats(self) -> List[Tuple[int, bool, dict]]:
        """``(replica_id, alive, last stats snapshot)`` per worker — the
        federation collector's feed (the snapshots are the dicts the
        probe already fetched; no extra network I/O per sample)."""
        with self._lock:
            return [(w.replica_id, w.alive, dict(w.stats))
                    for w in self._workers.values()]

    def alive_count(self) -> int:
        with self._lock:
            return sum(w.alive for w in self._workers.values())

    def refresh_gauges(self):
        with self._lock:
            alive = sum(w.alive for w in self._workers.values())
            lost = len(self._workers) - alive
        ROUTER_WORKERS.set(alive, state="alive")
        ROUTER_WORKERS.set(lost, state="lost")

    def wait_for_workers(self, n: int, timeout: float = 120.0) -> bool:
        """Block until ``n`` workers have joined (registered lease +
        metadata and answered a /health probe) or the deadline passes."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.refresh()
            with self._lock:
                ready = sum(1 for w in self._workers.values()
                            if w.alive and w.probe_ok)
            if ready >= n:
                return True
            time.sleep(0.2)
        return False
