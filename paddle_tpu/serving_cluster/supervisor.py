"""Worker supervision: restart-with-backoff, crash-loop containment, and
poison-request quarantine — the self-healing half of the serving tier.

PR 6's launcher spawned worker subprocesses and forgot them: a SIGKILL'd
worker's capacity was gone until an operator intervened, and a request
that deterministically crashes its engine (OOM, kernel assert, poisoned
input) was failover-retried onto the next worker — serial crash-loop
amplification. This module closes both holes:

- :class:`WorkerSupervisor` OWNS the worker subprocesses. A monitor
  thread detects death by ``Popen.poll()`` (waitpid) and — optionally —
  by sustained lease silence reported by the pool, and respawns the
  worker with the SAME role/replica_id under exponential backoff with
  jitter (:class:`RestartBackoff`). The restarted worker registers a
  fresh lease and rejoins the pool warm; the router's knee capacity
  recovers without an operator.
- A per-worker :class:`CircuitBreaker` contains crash loops: more than
  ``threshold`` restarts inside ``window_s`` holds the worker OPEN (no
  further restarts; the router's ``/health`` reports the tier degraded)
  instead of burning CPU respawning a process that dies on arrival.
- :class:`QuarantineLedger` + :class:`Deathnote` contain poison
  requests: before every decode dispatch the engine arms an atomic
  tmpfile naming the request ids entering that step (erased on step
  success), so a death blames exactly the rids in the fatal dispatch —
  not every request the router had in flight on the worker. A rid
  implicated in ≥ 2 distinct worker deaths is quarantined: the router
  answers a typed 422 ``code=request_quarantined`` and never retries it.
- On every death the supervisor sweeps the workers' incident directory
  into a cluster-level index (``incidents/INDEX.jsonl``) and persists
  its own state (restart history, breaker states, quarantine ledger) as
  ``SUPERVISOR.json`` — ``scripts/read_incident.py --index`` renders
  both.

See docs/SERVING.md "Self-healing & crash containment" for the
supervision tree and the operator runbook.
"""
from __future__ import annotations

import json
import os
import random
import subprocess
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ..analysis.threads.witness import make_lock
from ..distributed.log_utils import get_logger
from ..observability import flightrecorder as _frec
from ..observability.catalog import REQUESTS_QUARANTINED, WORKER_RESTARTS

__all__ = ["RestartBackoff", "CircuitBreaker", "QuarantineLedger",
           "Deathnote", "WorkerSupervisor", "QUARANTINE_THRESHOLD"]

#: distinct worker deaths that quarantine a request id. Two is the
#: containment bound the chaos gate pins: a poison request costs the
#: tier at most two workers before it is refused typed.
QUARANTINE_THRESHOLD = 2


class RestartBackoff:
    """Exponential restart backoff with jitter, per worker.

    ``next_delay()`` returns ``min(max_s, base_s * factor**attempt)``
    spread uniformly over ``[d*(1-jitter_frac), d*(1+jitter_frac)]`` and
    bumps the attempt counter; ``reset()`` (called after the worker
    survives a sustained-health window) starts the ladder over. Jitter
    matters for the same reason the pool's busy backoff is jittered: a
    correlated mass death would otherwise respawn every worker in the
    same instant, synchronizing their compile storms."""

    def __init__(self, base_s: float = 0.5, max_s: float = 30.0,
                 factor: float = 2.0, jitter_frac: float = 0.5,
                 rng: Optional[random.Random] = None):
        self.base_s = float(base_s)
        self.max_s = float(max_s)
        self.factor = float(factor)
        self.jitter_frac = float(jitter_frac)
        self._rng = rng or random.Random()
        self.attempt = 0

    def next_delay(self) -> float:
        d = min(self.max_s, self.base_s * (self.factor ** self.attempt))
        self.attempt += 1
        lo = max(0.0, 1.0 - self.jitter_frac)
        return d * self._rng.uniform(lo, 1.0 + self.jitter_frac)

    def reset(self):
        self.attempt = 0


class CircuitBreaker:
    """Per-worker crash-loop containment: at most ``threshold`` restarts
    inside a sliding ``window_s``. ``allow()`` is asked before every
    restart — stamps older than the window age out (a worker that has
    been healthy for a while earns its full restart budget back), and
    the restart that would exceed the budget TRIPS the breaker open.
    Open holds: no further restarts until an operator ``reset()`` — a
    worker that dies ``threshold`` times in the window is broken in a
    way a fourth respawn will not fix, and the router's ``/health``
    should say "degraded", not flap. ``clock`` is injectable for the
    fake-clock tests."""

    def __init__(self, threshold: int = 5, window_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic):
        if int(threshold) < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        self.threshold = int(threshold)
        self.window_s = float(window_s)
        self._clock = clock
        self._stamps: deque = deque()
        self.open_since: Optional[float] = None

    def _prune(self, now: float):
        while self._stamps and self._stamps[0] <= now - self.window_s:
            self._stamps.popleft()

    @property
    def is_open(self) -> bool:
        return self.open_since is not None

    def allow(self) -> bool:
        """True when one more restart is within budget (and records it);
        False trips/holds the breaker open."""
        now = self._clock()
        self._prune(now)
        if self.open_since is not None:
            return False
        if len(self._stamps) >= self.threshold:
            self.open_since = now
            return False
        self._stamps.append(now)
        return True

    def reset(self):
        """Operator intervention: close the breaker and forget history."""
        self._stamps.clear()
        self.open_since = None

    def state(self) -> dict:
        now = self._clock()
        self._prune(now)
        return {"open": self.is_open,
                "restarts_in_window": len(self._stamps),
                "threshold": self.threshold,
                "window_s": self.window_s}


class Deathnote:
    """The pre-dispatch blame record: an atomic tmpfile naming the
    request ids entering the engine's CURRENT step, erased when the step
    completes. If the process dies mid-dispatch the file survives it, so
    the supervisor blames exactly the rids in the fatal dispatch instead
    of implicating every request the router had in flight on the worker
    (queued and mid-prefill rids were not in the dispatch that died).

    Write cost is one small file rename per dispatch — the engine only
    arms it when a deathnote is configured (cluster workers), solo
    engines never pay it."""

    def __init__(self, path: str):
        self.path = str(path)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)

    def arm(self, rids: List[str]):
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"pid": os.getpid(), "ts": time.time(),
                       "rids": [str(r) for r in rids]}, f)
        os.replace(tmp, self.path)

    def clear(self):
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass

    @staticmethod
    def read(path: str) -> Optional[List[str]]:
        """The armed rids at ``path`` (None when the file is absent —
        the worker died between steps — or unreadable mid-write)."""
        try:
            with open(path, encoding="utf-8") as f:
                note = json.load(f)
            return [str(r) for r in note.get("rids") or ()]
        except (OSError, ValueError):
            return None


class QuarantineLedger:
    """Which request ids were implicated in which worker deaths, and
    which crossed the quarantine threshold. Thread-safe: the supervisor
    monitor thread records deaths, router handler threads query before
    every placement attempt."""

    def __init__(self, threshold: int = QUARANTINE_THRESHOLD):
        self.threshold = int(threshold)
        self._lock = make_lock("QuarantineLedger._lock")
        self._deaths: Dict[str, List[dict]] = {}   # rid -> death records
        self._quarantined: Dict[str, dict] = {}    # rid -> final record
        self._n_deaths = 0

    def record_death(self, replica_id: int, death_key, rids,
                     precise: bool = True) -> List[str]:
        """One worker death implicating ``rids`` (the deathnote's step
        batch when ``precise``, the router's in-flight journal
        otherwise). ``death_key`` identifies the death (the dead child's
        pid) so a death observed twice — by the router's broken socket
        AND the monitor's waitpid — counts once. Returns the rids this
        death pushed over the threshold."""
        newly: List[str] = []
        with self._lock:
            self._n_deaths += 1
            for rid in rids:
                rid = str(rid)
                recs = self._deaths.setdefault(rid, [])
                if any(r["death_key"] == death_key for r in recs):
                    continue
                recs.append({"death_key": death_key,
                             "replica_id": int(replica_id),
                             "precise": bool(precise),
                             "ts": time.time()})
                if (rid not in self._quarantined
                        and len(recs) >= self.threshold):
                    self._quarantined[rid] = {
                        "deaths": len(recs),
                        "replicas": sorted({r["replica_id"]
                                            for r in recs}),
                        "ts": time.time()}
                    newly.append(rid)
        rec = _frec.RECORDER
        for rid in newly:
            REQUESTS_QUARANTINED.inc()
            if rec.enabled:
                with self._lock:
                    q = dict(self._quarantined[rid])
                rec.record(_frec.EV_SCHED_QUARANTINE, rid=rid,
                           deaths=q["deaths"], replicas=q["replicas"])
            get_logger().warning(
                "quarantine: request %s implicated in %s distinct worker "
                "deaths — refused from now on (typed 422)", rid,
                self.threshold)
        return newly

    def is_quarantined(self, rid) -> bool:
        with self._lock:
            return str(rid) in self._quarantined

    def quarantined(self) -> List[str]:
        with self._lock:
            return sorted(self._quarantined)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "threshold": self.threshold,
                "deaths_recorded": self._n_deaths,
                "implicated": {rid: [dict(r) for r in recs]
                               for rid, recs in self._deaths.items()},
                "quarantined": {rid: dict(q)
                                for rid, q in self._quarantined.items()},
            }


class _Supervised:
    """One worker under supervision: its spawn closure, live process,
    restart history, backoff ladder and breaker. All mutation happens
    under the supervisor's lock."""

    __slots__ = ("replica_id", "spawn", "proc", "incarnation",
                 "backoff", "breaker", "restarts", "next_restart_at",
                 "held_open", "last_start", "blamed_pids", "last_exit")

    def __init__(self, replica_id: int, spawn, proc, backoff, breaker):
        self.replica_id = int(replica_id)
        self.spawn = spawn          # (replica_id, incarnation) -> Popen
        self.proc = proc
        self.incarnation = 0
        self.backoff = backoff
        self.breaker = breaker
        self.restarts: List[dict] = []
        self.next_restart_at: Optional[float] = None
        self.held_open = False
        self.last_start = time.monotonic()
        self.blamed_pids = set()
        self.last_exit: Optional[int] = None


class WorkerSupervisor:
    """Owns worker subprocesses: spawn, watch, blame, restart, contain.

    The launcher registers each worker with :meth:`adopt` (the spawn
    closure is re-invoked on restart with a bumped incarnation number —
    the chaos injector uses it to scope faults to one incarnation, so a
    planned kill does not re-fire in the respawned process). The monitor
    thread (``worker-supervisor``) polls ``Popen.poll()``; on death it

    1. reads the worker's deathnote (falling back to the router's
       in-flight journal via ``inflight_fn``) and records the implicated
       rids in the :class:`QuarantineLedger`;
    2. sweeps new incident bundles into ``INDEX.jsonl`` and persists
       ``SUPERVISOR.json``;
    3. asks the breaker for a restart budget — within budget the worker
       respawns after the jittered backoff delay (``sup.restart``),
       over budget it is held open (``sup.breaker_open``, the router's
       ``/health`` reports the tier degraded).

    The router calls :meth:`note_worker_death` the moment a placement
    socket breaks, so quarantine blame lands BEFORE the retry loop's
    next attempt — the monitor's slower waitpid sweep would lose that
    race. Both paths dedupe on the dead child's pid."""

    def __init__(self, *, ledger: Optional[QuarantineLedger] = None,
                 incident_dir: Optional[str] = None,
                 state_dir: Optional[str] = None,
                 backoff_base_s: float = 0.5, backoff_max_s: float = 30.0,
                 backoff_factor: float = 2.0,
                 breaker_threshold: int = 5,
                 breaker_window_s: float = 60.0,
                 healthy_reset_s: float = 30.0,
                 poll_interval_s: float = 0.2):
        self.ledger = ledger if ledger is not None else QuarantineLedger()
        self.incident_dir = incident_dir
        self.state_dir = state_dir or incident_dir
        if self.state_dir:
            os.makedirs(self.state_dir, exist_ok=True)
        self._backoff_cfg = (float(backoff_base_s), float(backoff_max_s),
                             float(backoff_factor))
        self._breaker_cfg = (int(breaker_threshold),
                             float(breaker_window_s))
        self.healthy_reset_s = float(healthy_reset_s)
        self.poll_interval_s = float(poll_interval_s)
        #: router hook: replica_id -> request ids the router has in
        #: flight there (the imprecise whole-batch fallback when a
        #: worker dies without arming a deathnote)
        self.inflight_fn: Optional[Callable[[int], List[str]]] = None
        self._lock = make_lock("WorkerSupervisor._lock")
        self._workers: Dict[int, _Supervised] = {}
        self._indexed: set = set()
        self._n_restarts = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- registration ---------------------------------------------------
    def deathnote_path(self, replica_id: int) -> Optional[str]:
        if not self.state_dir:
            return None
        return os.path.join(self.state_dir,
                            f"deathnote-{int(replica_id)}.json")

    def adopt(self, replica_id: int, spawn, proc) -> _Supervised:
        """Put one already-spawned worker under supervision. ``spawn`` is
        re-invoked as ``spawn(replica_id, incarnation)`` on restart."""
        base_s, max_s, factor = self._backoff_cfg
        threshold, window_s = self._breaker_cfg
        sup = _Supervised(replica_id, spawn, proc,
                          RestartBackoff(base_s, max_s, factor),
                          CircuitBreaker(threshold, window_s))
        with self._lock:
            self._workers[int(replica_id)] = sup
        return sup

    def proc(self, replica_id: int) -> Optional[subprocess.Popen]:
        with self._lock:
            sup = self._workers.get(int(replica_id))
            return sup.proc if sup is not None else None

    def kill(self, replica_id: int):
        """SIGKILL the worker's CURRENT incarnation (crash simulation)."""
        p = self.proc(replica_id)
        if p is not None:
            p.kill()

    # ---- lifecycle ------------------------------------------------------
    def start(self) -> "WorkerSupervisor":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._monitor, daemon=True,
                                        name="worker-supervisor")
        self._thread.start()
        return self

    def close(self, term_timeout: float = 10.0):
        """Stop supervising, SIGTERM every live child and REAP it — a
        torn-down cluster must leave no zombies (and no supervisor that
        would respawn what the teardown just killed)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        with self._lock:
            procs = [s.proc for s in self._workers.values()
                     if s.proc is not None]
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + term_timeout
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                get_logger().warning(
                    "supervisor: worker pid %s ignored SIGTERM; killing",
                    p.pid)
                p.kill()
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    get_logger().warning(
                        "supervisor: worker pid %s unreapable", p.pid)
        self.sweep_incidents()

    # ---- death handling -------------------------------------------------
    def note_worker_death(self, replica_id: int,
                          fallback_rids=()) -> bool:
        """Router-observed death (a placement socket broke): blame NOW,
        synchronously, so the ledger is current before the router's
        retry loop re-places the request. Returns True when a real
        process death was recorded (False: the process is alive — a
        connection blip, not a crash — so nothing is blamed)."""
        with self._lock:
            sup = self._workers.get(int(replica_id))
            proc = sup.proc if sup is not None else None
        if sup is None or proc is None:
            return False
        if proc.poll() is None:
            # the caller's socket broke BEFORE the exit became
            # waitpid-visible (os._exit closes fds a beat ahead of the
            # reapable state): give a real death a moment to land — a
            # genuine connection blip costs this wait once and is then
            # correctly not blamed
            try:
                proc.wait(timeout=0.5)
            except subprocess.TimeoutExpired:
                return False
        self._blame(sup, proc, fallback_rids=fallback_rids)
        return True

    def _blame(self, sup: _Supervised, proc, fallback_rids=()):
        """Record one death in the ledger, once per dead pid: the
        deathnote's step batch when armed (precise), else the router's
        in-flight journal for the replica (whole batch)."""
        with self._lock:
            if proc.pid in sup.blamed_pids:
                return
            sup.blamed_pids.add(proc.pid)
            sup.last_exit = proc.poll()
        note_path = self.deathnote_path(sup.replica_id)
        rids = Deathnote.read(note_path) if note_path else None
        precise = rids is not None
        if rids is None:
            fn = self.inflight_fn
            if fn is not None:
                try:
                    rids = [str(r) for r in fn(sup.replica_id)]
                except Exception as e:
                    get_logger().warning(
                        "supervisor: in-flight journal read failed "
                        "(%s: %s)", type(e).__name__, e)
                    rids = []
            else:
                rids = list(fallback_rids)
        if fallback_rids and not precise:
            rids = list(dict.fromkeys([*rids, *map(str, fallback_rids)]))
        if note_path:
            try:
                os.unlink(note_path)
            except FileNotFoundError:
                pass
        if rids:
            self.ledger.record_death(sup.replica_id, proc.pid, rids,
                                     precise=precise)

    def _handle_death(self, sup: _Supervised, proc):
        code = proc.poll()
        self._blame(sup, proc)
        self.sweep_incidents()
        now = time.monotonic()
        rec = _frec.RECORDER
        with self._lock:
            allowed = sup.breaker.allow()
        if not allowed:
            with self._lock:
                already = sup.held_open
                sup.held_open = True
                sup.proc = None
            if not already:
                with self._lock:
                    n_restarts = len(sup.restarts)
                if rec.enabled:
                    rec.record(_frec.EV_SUP_BREAKER,
                               replica_id=sup.replica_id,
                               restarts=n_restarts,
                               window_s=sup.breaker.window_s)
                get_logger().error(
                    "supervisor: worker %s crash-looped (%s restarts in "
                    "%.0fs window) — breaker OPEN, holding (reset via "
                    "WorkerSupervisor.reset_breaker)", sup.replica_id,
                    sup.breaker.threshold, sup.breaker.window_s)
            return
        with self._lock:
            delay = sup.backoff.next_delay()
            sup.proc = None
            sup.next_restart_at = now + delay
            sup.restarts.append({"ts": time.time(), "exit": code,
                                 "incarnation": sup.incarnation,
                                 "delay_s": round(delay, 3)})
            self._n_restarts += 1
        WORKER_RESTARTS.inc(replica=str(sup.replica_id))
        if rec.enabled:
            rec.record(_frec.EV_SUP_RESTART, replica_id=sup.replica_id,
                       incarnation=sup.incarnation + 1, exit_code=code,
                       delay_s=round(delay, 3))
        get_logger().warning(
            "supervisor: worker %s died (exit %s) — restarting as "
            "incarnation %s in %.2fs", sup.replica_id, code,
            sup.incarnation + 1, delay)

    def _respawn(self, sup: _Supervised):
        with self._lock:
            sup.incarnation += 1
            incarnation = sup.incarnation
            sup.next_restart_at = None
        try:
            proc = sup.spawn(sup.replica_id, incarnation)
        except Exception as e:
            get_logger().error(
                "supervisor: respawn of worker %s failed (%s: %s); will "
                "retry on the backoff ladder", sup.replica_id,
                type(e).__name__, e)
            with self._lock:
                sup.next_restart_at = (time.monotonic()
                                       + sup.backoff.next_delay())
            return
        with self._lock:
            sup.proc = proc
            sup.last_start = time.monotonic()

    def _monitor(self):
        while not self._stop.wait(self.poll_interval_s):
            with self._lock:
                snapshot = list(self._workers.values())
            now = time.monotonic()
            for sup in snapshot:
                with self._lock:
                    proc = sup.proc
                    due = (sup.next_restart_at is not None
                           and now >= sup.next_restart_at
                           and not sup.held_open)
                if proc is not None:
                    if proc.poll() is not None:
                        try:
                            self._handle_death(sup, proc)
                        except Exception as e:
                            # supervision must outlive its own bugs: a
                            # failed blame/sweep still schedules the
                            # restart path next tick
                            get_logger().warning(
                                "supervisor: death handling for worker "
                                "%s failed (%s: %s)", sup.replica_id,
                                type(e).__name__, e)
                    else:
                        with self._lock:
                            if (now - sup.last_start
                                    > self.healthy_reset_s
                                    and sup.backoff.attempt):
                                # sustained health re-arms the full
                                # backoff ladder (breaker stamps age
                                # out on their own)
                                sup.backoff.reset()
                elif due:
                    try:
                        self._respawn(sup)
                    except Exception as e:
                        # a failed spawn (fork/exec pressure) must not
                        # kill supervision: next_restart_at is still in
                        # the past, so the next tick retries
                        get_logger().warning(
                            "supervisor: respawn of worker %s failed "
                            "(%s: %s); retrying next tick",
                            sup.replica_id, type(e).__name__, e)

    def reset_breaker(self, replica_id: int):
        """Operator intervention: close a held-open breaker and schedule
        an immediate restart attempt."""
        with self._lock:
            sup = self._workers.get(int(replica_id))
            if sup is None:
                return
            sup.breaker.reset()
            sup.backoff.reset()
            sup.held_open = False
            if sup.proc is None:
                sup.next_restart_at = time.monotonic()

    # ---- state / forensics ----------------------------------------------
    def state(self) -> dict:
        """Restart history + breaker state per worker + the quarantine
        ledger — the SUPERVISOR section of /health and SUPERVISOR.json."""
        with self._lock:
            workers = {}
            restarts_total = self._n_restarts
            for rid, sup in self._workers.items():
                workers[str(rid)] = {
                    "incarnation": sup.incarnation,
                    "alive": (sup.proc is not None
                              and sup.proc.poll() is None),
                    "pid": (sup.proc.pid if sup.proc is not None
                            else None),
                    "last_exit": sup.last_exit,
                    "restarts": [dict(r) for r in sup.restarts],
                    "breaker": sup.breaker.state(),
                    "held_open": sup.held_open,
                    "restart_pending": sup.next_restart_at is not None,
                }
        ledger = self.ledger.snapshot()
        return {
            "restarts_total": restarts_total,
            "breakers_open": sum(1 for w in workers.values()
                                 if w["held_open"]),
            "quarantined_total": len(ledger["quarantined"]),
            "workers": workers,
            "quarantine": ledger,
        }

    def sweep_incidents(self) -> int:
        """Index every not-yet-seen incident OR divergence bundle in
        ``incident_dir`` into ``INDEX.jsonl`` (one line per bundle:
        file, reason, context, ts, pid, rank) and refresh
        ``SUPERVISOR.json``. Divergence bundles (written by the
        correctness sentinel) index with reason ``divergence`` and a
        context naming the audit source and first diverged position, so
        the cluster index answers "has ANY worker produced wrong
        tokens" the same way it answers "has any worker crashed".
        Returns the number of newly indexed bundles."""
        if not self.state_dir:
            return 0
        new = 0
        inc_dir = self.incident_dir or self.state_dir
        try:
            names = sorted(os.listdir(inc_dir))
        except OSError:
            names = []
        index_path = os.path.join(self.state_dir, "INDEX.jsonl")
        lines = []
        for name in names:
            is_div = name.startswith("divergence-")
            if (not (name.startswith("incident-") or is_div)
                    or not name.endswith(".json")):
                continue
            with self._lock:
                if name in self._indexed:
                    continue
                self._indexed.add(name)
            path = os.path.join(inc_dir, name)
            entry = {"file": name}
            try:
                with open(path, encoding="utf-8") as f:
                    b = json.load(f)
                if is_div:
                    entry.update({
                        "reason": "divergence",
                        "context": (f"{b.get('source', '?')} "
                                    f"rid={b.get('rid')} "
                                    f"first={b.get('first_divergence')} "
                                    f"engine={b.get('engine', '?')}"),
                        "ts": os.path.getmtime(path),
                        "pid": None, "rank": None,
                    })
                else:
                    entry.update({k: b.get(k) for k in
                                  ("reason", "context", "ts",
                                   "pid", "rank")})
            except (OSError, ValueError) as e:
                entry["error"] = f"{type(e).__name__}: {e}"
            lines.append(json.dumps(entry, default=str))
            new += 1
        if lines:
            with open(index_path, "a", encoding="utf-8") as f:
                f.write("\n".join(lines) + "\n")
        # SUPERVISOR.json is rewritten every sweep (atomic): the latest
        # restart/breaker/quarantine picture next to the bundle index
        sup_path = os.path.join(self.state_dir, "SUPERVISOR.json")
        tmp = sup_path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self.state(), f, indent=1, default=str)
            os.replace(tmp, sup_path)
        except OSError as e:
            get_logger().warning("supervisor: state persist failed "
                                 "(%s: %s)", type(e).__name__, e)
        return new
