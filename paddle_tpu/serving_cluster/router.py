"""RouterServer: the disaggregated tier's front door.

Admits ``POST /v1/completions`` on the same handler skeleton as the
single-process server (``serving_http.ServingHandlerBase`` — so /metrics,
/trace and /debug/* work identically on the router) and places each
request on a worker with **queue-depth-aware least-loaded scheduling**:
the pool scores every live worker by active slots + its own queue depth +
this router's not-yet-visible placements, and the emptiest one wins.

Streaming is relayed token by token (SSE in, SSE out). Fault handling is
placement-scoped: a worker that dies mid-request (socket error, EOF
before ``[DONE]``, 5xx) is marked dead in the pool and the request
REQUEUES onto another worker within a bounded retry budget — for greedy
streams the router skips the tokens it already delivered, so the client
sees one continuous, correct stream across the failover. Every placement
/ retry / loss decision is a flight-recorder event (``router.*``), and
the router's ``router.request``/``router.upstream`` spans propagate
``traceparent`` downstream, so one trace_id covers router and worker
spans across processes.

When the pool contains ``prefill``-role workers, requests run
disaggregated: a prefill worker computes the prompt KV and ships it over
the decode worker's handoff channel (``kv_handoff``), then the decode
worker streams tokens from the shipped state.
"""
from __future__ import annotations

import http.client
import json
import threading
import time
import urllib.request
import uuid
from http.server import ThreadingHTTPServer
from typing import List, Optional, Tuple

from ..analysis.threads.witness import make_lock
from ..chaos import inject as _chaos
from ..distributed.log_utils import get_logger
from ..observability import alerts as _alerts
from ..observability import flightrecorder as _frec
from ..observability import timeseries as _ts
from ..observability import tracing as _tracing
from ..observability.catalog import ROUTER_PLACEMENTS
from ..observability.metrics import PROMETHEUS_CONTENT_TYPE, get_registry
from ..serving_http import (AUDIT_HEADER, DEADLINE_HEADER,
                            ServingHandlerBase, alerts_payload,
                            kvstate_payload, profile_payload,
                            timeseries_payload)
from .pool import WorkerInfo, WorkerPool, jittered

__all__ = ["RouterServer"]


def _deadline_body(note: str = "") -> dict:
    return {"error": "request deadline exceeded" + note,
            "code": "deadline_exceeded"}


class _ClientError(Exception):
    """The worker judged the request invalid (4xx): forward verbatim,
    never retry — a bad request is bad on every replica."""

    def __init__(self, status: int, body: dict):
        super().__init__(f"client error {status}")
        self.status = status
        self.body = body


class _UpstreamError(Exception):
    """A placement attempt failed for reasons a DIFFERENT worker might
    not share: transport death, 5xx, mid-stream EOF. ``dead`` names a
    worker the router observed failing at the socket level (marked dead
    in the pool immediately — the lease would take up to ttl to lapse)."""

    def __init__(self, reason: str, dead: Optional[WorkerInfo] = None,
                 exclude: Tuple[int, ...] = ()):
        super().__init__(reason)
        self.reason = reason
        self.dead = dead
        self.exclude = exclude


class _WorkerBusy(Exception):
    """The worker answered 429 (bounded admission queue): placement
    FEEDBACK, not a failure — skip the worker for a short backoff and
    try another without marking it dead or burning the failover-retry
    budget. If every worker is busy the client gets the 429 +
    Retry-After back."""

    def __init__(self, worker: WorkerInfo, body: dict,
                 retry_after: str = "1"):
        super().__init__(f"worker {worker.replica_id} busy")
        self.worker = worker
        self.body = body
        self.retry_after = retry_after


class _ClientGone(Exception):
    """The DOWNSTREAM client disconnected mid-relay; nothing to answer."""


class _DeadlineExpired(Exception):
    """The request's end-to-end SLO budget ran out at the router —
    before a placement, or mid-hop (the upstream timeout now derives
    from the REMAINING budget, not a fixed constant). Terminal and
    typed: 504 with ``code=deadline_exceeded``, never a retry (another
    replica cannot un-expire a global deadline) and never a mark_dead
    (the worker did nothing wrong)."""


class _Migrated(Exception):
    """The upstream worker ended the stream with a migrate marker: the
    request's slot was exported to another worker (drain / rebalance).
    NOT a failure — the relay continues on the destination by claiming
    the named handoff id, without burning the failover-retry budget."""

    def __init__(self, info: dict):
        super().__init__(f"migrated to {info.get('dst')}")
        self.info = info


class RouterServer:
    """HTTP front-end placing completions across a WorkerPool."""

    #: bound on PLANNED migration hops per request (a drain chain, not a
    #: retry budget) — a pathological migrate loop must still terminate
    max_migrations = 16

    def __init__(self, pool: WorkerPool, host: str = "127.0.0.1",
                 port: int = 0, model_name: str = "paddle-tpu",
                 max_retries: int = 2, upstream_timeout: float = 120.0,
                 retry_backoff_s: float = 0.05,
                 enable_tracing: bool = True,
                 enable_flight_recorder: bool = True,
                 enable_timeseries: bool = True,
                 ts_interval_s: Optional[float] = None,
                 alert_objectives=None, alert_time_scale: float = 1.0,
                 quarantine=None, supervisor=None):
        self.pool = pool
        self.model_name = model_name
        self.max_retries = int(max_retries)
        self.upstream_timeout = float(upstream_timeout)
        # poison containment (supervisor.QuarantineLedger): a request id
        # implicated in >= 2 distinct worker deaths answers a typed 422
        # code=request_quarantined and is NEVER placed again — one
        # poisoned input must not serially crash the whole tier
        self._quarantine = quarantine
        # the worker supervisor (when this router fronts a supervised
        # launcher): notified the moment a placement socket observes a
        # death, so deathnote blame lands before the next retry; its
        # state() rides /health as the degraded-capacity report
        self._supervisor = supervisor
        if (self._quarantine is None and supervisor is not None):
            self._quarantine = supervisor.ledger
        # in-flight journal: request_id -> replica_id currently serving
        # it — the imprecise whole-batch blame fallback the supervisor
        # reads when a worker dies without arming a deathnote
        self._journal = {}
        # jittered sleep before each failover retry: after a mass event
        # (worker death under load) every relay would otherwise hammer
        # the survivors in the same instant
        self.retry_backoff_s = float(retry_backoff_s)
        if enable_tracing:
            _tracing.get_tracer().enable()
        self._tracer = _tracing.get_tracer()
        if enable_flight_recorder:
            _frec.get_recorder().enable()
        # cluster watchtower: the router's ts-sampler additionally
        # federates pool/supervisor-derived series (per-replica worker
        # counters off the probes the pool already runs, live-worker
        # count, breaker state) into the process store, and a CLUSTER
        # AlertManager judges the tier-level objectives over it — one
        # GET /alerts answers "is the tier healthy" with history
        self._alert_mgr = None
        self._ts_store = None
        if enable_timeseries:
            self._ts_store = _ts.get_store()
            self._ts_store.add_collector(self._collect_cluster)
            self._ts_store.start(interval_s=ts_interval_s)
            self._alert_mgr = _alerts.AlertManager(
                self._ts_store,
                alert_objectives
                or _alerts.cluster_objectives(alert_time_scale),
                name="cluster").attach()
        self._lock = make_lock("RouterServer._lock")
        self._placed = 0
        self._retried = 0
        self._failed = 0
        self._busy = 0
        self._deadline = 0
        self._quarantined_hits = 0
        self._httpd = ThreadingHTTPServer((host, port),
                                          self._make_handler())
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="router-http-loop")

    # ---- lifecycle -----------------------------------------------------
    @property
    def address(self):
        return self._httpd.server_address

    def start(self):
        self._http_thread.start()
        return self

    def close(self):
        if self._ts_store is not None:
            # the store is a process singleton that outlives this
            # router: unhook the collector/listener so a torn-down
            # router's dead pool is never sampled again
            self._ts_store.remove_collector(self._collect_cluster)
            if self._alert_mgr is not None:
                self._alert_mgr.detach()
        self._httpd.shutdown()
        self._httpd.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # ---- handler hooks ---------------------------------------------------
    def _make_handler(server_self):
        class Handler(ServingHandlerBase):
            server_obj = server_self
            # the router's POST span is router.request, not http.request:
            # it parents router.upstream AND (via the forwarded
            # traceparent) the worker's http.request across the process
            # boundary
            post_span_name = _tracing.SPAN_ROUTER_REQUEST

        return Handler

    def _refresh_metrics(self):
        self.pool.refresh_gauges()

    def _health_payload(self) -> dict:
        """The POOL's health, aggregated: per-worker liveness + occupancy
        (so one scrape shows a load balancer the whole tier), the
        router's own placement counters, and — under supervision — the
        supervisor's restart/breaker/quarantine report. ``status`` says
        ``degraded`` while a breaker holds a worker down or a restart is
        pending: the tier serves, but below its provisioned capacity."""
        workers = self.pool.workers()
        alive = sum(1 for w in workers if w["alive"])
        roles: dict = {}
        for w in workers:
            if w["alive"]:
                roles[w["role"]] = roles.get(w["role"], 0) + 1
        with self._lock:
            router_stats = {"placed": self._placed,
                            "retried": self._retried,
                            "failed": self._failed,
                            "busy": self._busy,
                            "deadline": self._deadline,
                            "quarantined": self._quarantined_hits,
                            "max_retries": self.max_retries}
        status = "ok" if alive else "unavailable"
        supervisor = None
        if self._supervisor is not None:
            supervisor = self._supervisor.state()
            # the ledger's full implication lists are forensics
            # (SUPERVISOR.json / read_incident --index); /health carries
            # the operator summary
            q = supervisor.pop("quarantine", {})
            supervisor["quarantined"] = sorted(q.get("quarantined", ()))
            supervisor["deaths_recorded"] = q.get("deaths_recorded", 0)
            degraded = (supervisor["breakers_open"] > 0
                        or any(not w["alive"]
                               for w in supervisor["workers"].values()))
            if alive and degraded:
                status = "degraded"
        payload = {
            "status": status,
            "alive": alive,
            "roles": roles,
            "workers": {str(w["replica_id"]): w for w in workers},
            "router": router_stats,
        }
        if supervisor is not None:
            payload["supervisor"] = supervisor
        return payload

    def _models_payload(self) -> dict:
        return {"object": "list",
                "data": [{"id": self.model_name, "object": "model"}]}

    def _timeseries_payload(self, query: str) -> dict:
        return timeseries_payload(query)

    def _alerts_payload(self) -> dict:
        # the CLUSTER manager: tier-level objectives over the federated
        # store, not the per-process serving defaults
        return alerts_payload(self._alert_mgr)

    # ---- metrics federation ----------------------------------------------
    # the worker-stats counters the collector federates as per-replica
    # cluster_* series (keys off the engines' shared stats() schema);
    # alerts.FEDERATED_SERIES pins the resulting names for the lint
    _FEDERATED_STATS = (
        ("requests_admitted", "cluster_requests_admitted"),
        ("requests_finished", "cluster_requests_finished"),
        ("requests_shed", "cluster_requests_shed"),
        ("deadline_misses", "cluster_deadline_misses"),
        ("tokens_generated", "cluster_tokens_generated"),
    )

    # step-anatomy profiler scalars federated as per-replica GAUGES (the
    # watch_cluster perf panel's sparkline feed); same /health-probe
    # transport as the counters above — a sample never does network I/O
    _FEDERATED_PERF = (
        ("profile_step_ms", "cluster_profile_step_ms"),
        ("profile_roofline_ratio", "cluster_profile_roofline_ratio"),
    )

    # KV-atlas scalars federated as per-replica GAUGES (the
    # watch_cluster MEM panel's sparkline feed + the capacity signal
    # ROADMAP item 4 consumes); same zero-I/O transport
    _FEDERATED_KV = (
        ("kv_pages_in_use", "cluster_kv_pages_in_use"),
        ("kv_bytes", "cluster_kv_bytes"),
        ("kv_headroom_slots", "cluster_kv_headroom_slots"),
        ("prefix_hit_ratio", "cluster_prefix_hit_ratio"),
    )

    # correctness-sentinel scalars federated per replica: the verdict
    # counters feed the cluster_audit_divergence objective, the drift
    # gauge feeds the watch_cluster AUDIT sparkline; same zero-I/O
    # /health-probe transport (kind rides the tuple — counters and a
    # gauge share the table)
    _FEDERATED_AUDIT = (
        ("audit_pass", "cluster_audit_pass", "counter"),
        ("audit_diverged", "cluster_audit_diverged", "counter"),
        ("audit_skipped", "cluster_audit_skipped", "counter"),
        ("audit_drift", "cluster_audit_drift", "gauge"),
    )

    def _collect_cluster(self) -> list:
        """ts-sampler collector: pool/supervisor-derived series. Reads
        ONLY state the pool's own /health probes already hold — a
        sample never does network I/O."""
        out: list = []
        alive = 0
        for rid, w_alive, stats in self.pool.worker_stats():
            if not w_alive:
                continue
            alive += 1
            labels = {"replica": str(rid)}
            for key, series in self._FEDERATED_STATS:
                if key in stats:
                    out.append((series, "counter", labels,
                                float(stats.get(key) or 0), None))
            for key, series in self._FEDERATED_PERF:
                if key in stats:
                    out.append((series, "gauge", labels,
                                float(stats.get(key) or 0), None))
            for key, series in self._FEDERATED_KV:
                if key in stats:
                    out.append((series, "gauge", labels,
                                float(stats.get(key) or 0), None))
            for key, series, kind in self._FEDERATED_AUDIT:
                if key in stats:
                    out.append((series, kind, labels,
                                float(stats.get(key) or 0), None))
        out.append(("cluster_workers_alive", "gauge", {}, float(alive),
                    None))
        breakers = 0.0
        if self._supervisor is not None:
            try:
                breakers = float(self._supervisor.state()["breakers_open"])
            except Exception as e:
                get_logger().debug("federation: supervisor state "
                                   "unavailable (%s: %s)",
                                   type(e).__name__, e)
        out.append(("cluster_breakers_open", "gauge", {}, breakers, None))
        return out

    def _scrape_worker(self, url: str) -> str:
        timeout = getattr(self.pool, "_probe_timeout", 2.0)
        with urllib.request.urlopen(url + "/metrics", timeout=timeout) as r:
            return r.read().decode("utf-8", errors="replace")

    @staticmethod
    def _merge_exposition(text: str, replica: str, seen_meta: set
                          ) -> List[str]:
        """Label-merge one process's exposition into the federated view:
        every sample line gains ``replica="N"``; # HELP/# TYPE headers
        are kept once per family; other comments (exemplars) are
        dropped — a federated surface carries samples, not per-process
        annotations."""
        lines: List[str] = []
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("#"):
                parts = line.split(None, 3)
                if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                    meta_key = (parts[1], parts[2])
                    if meta_key not in seen_meta:
                        seen_meta.add(meta_key)
                        lines.append(line)
                continue
            name, _, rest = line.partition("{")
            if rest:                                 # name{labels} value
                lines.append(f'{name}{{replica="{replica}",{rest}')
            else:                                    # name value
                name, _, value = line.partition(" ")
                lines.append(f'{name}{{replica="{replica}"}} {value}')
        return lines

    def _cluster_metrics_text(self) -> str:
        """``GET /metrics/cluster``: one exposition for the whole tier —
        the router's own registry (``replica="router"``), every live
        worker's /metrics scraped and label-merged per replica, and the
        pool/supervisor-derived gauges. A worker that fails its scrape
        contributes a comment, never a 5xx: a half-scraped tier view
        still beats none mid-incident."""
        seen_meta: set = set()
        lines = self._merge_exposition(
            get_registry().render_prometheus(), "router", seen_meta)
        for w in self.pool.workers():
            if not w["alive"]:
                continue
            rid = str(w["replica_id"])
            try:
                text = self._scrape_worker(w["url"])
            except (OSError, ValueError) as e:
                lines.append(f'# scrape_error replica="{rid}" '
                             f'{type(e).__name__}: {e}')
                continue
            lines.extend(self._merge_exposition(text, rid, seen_meta))
        for name, kind, labels, value, _e in self._collect_cluster():
            label_s = "".join(f'{{replica="{v}"}}'
                              for k, v in labels.items() if k == "replica")
            meta_key = ("TYPE", name)
            if meta_key not in seen_meta:
                seen_meta.add(meta_key)
                lines.append(f"# TYPE {name} {kind}")
            lines.append(f"{name}{label_s} {value:g}")
        return "\n".join(lines) + "\n"

    def _cluster_profile(self, query: str) -> dict:
        """``GET /profile/cluster``: every live worker's /profile
        fetched and keyed by replica id. Same contract as the metrics
        federation — a worker that fails its fetch contributes an error
        entry, never a 5xx."""
        q = f"?{query}" if query else ""
        timeout = getattr(self.pool, "_probe_timeout", 2.0)
        out: dict = {"schema_version": 1, "replicas": {}, "errors": {}}
        for w in self.pool.workers():
            if not w["alive"]:
                continue
            rid = str(w["replica_id"])
            try:
                with urllib.request.urlopen(w["url"] + "/profile" + q,
                                            timeout=timeout) as r:
                    out["replicas"][rid] = json.loads(r.read())
            except (OSError, ValueError) as e:
                out["errors"][rid] = f"{type(e).__name__}: {e}"
        return out

    def _cluster_kvstate(self, query: str) -> dict:
        """``GET /kvstate/cluster``: every live worker's /kvstate fetched
        and keyed by replica id, plus each replica's pool-published kv
        summary (prefix hashes + headroom off store metadata — readable
        even when the worker's HTTP fetch fails). Same contract as the
        other federations: fetch failures land in ``errors``, never a
        5xx."""
        q = f"?{query}" if query else ""
        timeout = getattr(self.pool, "_probe_timeout", 2.0)
        out: dict = {"schema_version": 1, "replicas": {}, "errors": {},
                     "pool": {}}
        for w in self.pool.workers():
            if not w["alive"]:
                continue
            rid = str(w["replica_id"])
            if w.get("kv") is not None:
                out["pool"][rid] = w["kv"]
            try:
                with urllib.request.urlopen(w["url"] + "/kvstate" + q,
                                            timeout=timeout) as r:
                    out["replicas"][rid] = json.loads(r.read())
            except (OSError, ValueError) as e:
                out["errors"][rid] = f"{type(e).__name__}: {e}"
        return out

    def _cluster_audit(self, query: str) -> dict:
        """``GET /audit/cluster``: every live worker's /audit fetched and
        keyed by replica id — the tier-wide sentinel view (who audited,
        who skipped, whose canaries drifted, where the sealed divergence
        bundles live). Same contract as the other federations: fetch
        failures land in ``errors``, never a 5xx."""
        q = f"?{query}" if query else ""
        timeout = getattr(self.pool, "_probe_timeout", 2.0)
        out: dict = {"schema_version": 1, "replicas": {}, "errors": {}}
        for w in self.pool.workers():
            if not w["alive"]:
                continue
            rid = str(w["replica_id"])
            try:
                with urllib.request.urlopen(w["url"] + "/audit" + q,
                                            timeout=timeout) as r:
                    out["replicas"][rid] = json.loads(r.read())
            except (OSError, ValueError) as e:
                out["errors"][rid] = f"{type(e).__name__}: {e}"
        return out

    def _extra_get(self, handler, route, query) -> bool:
        if route == "/metrics/cluster":
            handler._count(200)
            body = self._cluster_metrics_text().encode()
            handler.send_response(200)
            handler.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)
            return True
        if route == "/profile":
            # the router process has no engine; the payload is its own
            # (empty) profiler view — the federated one is next door
            handler._json(200, profile_payload(query))
            return True
        if route == "/profile/cluster":
            handler._json(200, self._cluster_profile(query))
            return True
        if route == "/kvstate":
            # no engine in the router process — the (empty) local atlas
            # view; the federated one is next door
            handler._json(200, kvstate_payload(query))
            return True
        if route == "/kvstate/cluster":
            handler._json(200, self._cluster_kvstate(query))
            return True
        if route == "/audit":
            # no engine in the router process — the (empty) local
            # sentinel view; the federated one is next door
            from ..observability import sentinel as _sentinel

            handler._json(200, _sentinel.audit_payload())
            return True
        if route == "/audit/cluster":
            handler._json(200, self._cluster_audit(query))
            return True
        return False

    def _post_handler(self, route):
        if route == "/v1/completions":
            return self._complete
        if route == "/drain":
            return self._drain
        return None

    # ---- graceful drain --------------------------------------------------
    def _drain(self, handler, req):
        """``POST /drain {"replica_id": N}``: gracefully drain a worker —
        stop its admission, migrate its live slots to peers (zero token
        loss), then release its pool lease. Answers the drain summary."""
        try:
            replica = int(req["replica_id"])
        except (KeyError, TypeError, ValueError):
            return handler._json(400, {
                "error": "drain needs an integer 'replica_id'"})
        try:
            summary = self.drain_worker(
                replica, timeout=float(req.get("timeout", 60.0)))
        except ValueError as e:
            return handler._json(404, {"error": str(e)})
        except _ClientError as e:
            # the worker judged a drain-path request invalid: forward
            # the verdict verbatim, as the completion path would
            return handler._json(e.status, e.body)
        except _WorkerBusy as e:
            return handler._json(429, dict(e.body,
                                           retry_after=e.retry_after))
        except _DeadlineExpired:
            return handler._json(504, _deadline_body())
        except _UpstreamError as e:
            return handler._json(502, {
                "error": f"drain failed upstream: {e.reason}"})
        except Exception as e:
            return handler._json(502, {
                "error": f"drain failed: {type(e).__name__}: {e}"})
        return handler._json(200, summary)

    def drain_worker(self, replica_id: int, timeout: float = 60.0) -> dict:
        """Drain one worker: mark it draining in the pool (no new
        placements), stop its admission (worker ``/drain``), migrate
        every active slot to a peer with a handoff channel (the relays
        follow their migrate markers), wait for the worker to empty, and
        release its lease. Slots that cannot migrate (no destination,
        n>1 sibling groups) finish locally — the drain waits them out.

        Upgrades scale-down and deploys from "kill and re-prefill" to
        zero-token-loss: a migrated stream is token-identical and its
        SSE delivery continuous."""
        w = self.pool.get(int(replica_id))
        if w is None or not w.alive:
            raise ValueError(f"no live worker {replica_id} in the pool")
        self.pool.set_draining(replica_id)
        migrated, failed = [], []
        deadline = time.monotonic() + float(timeout)
        drained = False
        while time.monotonic() < deadline:
            status, body = self._post_json(w, "/drain", {}, None)
            if status != 200:
                raise RuntimeError(
                    f"worker {replica_id} refused /drain: {status} "
                    f"{body.get('error', body)}")
            active = [int(r) for r in body.get("active") or []]
            if not (active or body.get("queued")
                    or body.get("prefilling")):
                drained = True
                break
            for rid in active:
                dst = self.pool.select(roles=("decode", "unified"),
                                       exclude=(int(replica_id),))
                if dst is None or not dst.kv_channel:
                    if dst is not None:
                        self.pool.release(dst)
                    # no migration destination: the slot finishes
                    # locally, the drain loop waits it out
                    if rid not in failed:
                        failed.append(rid)
                    continue
                hid = uuid.uuid4().hex
                try:
                    st, resp = self._post_json(
                        w, "/v1/migrate_out",
                        {"rid": rid, "channel": dst.kv_channel,
                         "dst": dst.replica_id, "handoff_id": hid}, None)
                finally:
                    self.pool.release(dst)
                if st == 200:
                    migrated.append(rid)
                    if rid in failed:
                        failed.remove(rid)
                elif rid not in failed:
                    # 409: finished / not yet decoding — next round
                    failed.append(rid)
            time.sleep(0.1)
        released = False
        if drained:
            st, _resp = self._post_json(w, "/v1/release", {}, None)
            released = (st == 200)
        get_logger().info(
            "router: drained worker %s (migrated=%s, local=%s, "
            "released=%s)", replica_id, migrated, failed, released)
        return {"replica_id": int(replica_id), "drained": drained,
                "migrated": migrated, "finished_locally": failed,
                "released": released}

    # ---- placement -------------------------------------------------------
    def _plan(self, exclude: Tuple[int, ...]):
        """(mode, prefill_worker | None, serve_worker) or None. Disagg
        when a prefill-role worker AND a handoff-capable decode target
        are both live; direct otherwise."""
        serve = self.pool.select(roles=("decode", "unified"),
                                 exclude=exclude)
        if serve is None:
            return None
        try:
            if self.pool.has_role("prefill") and serve.kv_channel:
                pre = self.pool.select(roles=("prefill",),
                                       exclude=exclude)
                if pre is not None:
                    return ("disagg", pre, serve)
            return ("direct", None, serve)
        except BaseException:
            # the lease counts pending load on the worker; an exception
            # between select() and the ownership-transferring return
            # would otherwise leave phantom load behind forever
            self.pool.release(serve)
            raise

    def _count_outcome(self, outcome: str):
        ROUTER_PLACEMENTS.inc(outcome=outcome)
        with self._lock:
            if outcome == "placed":
                self._placed += 1
            elif outcome == "retried":
                self._retried += 1
            elif outcome == "failed":
                self._failed += 1
            elif outcome == "busy":
                self._busy += 1
            elif outcome == "deadline":
                self._deadline += 1
            elif outcome == "quarantined":
                self._quarantined_hits += 1

    def _busy_blocked(self, exclude: Tuple[int, ...]):
        """When placement found no worker, distinguish FULL from DOWN:
        returns a live, non-draining, non-excluded worker that is only
        unavailable because of a 429 busy backoff (None when the pool is
        genuinely empty/dead). A full tier answers 429; only a dead one
        earns the 502."""
        candidates = [w for w in self.pool.workers()
                      if w["alive"] and not w["draining"]
                      and w["replica_id"] not in exclude]
        if not (candidates and all(w["busy"] for w in candidates)):
            return None
        return self.pool.get(candidates[0]["replica_id"])

    def _retry_after_for(self, worker: WorkerInfo) -> str:
        """Retry-After fallback when a 429 carries no header: the
        worker's last-reported backlog divided by its observed drain
        rate (both from the pool's /health polls), clamped to [1s, 30s]
        — backoff reflects actual congestion, not a constant."""
        w = self.pool.get(worker.replica_id) or worker
        depth = max(1, int(getattr(w, "queued", 0) or 0)
                    + int(getattr(w, "active", 0) or 0))
        rate = getattr(w, "drain_rate", None)
        est = depth / rate if rate else 1.0
        return str(max(1, min(30, round(est))))

    def _complete(self, handler, req):
        stream = bool(req.get("stream"))
        # the on-demand audit header survives the router hop as the
        # equivalent body field (upstream hops carry only the parsed
        # body; the worker accepts either form — serving_http
        # AUDIT_HEADER) and so also survives a failover re-placement
        hdr = (handler.headers.get(AUDIT_HEADER) or "").strip().lower()
        if hdr in ("1", "true") and "audit" not in req:
            req = dict(req, audit=True)
        # the request's cluster-wide identity: the client's request_id,
        # or one stamped here — every upstream hop carries it (the
        # engine's deathnote names it), the in-flight journal keys on
        # it, and the quarantine ledger refuses it after 2 worker
        # deaths. A router-stamped id still contains a crash loop WITHIN
        # this relay's retry budget; a client-provided id additionally
        # survives re-submissions.
        req_id = str(req.get("request_id")
                     or f"req-{uuid.uuid4().hex[:16]}")
        req = dict(req, request_id=req_id)
        # relay state survives retries: once SSE headers (or tokens) hit
        # the client socket, a failover must continue the SAME stream —
        # delivered counts the token chunks already written so the
        # replacement worker's (deterministic) stream is deduplicated
        state = {"headers_sent": False, "delivered": 0}
        exclude: Tuple[int, ...] = ()
        attempts = 0
        hops = 0      # planned migration continuations (not failures)
        cont = None   # migrate-marker info pinning the next hop
        last_reason = "no live worker available"
        busy: Optional[_WorkerBusy] = None
        root = handler._trace_span
        # end-to-end deadline: stamped at ARRIVAL, so every placement
        # attempt (and the X-Request-Deadline header each hop carries)
        # works off the remaining budget, not a fresh one
        slo_deadline = None
        try:
            slo = req.get("slo_ms")
            if slo is not None and float(slo) > 0:
                slo_deadline = time.monotonic() + float(slo) / 1000.0
        except (TypeError, ValueError):
            pass   # malformed slo_ms: the worker's 400 will name it
        while attempts <= self.max_retries and hops <= self.max_migrations:
            if (self._quarantine is not None
                    and self._quarantine.is_quarantined(req_id)):
                # poison containment: this rid has now been implicated
                # in >= 2 distinct worker deaths — typed 422, never
                # another placement (checked per attempt, so the retry
                # loop itself stops the serial crash amplification the
                # moment the second death lands)
                self._respond_quarantined(handler, state, req_id)
                return
            if (slo_deadline is not None
                    and time.monotonic() >= slo_deadline):
                # shed at the router: the budget is spent, so placing
                # the request would burn a prefill on a stream nobody
                # can use — answer typed instead
                self._respond_deadline(handler, state, slo_deadline)
                return
            rec = _frec.RECORDER
            pre = None
            if cont is not None:
                # a migrate marker pinned the destination: follow the
                # stream there by claiming its handoff id — a PLANNED
                # hop, so it spends max_migrations, not the retry budget
                info, cont = cont, None
                serve = self.pool.get(int(info.get("dst", -1)))
                if serve is None or not serve.alive:
                    # the drain's destination vanished before the
                    # continuation landed: fall back to a full replay
                    attempts += 1
                    last_reason = (f"migration destination "
                                   f"{info.get('dst')} left the pool")
                    self._count_outcome("retried")
                    continue
                self.pool.claim(serve)
                hops += 1
                mode = "migrate"
                # the destination streams only NEW tokens, numbered from
                # the bundle's generated count
                base = int(info.get("generated", state["delivered"]))
                up_req = {"handoff_id": info["handoff_id"],
                          "stream": stream}
            else:
                plan = self._plan(exclude)
                if plan is None:
                    break
                mode, pre, serve = plan
                attempts += 1
                base = 0
            try:
                self._journal_place(req_id, serve.replica_id)
                if rec.enabled:
                    rec.record(_frec.EV_ROUTER_PLACE,
                               replica_id=serve.replica_id,
                               role=serve.role, score=serve.score(),
                               attempt=attempts, mode=mode)
                sp = self._tracer.start_span(
                    _tracing.SPAN_ROUTER_UPSTREAM, parent=root,
                    attrs={"replica_id": serve.replica_id,
                           "role": serve.role, "attempt": attempts,
                           "mode": mode})
            except BaseException:
                # the attempt never started, so the attempt's finally
                # below can never run — the leases would stay counted as
                # phantom pending load on the workers. Releases first:
                # they cannot raise, the journal write could
                self.pool.release(serve)
                if pre is not None:
                    self.pool.release(pre)
                self._journal_clear(req_id)
                raise
            try:
                if mode != "migrate":
                    up_req = req
                    if mode == "disagg":
                        hid = self._prefill_hop(pre, serve, req, sp,
                                                deadline=slo_deadline)
                        up_req = {k: v for k, v in req.items()
                                  if k not in ("prompt",
                                               "prompt_token_ids",
                                               "pixel_values")}
                        up_req["handoff_id"] = hid
                if stream:
                    self._proxy_stream(handler, serve, up_req, state, sp,
                                       base=base, deadline=slo_deadline)
                else:
                    status, body = self._post_json(
                        serve, "/v1/completions", up_req, sp,
                        deadline=slo_deadline)
                    if 400 <= status < 500:
                        raise _ClientError(status, body)
                    if status != 200:
                        raise _UpstreamError(
                            f"worker {serve.replica_id} answered "
                            f"{status}: {body.get('error', body)}")
                    if isinstance(body, dict) and body.get("migrated"):
                        raise _Migrated(body["migrated"])
                    handler._json(200, body)
                sp.end()
                self._count_outcome("placed")
                return
            except _DeadlineExpired:
                # the budget ran out mid-hop: typed 504 / error chunk,
                # no retry, no mark_dead — the worker is innocent
                sp.end("error")
                self._respond_deadline(handler, state, slo_deadline)
                return
            except _Migrated as e:
                sp.end()  # the upstream hop SUCCEEDED — by migrating
                cont = e.info
                if rec.enabled:
                    rec.record(_frec.EV_ROUTER_RETRY,
                               replica_id=serve.replica_id,
                               attempt=attempts,
                               delivered=state["delivered"],
                               reason=("migrated to "
                                       f"{e.info.get('dst')}"))
            except _ClientError as e:
                sp.end("error")
                if state["headers_sent"]:
                    # the status line is long gone (a migrated stream's
                    # continuation can 4xx/deadline-504 after tokens
                    # flowed): end the SSE typed, without [DONE]
                    try:
                        handler._chunk(b"data: "
                                       + json.dumps(e.body).encode()
                                       + b"\n\n")
                        handler._chunk(b"")
                    except OSError:
                        handler.close_connection = True
                else:
                    handler._json(e.status, e.body)
                return
            except _ClientGone:
                sp.end("cancelled")
                handler.close_connection = True
                return
            except _WorkerBusy as e:
                sp.end("busy")
                # placement FEEDBACK, not a failure: short busy backoff
                # (not mark_dead), skip the worker this request, and do
                # NOT burn the failover-retry budget on backpressure
                busy = e
                attempts -= 1
                self.pool.mark_busy(e.worker.replica_id)
                exclude = exclude + (e.worker.replica_id,)
                if rec.enabled:
                    rec.record(_frec.EV_ROUTER_RETRY,
                               replica_id=e.worker.replica_id,
                               attempt=attempts + 1,
                               delivered=state["delivered"],
                               reason="busy")
                self._count_outcome("busy")
            except _UpstreamError as e:
                sp.end("error")
                last_reason = e.reason
                if e.dead is not None:
                    self.pool.mark_dead(e.dead.replica_id, "connection")
                    if self._supervisor is not None:
                        # blame NOW, before the retry places this rid
                        # again: the supervisor checks waitpid, reads
                        # the worker's deathnote (falling back to this
                        # relay's journal entry) and records the death
                        # in the quarantine ledger — the loop-top check
                        # sees a second death immediately
                        self._supervisor.note_worker_death(
                            e.dead.replica_id, fallback_rids=(req_id,))
                if e.dead is not None or mode != "disagg":
                    blame = (serve.replica_id,)
                else:
                    # a disagg decode worker answering 5xx is usually
                    # reporting a BUNDLE problem (handoff never arrived,
                    # checksum refused) — the worker is innocent, so a
                    # retry may re-plan the same pair with a freshly
                    # exported bundle instead of exhausting the pool
                    blame = ()
                exclude = exclude + blame + tuple(e.exclude)
                if rec.enabled:
                    rec.record(_frec.EV_ROUTER_RETRY,
                               replica_id=serve.replica_id,
                               attempt=attempts,
                               delivered=state["delivered"],
                               reason=e.reason)
                self._count_outcome("retried")
                get_logger().warning(
                    "router: placement attempt %s on replica %s failed "
                    "(%s); requeueing", attempts, serve.replica_id,
                    e.reason)
                if self.retry_backoff_s > 0:
                    # jittered, so a mass failure doesn't stampede every
                    # relay onto the survivors in the same instant
                    time.sleep(jittered(self.retry_backoff_s))
            finally:
                # releases first (no-raise decrements), then the span,
                # then the journal write — ordered so nothing that can
                # fail runs before a resource others account for is
                # given back. Span.end is idempotent (first end wins):
                # the typed ends in the handlers above stay
                # authoritative, this only catches exceptions no
                # handler matched, where the span would otherwise never
                # reach the trace buffer
                self.pool.release(serve)
                if pre is not None:
                    self.pool.release(pre)
                sp.end("error")
                self._journal_clear(req_id)
        # retry budget exhausted (or the pool is empty) — but if this
        # rid's LAST death is what emptied the pool, the quarantine may
        # have tripped after the loop-top check: answer the typed 422,
        # not a 502 (the tier is poisoned-by-this-request, not down)
        if (self._quarantine is not None
                and self._quarantine.is_quarantined(req_id)):
            self._respond_quarantined(handler, state, req_id)
            return
        self._count_outcome("failed")
        if not state["headers_sent"]:
            if busy is not None:
                # every placeable worker pushed back: forward the
                # backpressure (429 + Retry-After), never a 502 — the
                # tier is healthy, just full
                handler._json(429,
                              busy.body or {"error": "all workers busy"},
                              headers=(("Retry-After",
                                        busy.retry_after),))
                return
            blocked = self._busy_blocked(exclude)
            if blocked is not None:
                # this request saw no 429 itself, but every live worker
                # is sitting out a busy backoff earned from OTHER
                # requests' rejections — same situation, same typed
                # answer: the tier is at admission capacity, not down
                handler._json(
                    429, {"error": "all workers are at admission "
                                   "capacity; retry later"},
                    headers=(("Retry-After",
                              self._retry_after_for(blocked)),))
                return
        msg = (f"could not serve the request after {attempts} "
               f"placement attempt(s): {last_reason}")
        if state["headers_sent"]:
            # mid-stream: the status line is long gone — end the SSE with
            # an error and WITHOUT [DONE] (failed streams must not look
            # clean), exactly like the single-process server
            try:
                handler._chunk(b'data: {"error": '
                               + json.dumps(msg).encode() + b"}\n\n")
                handler._chunk(b"")
            except OSError:
                handler.close_connection = True
        else:
            handler._json(502, {"error": msg})

    # ---- poison quarantine ----------------------------------------------
    def _journal_place(self, req_id: str, replica_id: int):
        with self._lock:
            self._journal[req_id] = int(replica_id)

    def _journal_clear(self, req_id: str):
        with self._lock:
            self._journal.pop(req_id, None)

    def inflight_on(self, replica_id: int):
        """Request ids this router currently has placed on ``replica_id``
        — the supervisor's whole-batch blame fallback when a worker dies
        without arming a deathnote."""
        with self._lock:
            return [rid for rid, r in self._journal.items()
                    if r == int(replica_id)]

    def _respond_quarantined(self, handler, state: dict, req_id: str):
        """Answer a quarantined rid typed: 422 ``request_quarantined``
        before any bytes went out, an error chunk (no [DONE]) mid-stream
        — and NEVER another placement; the 4xx contract (a bad request
        is bad on every replica) now extends to requests proven to kill
        replicas."""
        self._count_outcome("quarantined")
        body = {"error": (f"request {req_id} quarantined: implicated in "
                          "repeated worker crashes; it will not be "
                          "retried"),
                "code": "request_quarantined"}
        if state["headers_sent"]:
            try:
                handler._chunk(b"data: " + json.dumps(body).encode()
                               + b"\n\n")
                handler._chunk(b"")
            except OSError:
                handler.close_connection = True
        else:
            handler._json(422, body)

    # ---- upstream hops ---------------------------------------------------
    def _respond_deadline(self, handler, state: dict, slo_deadline):
        """Answer a spent deadline typed: a real 504 before any bytes
        went out, an error chunk (no [DONE]) mid-stream — never a
        silent stall, never a retry."""
        self._count_outcome("deadline")
        miss_ms = (time.monotonic() - slo_deadline) * 1000.0 \
            if slo_deadline is not None else 0.0
        body = _deadline_body(f" (missed by {miss_ms:.0f}ms at the "
                              "router)")
        if state["headers_sent"]:
            try:
                handler._chunk(b"data: " + json.dumps(body).encode()
                               + b"\n\n")
                handler._chunk(b"")
            except OSError:
                handler.close_connection = True
        else:
            handler._json(504, body)

    def _headers(self, span, deadline=None) -> dict:
        h = {"Content-Type": "application/json"}
        if span:
            h[_tracing.TRACEPARENT_HEADER] = _tracing.format_traceparent(
                span.trace_id, span.span_id)
        if deadline is not None:
            # the deadline contract: each hop carries the REMAINING
            # budget in ms, so the worker's admission deadline equals
            # the router's minus elapsed time (pinned in tier-1)
            h[DEADLINE_HEADER] = (
                f"{max(0.0, (deadline - time.monotonic()) * 1000.0):.1f}")
        return h

    def _upstream_timeout(self, deadline) -> float:
        """The per-hop socket timeout derives from the remaining budget
        (plus a small grace so the worker's own typed shed wins the
        race) instead of the fixed constant — a spent deadline must
        surface in bounded time, typed."""
        if deadline is None:
            return self.upstream_timeout
        return min(self.upstream_timeout,
                   max(0.05, deadline - time.monotonic()) + 2.0)

    def _post_json(self, worker: WorkerInfo, path: str, body: dict,
                   span, deadline=None) -> Tuple[int, dict]:
        """One upstream POST, full-body; transport failures raise
        _UpstreamError naming the worker as observed-dead — unless the
        request's deadline has passed, which is the request's fault,
        not the worker's (_DeadlineExpired)."""
        self._chaos_upstream(worker, path)
        conn = http.client.HTTPConnection(
            worker.host, worker.port,
            timeout=self._upstream_timeout(deadline))
        try:
            conn.request("POST", path, json.dumps(body),
                         self._headers(span, deadline))
            resp = conn.getresponse()
            status = resp.status
            raw = resp.read()
            retry_after = ((resp.getheader("Retry-After")
                            or self._retry_after_for(worker))
                           if status == 429 else None)
        except (OSError, http.client.HTTPException) as e:
            if deadline is not None and time.monotonic() >= deadline:
                raise _DeadlineExpired() from e
            raise _UpstreamError(
                f"worker {worker.replica_id} transport failure on "
                f"{path}: {type(e).__name__}: {e}", dead=worker)
        finally:
            conn.close()
        try:
            parsed = json.loads(raw)
        except ValueError:
            parsed = {"error": raw.decode(errors="replace")}
        if (status == 504 and isinstance(parsed, dict)
                and parsed.get("code") == "deadline_exceeded"):
            # a worker's deadline shed is TERMINAL: the budget is
            # global, another replica cannot un-expire it — forward
            # verbatim through the no-retry path
            raise _ClientError(status, parsed)
        if status == 429:
            raise _WorkerBusy(worker, parsed, retry_after)
        return status, parsed

    def _prefill_hop(self, pre: WorkerInfo, serve: WorkerInfo, req: dict,
                     span, deadline=None) -> str:
        """Run the prompt through a prefill worker, shipping its KV to
        ``serve``'s handoff channel; returns the handoff id the decode
        request claims."""
        hid = uuid.uuid4().hex
        body = {"channel": serve.kv_channel, "handoff_id": hid,
                "max_tokens": req.get("max_tokens", 16)}
        for k in ("prompt", "prompt_token_ids"):
            if k in req:
                body[k] = req[k]
        try:
            status, resp = self._post_json(pre, "/v1/prefill", body, span,
                                           deadline=deadline)
        except _UpstreamError as e:
            # the SERVE worker is fine — only exclude/blame the prefill
            # worker so the retry can reuse the decode side
            raise _UpstreamError(e.reason, dead=e.dead,
                                 exclude=(pre.replica_id,)) from e
        if 400 <= status < 500:
            raise _ClientError(status, resp)
        if status != 200:
            raise _UpstreamError(
                f"prefill worker {pre.replica_id} answered {status}: "
                f"{resp.get('error', resp)}", exclude=(pre.replica_id,))
        return hid

    def _chaos_upstream(self, worker: WorkerInfo, path: str):
        """router.upstream injection point: a planned http_500 fails the
        placement attempt exactly like a worker 5xx would (retryable,
        worker NOT marked dead), a delay stalls the hop."""
        fault = _chaos.on("router.upstream",
                          replica_id=worker.replica_id, path=path)
        if fault is not None:
            if fault.action == "http_500":
                raise _UpstreamError(
                    f"chaos: injected 5xx placing on worker "
                    f"{worker.replica_id}")
            if fault.action == "delay":
                time.sleep(fault.delay_s)

    def _proxy_stream(self, handler, worker: WorkerInfo, body: dict,
                      state: dict, span, base: int = 0, deadline=None):
        """Relay one SSE stream, skipping the token chunks the client
        already has: the upstream's chunks are numbered from ``base``
        (0 for a full replay, the bundle's generated count for a
        migration continuation that emits only new tokens), and chunks
        numbered <= ``state['delivered']`` are dropped."""
        self._chaos_upstream(worker, "/v1/completions")
        conn = http.client.HTTPConnection(
            worker.host, worker.port,
            timeout=self._upstream_timeout(deadline))
        try:
            try:
                conn.request("POST", "/v1/completions", json.dumps(body),
                             self._headers(span, deadline))
                resp = conn.getresponse()
            except (OSError, http.client.HTTPException) as e:
                if deadline is not None and time.monotonic() >= deadline:
                    raise _DeadlineExpired() from e
                raise _UpstreamError(
                    f"worker {worker.replica_id} transport failure: "
                    f"{type(e).__name__}: {e}", dead=worker)
            if resp.status != 200:
                try:
                    raw = resp.read()
                except (OSError, http.client.HTTPException):
                    raw = b""
                try:
                    parsed = json.loads(raw)
                except ValueError:
                    parsed = {"error": raw.decode(errors="replace")}
                if (resp.status == 504 and isinstance(parsed, dict)
                        and parsed.get("code") == "deadline_exceeded"):
                    # terminal typed shed — forward, never retry
                    raise _ClientError(resp.status, parsed)
                if resp.status == 429:
                    raise _WorkerBusy(worker, parsed,
                                      resp.getheader("Retry-After")
                                      or self._retry_after_for(worker))
                if 400 <= resp.status < 500:
                    raise _ClientError(resp.status, parsed)
                raise _UpstreamError(
                    f"worker {worker.replica_id} answered {resp.status}: "
                    f"{parsed.get('error', parsed)}")
            if not state["headers_sent"]:
                handler._begin_sse()
                state["headers_sent"] = True
            seen = int(base)
            while True:
                try:
                    line = resp.readline()
                except (OSError, http.client.HTTPException) as e:
                    if (deadline is not None
                            and time.monotonic() >= deadline):
                        raise _DeadlineExpired() from e
                    raise _UpstreamError(
                        f"worker {worker.replica_id} stream broke: "
                        f"{type(e).__name__}: {e}", dead=worker)
                if not line:
                    # EOF without [DONE]: the worker died mid-stream
                    raise _UpstreamError(
                        f"worker {worker.replica_id} stream ended "
                        "without [DONE]", dead=worker)
                if not line.startswith(b"data: "):
                    continue
                payload = line[len(b"data: "):].strip()
                if payload == b"[DONE]":
                    try:
                        handler._chunk(b"data: [DONE]\n\n")
                        handler._chunk(b"")
                    except OSError:
                        raise _ClientGone()
                    return
                if payload.startswith(b'{"migrated"'):
                    # planned exit: the slot moved to another worker —
                    # the relay continues there (every token generated
                    # before the export was relayed ahead of the marker)
                    raise _Migrated(json.loads(payload)["migrated"])
                if payload.startswith(b'{"error"'):
                    try:
                        d = json.loads(payload)
                    except ValueError:
                        d = {}
                    if (isinstance(d, dict)
                            and d.get("code") == "deadline_exceeded"):
                        # a deadline shed after tokens flowed (preempted
                        # then requeued past its budget): terminal —
                        # forward typed, never replay on another worker
                        raise _ClientError(504, d)
                    # engine-level mid-stream failure: another worker
                    # can finish this request
                    raise _UpstreamError(
                        f"worker {worker.replica_id} streamed an error: "
                        f"{payload.decode(errors='replace')}")
                seen += 1
                if seen <= state["delivered"]:
                    continue  # already relayed before the failover
                try:
                    handler._chunk(b"data: " + payload + b"\n\n")
                except OSError:
                    # the DOWNSTREAM client went away: closing the
                    # upstream socket makes the worker see its own SSE
                    # disconnect and cancel the slot
                    raise _ClientGone()
                state["delivered"] += 1
        finally:
            conn.close()
