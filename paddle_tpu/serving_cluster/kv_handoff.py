"""KV handoff channel: prefill workers ship finished KV pages to decode
workers.

The disaggregated tier's data plane. A prefill worker runs
``engine.export_prefill`` (bucketed prefill, KV fetched to host as numpy)
and SENDS the bundle; the decode worker that owns the channel RECEIVES
it, parks it by ``handoff_id``, and admits it with
``engine.admit_prefilled`` when the router's completion request arrives —
the decode engine never runs the prompt's forward pass.

Transport is pluggable (``make_receiver``/``open_sender`` route through
``TRANSPORTS``): the CPU dryrun path rides ``io/shm_channel``'s native
ring (numpy payloads serialize as raw bytes, no pickle on the KV), and a
device-collective transport can register under its own name when
same-slice workers can move pages device-to-device without the host
round-trip. Every send/recv is a flight-recorder event
(``kv.handoff_send`` / ``kv.handoff_recv``) so a lost bundle is visible
in both processes' rings.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

import numpy as np

from ..analysis.threads.witness import make_lock
from ..chaos import inject as _chaos
from ..distributed.log_utils import get_logger
from ..io.shm_channel import ShmChannel, ShmChannelTimeout
from ..observability import flightrecorder as _frec

__all__ = ["KvHandoffSender", "KvHandoffReceiver", "bundle_nbytes",
           "make_receiver", "open_sender", "TRANSPORTS"]


def bundle_nbytes(bundle: dict) -> int:
    """Approximate wire size of a handoff bundle (the numpy leaves; the
    skeleton is noise) — the number the flight-recorder events carry."""
    total = 0

    def walk(o):
        nonlocal total
        if isinstance(o, np.ndarray):
            total += o.nbytes
        elif isinstance(o, (list, tuple)):
            for x in o:
                walk(x)
        elif isinstance(o, dict):
            for x in o.values():
                walk(x)

    walk(bundle)
    return total


class KvHandoffSender:
    """Prefill-side: opens a decode worker's channel BY NAME and pushes
    bundles into it. One sender per (prefill worker, decode channel)
    pair; senders are cheap — the ring is owned by the receiver."""

    def __init__(self, channel_name: str, timeout: float = 30.0):
        self.channel_name = channel_name
        self.timeout = float(timeout)
        self._chan = ShmChannel(channel_name, create=False)

    def send(self, handoff_id: str, bundle: dict) -> int:
        """Ship one bundle; returns its approximate byte size. Raises
        ``ShmChannelTimeout`` when the decode worker stops draining."""
        nbytes = bundle_nbytes(bundle)
        fault = _chaos.on("kv_handoff.send", handoff_id=handoff_id,
                          channel=self.channel_name)
        if fault is not None:
            if fault.action == "drop":
                # silently lost in transport: the receiver's wait() times
                # out and the caller's 5xx turns into a router retry
                return nbytes
            if fault.action == "delay":
                time.sleep(fault.delay_s)
            elif fault.action == "corrupt":
                # one byte flipped AFTER sealing — the admitting engine's
                # checksum must refuse it with HandoffCorrupt
                bundle = _chaos.corrupt_bundle(bundle)
        self._chan.put({"handoff_id": handoff_id, "bundle": bundle},
                       timeout=self.timeout)
        rec = _frec.RECORDER
        if rec.enabled:
            rec.record(_frec.EV_KV_HANDOFF_SEND, handoff_id=handoff_id,
                       channel=self.channel_name,
                       prompt_tokens=int(bundle.get("prompt_tokens", 0)),
                       bytes=nbytes)
        return nbytes

    def close(self):
        self._chan.close()


class KvHandoffReceiver:
    """Decode-side: owns the shm ring, drains it from a consumer thread,
    and parks bundles by ``handoff_id`` until the matching completion
    request claims them with :meth:`wait`."""

    def __init__(self, name: Optional[str] = None, capacity_mb: int = 64,
                 max_parked: int = 64):
        self.name = name or f"/pdtpu_kv_{os.getpid()}"
        self._chan = ShmChannel(self.name, capacity_mb=capacity_mb,
                                create=True)
        # the witness factory hands back a plain Lock unless
        # FLAGS_lock_witness is on; Condition's acquire/release fallbacks
        # work over either, so even wait/notify traffic is witnessed
        self._lock = make_lock("KvHandoffReceiver._lock")
        self._parked: Dict[str, dict] = {}
        self._arrived = threading.Condition(self._lock)
        self._max_parked = int(max_parked)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- consumer ------------------------------------------------------
    def start(self) -> "KvHandoffReceiver":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._drain, daemon=True,
                                        name="kv-handoff-recv")
        self._thread.start()
        return self

    def _drain(self):
        while not self._stop.is_set():
            try:
                msg = self._chan.get(timeout=0.2)
            except ShmChannelTimeout:
                continue
            except (EOFError, BrokenPipeError):
                return  # channel closed: consumer is done
            except Exception as e:
                get_logger().warning(
                    "kv handoff receiver %s: drain failed (%s: %s)",
                    self.name, type(e).__name__, e)
                continue
            try:
                hid = msg.get("handoff_id")
                bundle = msg.get("bundle")
                if hid is None or bundle is None:
                    get_logger().warning(
                        "kv handoff receiver %s: malformed message "
                        "dropped", self.name)
                    continue
                rec = _frec.RECORDER
                if rec.enabled:
                    rec.record(_frec.EV_KV_HANDOFF_RECV, handoff_id=hid,
                               channel=self.name,
                               prompt_tokens=int(
                                   bundle.get("prompt_tokens", 0)),
                               bytes=bundle_nbytes(bundle))
                with self._arrived:
                    # bounded parking: an orphaned bundle (its
                    # completion request never came) must not hold KV
                    # bytes forever
                    while len(self._parked) >= self._max_parked:
                        evicted = next(iter(self._parked))
                        del self._parked[evicted]
                        get_logger().warning(
                            "kv handoff receiver %s: parked bundle %s "
                            "evicted (never claimed)", self.name,
                            evicted)
                    self._parked[hid] = bundle
                    self._arrived.notify_all()
            except Exception as e:
                # one bad bundle loses one handoff (the claimer times
                # out into the router-retry path), never the receiver
                get_logger().warning(
                    "kv handoff receiver %s: parking failed (%s: %s)",
                    self.name, type(e).__name__, e)

    # ---- claim ---------------------------------------------------------
    def wait(self, handoff_id: str,
             timeout: float = 30.0) -> Optional[dict]:
        """Claim (and remove) the bundle for ``handoff_id``, blocking up
        to ``timeout``; None when it never arrives (the prefill worker
        died mid-handoff — the caller's 5xx turns into a router retry)."""
        with self._arrived:
            end = None if timeout is None else time.monotonic() + timeout
            while handoff_id not in self._parked:
                remain = None if end is None else end - time.monotonic()
                if remain is not None and remain <= 0:
                    return None
                self._arrived.wait(timeout=remain)
            return self._parked.pop(handoff_id)

    def close(self):
        # join the consumer BEFORE closing the ring: pd_shmq_close frees
        # the native handle, and a drain thread still blocked inside
        # pd_shmq_pop on it would fault, not raise
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._chan.close()


# ---- transport registry -----------------------------------------------------
# "shm" is the CPU dryrun path; a device-collective transport registers
# its own (receiver_factory, sender_factory) pair here when pages can
# move device-to-device without the host round-trip.

TRANSPORTS = {
    "shm": (KvHandoffReceiver, KvHandoffSender),
}


def make_receiver(kind: str = "shm", **kw) -> KvHandoffReceiver:
    return TRANSPORTS[kind][0](**kw)


def open_sender(channel_name: str, kind: str = "shm",
                **kw) -> KvHandoffSender:
    return TRANSPORTS[kind][1](channel_name, **kw)
