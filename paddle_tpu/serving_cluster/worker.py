"""Cluster worker: one ContinuousBatchEngine in a role, behind HTTP.

A worker is a :class:`~paddle_tpu.serving_http.CompletionServer` (same
engine thread, same observability surface) plus the cluster contract:

- **membership** — it registers a lease heartbeat + a metadata record
  (address, role, kv channel) through ``distributed/elastic.py``'s
  ElasticManager, so the router's WorkerPool discovers it through the
  store like trainers discover peers;
- **role** — ``unified`` serves completions end to end; ``prefill``
  serves ``POST /v1/prefill`` (bucketed prefill → KV bundle shipped to a
  decode worker's handoff channel) and refuses completions; ``decode``
  additionally accepts completions whose prompt KV arrives by
  ``handoff_id`` instead of running the prefill itself;
- **/health** — gains ``role``, ``replica_id``, ``lease_age_s`` and
  ``draining`` so a load balancer (and the router's aggregate /health)
  sees both what a worker is and how fresh its membership claim is;
- **drain / migration** — ``POST /drain`` stops admission and reports
  the live request ids; ``POST /v1/migrate_out`` exports one decoding
  slot as a sealed bundle, ships it to a peer's handoff channel, and
  ends the departing SSE stream with a migrate marker (the router's
  relay follows it); ``POST /v1/release`` gives up the pool lease once
  the drain emptied the worker.

``python -m paddle_tpu.serving_cluster.worker '<json cfg>'`` is the
process entry the launcher (scripts/serve_cluster.py) spawns.
"""
from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
import uuid
from typing import Optional

from ..analysis.threads.witness import make_lock
from ..chaos import inject as _chaos
from ..distributed.elastic import ElasticManager
from ..distributed.log_utils import get_logger
from ..serving_http import (CompletionServer, EngineCommand, _Submission,
                            apply_deadline_header)
from .kv_handoff import KvHandoffReceiver, make_receiver, open_sender

__all__ = ["WorkerServer", "run_worker", "build_model", "MODEL_BUILDERS"]

ROLES = ("prefill", "decode", "unified")


class _ExportPrefill(EngineCommand):
    """Engine-thread command: run the bucketed prefill for one prompt and
    return its host-side KV bundle (no slot taken)."""

    def __init__(self, ids, max_new_tokens: int):
        super().__init__()
        self.ids = ids
        self.max_new_tokens = max_new_tokens

    def execute(self, engine):
        return engine.export_prefill(self.ids,
                                     max_new_tokens=self.max_new_tokens)


class _ListLive(EngineCommand):
    """Engine-thread command: the live request ids by lifecycle stage —
    what a drain still has to move (active slots migrate; queued and
    mid-prefill requests become active first and migrate next round)."""

    def execute(self, engine):
        d = engine.debug_state()
        return {
            "active": [s["rid"] for s in d["slots"] if s is not None],
            "queued": list(d["queue"]),
            "prefilling": [v["rid"] for v in d["prefilling"].values()],
        }


class _ExportSlot(EngineCommand):
    """Engine-thread command: export one decoding slot as a migration
    bundle and detach its live submission (the handler thread ships the
    bundle and ends the stream with a migrate marker)."""

    def __init__(self, server: "WorkerServer", rid: int):
        super().__init__()
        self.server = server
        self.rid = rid

    def execute(self, engine):
        sub = self.server._live_subs.get(self.rid)
        if sub is not None and sub.n > 1:
            raise ValueError(
                f"request {self.rid} is one of n={sub.n} sibling "
                "completions — sibling groups finish locally instead of "
                "migrating")
        bundle = engine.export_slot(self.rid)
        self.server._live_subs.pop(self.rid, None)
        return bundle, sub


class _AdmitMigrated(EngineCommand):
    """Engine-thread command: re-admit an exported bundle LOCALLY — the
    fallback when the migration send fails after the slot was already
    exported (the stream continues here as if nothing happened)."""

    def __init__(self, server: "WorkerServer", bundle: dict, sub):
        super().__init__()
        self.server = server
        self.bundle = bundle
        self.sub = sub

    def execute(self, engine):
        sub = self.sub
        if sub is None:
            return engine.admit_migrated(self.bundle)
        ev = sub.events

        def on_token(rid, tok, done, logprob, _ev=ev):
            _ev.put(("token", (rid, tok, logprob), done))

        def on_shed(rid, info, _ev=ev):
            _ev.put(("shed", info, True))

        rid = engine.admit_migrated(self.bundle, on_token=on_token,
                                    trace_ctx=sub.trace_ctx,
                                    on_shed=on_shed)
        sub.rids.append(rid)
        self.server._live_subs[rid] = sub
        return rid


class WorkerServer(CompletionServer):
    """CompletionServer speaking the cluster protocol for one role."""

    def __init__(self, engine, *, role: str = "unified",
                 replica_id: int = 0,
                 elastic: Optional[ElasticManager] = None,
                 kv_receiver: Optional[KvHandoffReceiver] = None,
                 handoff_wait_s: float = 30.0, **kw):
        if role not in ROLES:
            raise ValueError(f"role must be one of {ROLES}, got {role!r}")
        super().__init__(engine, **kw)
        self.role = role
        self.replica_id = int(replica_id)
        self._elastic = elastic
        self._kv = kv_receiver
        self._handoff_wait_s = float(handoff_wait_s)
        self._senders = {}           # channel name -> KvHandoffSender
        self._senders_lock = make_lock("WorkerServer._senders_lock")
        # drain: admission stops, live slots migrate off, lease releases
        self.draining = False
        # rid -> live _Submission; ENGINE-THREAD ONLY (written in
        # _handle_submission and the migrate command, both of which run
        # on the engine thread) — the map that lets a migrate-out hand
        # the departing stream its marker event
        self._live_subs = {}
        if self._kv is not None:
            self._kv.start()

    def close(self):
        super().close()
        if self._kv is not None:
            self._kv.close()
        with self._senders_lock:
            senders, self._senders = dict(self._senders), {}
        for s in senders.values():
            s.close()

    # ---- cluster surface ------------------------------------------------
    def health_extra(self) -> dict:
        lease_age = (self._elastic.lease_age()
                     if self._elastic is not None else None)
        return {
            "role": self.role,
            "replica_id": self.replica_id,
            "lease_age_s": lease_age,
            "draining": self.draining,
            "kv_channel": (self._kv.name if self._kv is not None
                           else None),
        }

    def _handle_submission(self, sub):
        # engine thread: index live submissions by their engine rids so a
        # migrate-out can detach the right stream; pruned lazily against
        # the engine's live set (finished rids linger briefly, harmless)
        super()._handle_submission(sub)
        if isinstance(sub, _Submission):
            for rid in sub.rids:
                self._live_subs[rid] = sub
            if len(self._live_subs) > 4 * max(self.engine.max_batch, 1):
                eng = self.engine
                live = {r.rid for r in eng._slots if r is not None}
                live |= {r.rid for r in eng._queue}
                live |= {st.req.rid
                         for st in getattr(eng, "_chunking", {}).values()}
                self._live_subs = {rid: s
                                   for rid, s in self._live_subs.items()
                                   if rid in live}

    def _post_handler(self, route):
        fn = self._route_post(route)
        if fn is None:
            return None
        fault = _chaos.on("worker.request", route=route)
        if fault is not None:
            if fault.action == "http_500":
                return lambda handler, req: handler._json(
                    500, {"error": "chaos: injected worker fault"})
            if fault.action == "stall_heartbeat":
                if self._elastic is not None:
                    self._elastic.pause_heartbeat(
                        fault.duration_s or 3.0 * self._elastic.ttl)
            elif fault.action == "delay":
                time.sleep(fault.delay_s)
        return fn

    def _route_post(self, route):
        if route == "/drain":
            return self._drain_post
        if route == "/v1/migrate_out":
            return self._migrate_out_post
        if route == "/v1/release":
            return self._release_post
        if route == "/v1/prefill" and self.role in ("prefill", "unified"):
            return self._prefill_post
        return super()._post_handler(route)

    # ---- drain / migration ----------------------------------------------
    def _drain_post(self, handler, req):
        """Stop admission and report what is still live. Idempotent: the
        router's drain loop re-POSTs to watch the worker empty out while
        it migrates the active slots via /v1/migrate_out."""
        self.draining = True
        try:
            live = self.submit_command(_ListLive())
        except Exception as e:
            return handler._json(500, {"error": f"{type(e).__name__}: {e}"})
        return handler._json(200, {"draining": True,
                                   "replica_id": self.replica_id, **live})

    def _migrate_out_post(self, handler, req):
        """Export one decoding slot and ship it to a peer's handoff
        channel. The departing stream ends with a migrate marker naming
        the handoff id + destination; if the SEND fails, the bundle is
        re-admitted locally so the stream continues here instead of
        stranding the client."""
        try:
            rid = int(req["rid"])
            channel = req.get("channel")
            if not channel:
                raise ValueError("migrate_out needs 'channel' — the "
                                 "destination worker's kv handoff channel")
            dst = req.get("dst")
            hid = str(req.get("handoff_id") or uuid.uuid4().hex)
        except (KeyError, TypeError, ValueError) as e:
            return handler._json(400, {"error": str(e)})
        try:
            bundle, sub = self.submit_command(_ExportSlot(self, rid))
        except ValueError as e:
            # not actively decoding (queued / prefilling / finished) or
            # an n>1 sibling group: nothing exported, caller may retry
            # next drain round
            return handler._json(409, {"error": str(e)})
        except Exception as e:
            return handler._json(500, {"error": f"{type(e).__name__}: {e}"})
        generated = int(len(bundle["tokens"]))
        try:
            nbytes = self._sender(channel).send(hid, bundle)
        except Exception as e:
            get_logger().warning(
                "migrate_out %s -> %s failed (%s: %s); re-admitting "
                "locally", hid, channel, type(e).__name__, e)
            self.submit_command(_AdmitMigrated(self, bundle, sub))
            return handler._json(502, {
                "error": f"migration send failed ({type(e).__name__}: "
                         f"{e}); request re-admitted locally"})
        if sub is not None:
            sub.events.put(("migrated",
                            {"handoff_id": hid, "dst": dst,
                             "generated": generated}, True))
        return handler._json(200, {
            "handoff_id": hid, "channel": channel, "dst": dst,
            "rid": rid, "generated": generated, "bytes": nbytes,
        })

    def _release_post(self, handler, req):
        """Release the pool lease after a drain: the pool sees the lease
        lapse (no churn alarm — the drain was deliberate) and the worker
        process can be torn down at leisure."""
        if not self.draining:
            return handler._json(409, {
                "error": "release without drain — POST /drain first"})
        if self._elastic is not None:
            self._elastic.mark_done()
        return handler._json(200, {"released": True,
                                   "replica_id": self.replica_id})

    # ---- completions (decode side of the handoff) -----------------------
    def _complete(self, handler, req):
        if self.draining:
            # admission is closed; the router's placement already skips
            # draining workers, so this only catches racing requests
            return handler._json(503, {
                "error": f"worker {self.replica_id} is draining; "
                         "re-place this request"})
        if "handoff_id" in req:
            if self._kv is None:
                return handler._json(409, {
                    "error": f"this {self.role}-role worker has no kv "
                             "handoff channel"})
            return self._complete_from_handoff(handler, req)
        if self.role == "prefill":
            # a prefill-role worker holds no decode slots; the router
            # must not fall back to it for full completions
            return handler._json(409, {
                "error": "prefill-role worker serves /v1/prefill only"})
        return super()._complete(handler, req)

    def _complete_from_handoff(self, handler, req):
        hid = str(req["handoff_id"])
        bundle = self._kv.wait(hid, timeout=self._handoff_wait_s)
        if bundle is None:
            # the sender never delivered (died mid-handoff, or the
            # transport dropped the bundle): a 5xx here is what turns
            # into a router retry
            return handler._json(504, {
                "error": f"kv handoff {hid} not received within "
                         f"{self._handoff_wait_s}s"})
        sp = handler._trace_span
        trace_ctx = ((sp.trace_id, sp.span_id) if sp is not None else None)
        cid = f"cmpl-{uuid.uuid4().hex[:24]}"
        if bundle.get("kind") == "migrate":
            # migration continuation: every decode-side knob rides the
            # bundle; the stream emits only NEW tokens (the relay already
            # delivered the rest), a collect prepends them
            sub = _Submission(None, {}, handoff=bundle,
                              trace_ctx=trace_ctx)
            self._subs.put(sub)
            want_logprobs = bool(bundle.get("want_logprobs"))
            if req.get("stream"):
                return self._stream(handler, sub, cid, want_logprobs)
            prior = [int(t) for t in bundle["tokens"]]
            prior_lp = [float(x) for x in bundle.get("logprobs") or []]
            return self._collect(handler, sub, cid,
                                 int(bundle["prompt_tokens"]),
                                 want_logprobs, prior_tokens=prior,
                                 prior_logprobs=prior_lp)
        try:
            params, want_logprobs = self._parse_decode_params(req)
        except (ValueError, TypeError) as e:
            return handler._json(400, {"error": str(e)})
        # the router's deadline header carries the REMAINING budget —
        # the decode-side admission deadline derives from it, never a
        # fresh one (the prefill hop's time is already charged)
        err = apply_deadline_header(handler, params)
        if err is not None:
            return handler._json(*err)
        sub = _Submission(None, params, handoff=bundle,
                          trace_ctx=trace_ctx)
        self._subs.put(sub)
        n_prompt = int(bundle["prompt_tokens"])
        if req.get("stream"):
            return self._stream(handler, sub, cid, want_logprobs)
        return self._collect(handler, sub, cid, n_prompt, want_logprobs)

    def _parse_decode_params(self, req):
        """The decode-side subset of the completion params (the prompt
        lives in the handoff bundle): token budget, sampling overrides,
        stops, logprobs."""
        max_tokens = int(req.get("max_tokens", 16))
        if max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        params = dict(max_new_tokens=max_tokens)
        if ("temperature" in req or "top_p" in req
                or "top_k" in req or req.get("do_sample")):
            params.update(
                do_sample=True,
                temperature=float(req.get("temperature", 1.0)),
                top_k=int(req.get("top_k", 0)),
                top_p=float(req.get("top_p", 1.0)))
        stop = req.get("stop_token_ids")
        if stop is not None:
            params["stop_token_ids"] = [int(s) for s in stop]
        # SLO-aware scheduling rides the decode side: the decode worker
        # owns the slot pool the priority/deadline queue feeds
        if req.get("priority") is not None:
            params["priority"] = int(req["priority"])
        if req.get("slo_ms") is not None:
            slo = float(req["slo_ms"])
            if slo <= 0:
                raise ValueError("slo_ms must be > 0")
            params["slo_ms"] = slo
        # the router's request identity: the deathnote names it, so
        # poison blame follows the request across workers and retries
        if req.get("request_id") is not None:
            params["request_id"] = str(req["request_id"])
        lp_req = req.get("logprobs")
        want_logprobs = (lp_req is not None and lp_req is not False)
        if want_logprobs:
            params["logprobs"] = True
        return params, want_logprobs

    # ---- the prefill hop -------------------------------------------------
    def _prefill_post(self, handler, req):
        if self.draining:
            return handler._json(503, {
                "error": f"worker {self.replica_id} is draining"})
        try:
            ids = self._prompt_ids(req)
            max_tokens = int(req.get("max_tokens", 16))
            if max_tokens < 1:
                raise ValueError("max_tokens must be >= 1")
            channel = req.get("channel")
            if not channel:
                raise ValueError(
                    "prefill needs 'channel' — the decode worker's kv "
                    "handoff channel name")
            hid = str(req.get("handoff_id") or uuid.uuid4().hex)
        except (ValueError, TypeError) as e:
            return handler._json(400, {"error": str(e)})
        try:
            # the prefill runs ON the engine thread (only device-state
            # toucher); the shm push happens HERE on the handler thread —
            # the bundle is host numpy by then, and a full ring must
            # stall this request, not the engine loop
            bundle = self.submit_command(
                _ExportPrefill(ids, max_tokens))
            nbytes = self._sender(channel).send(hid, bundle)
        except (ValueError, TypeError, NotImplementedError) as e:
            return handler._json(400, {"error": str(e)})
        except Exception as e:
            get_logger().warning("prefill handoff %s -> %s failed "
                                 "(%s: %s)", hid, channel,
                                 type(e).__name__, e)
            return handler._json(500, {"error": f"{type(e).__name__}: {e}"})
        return handler._json(200, {
            "handoff_id": hid,
            "channel": channel,
            "prompt_tokens": int(bundle["prompt_tokens"]),
            "bytes": nbytes,
        })

    def _sender(self, channel: str):
        with self._senders_lock:
            s = self._senders.get(channel)
            if s is None:
                s = open_sender(channel)
                self._senders[channel] = s
            return s


# ---- model construction in the worker process -------------------------------

def _tiny_llama(spec: dict):
    from ..models.llama import LlamaConfig, LlamaForCausalLM

    kw = {k: spec[k] for k in ("num_hidden_layers", "hidden_size",
                               "num_attention_heads",
                               "num_key_value_heads") if k in spec}
    return LlamaForCausalLM(LlamaConfig.tiny(**kw))


MODEL_BUILDERS = {"tiny_llama": _tiny_llama}


def build_model(spec: dict):
    """Build the worker's model from its config spec: a registry ``kind``
    or a dotted ``factory`` ("pkg.module:fn", called with the spec).
    Weights must be DETERMINISTIC given the spec (every worker seeds
    before building) — prefill and decode engines only interoperate over
    identical weights."""
    import paddle_tpu as paddle

    paddle.seed(int(spec.get("seed", 0)))
    factory = spec.get("factory")
    if factory:
        mod_name, _, fn_name = factory.partition(":")
        if not fn_name:
            raise ValueError(
                f"factory must look like 'pkg.module:fn', got {factory!r}")
        import importlib

        return getattr(importlib.import_module(mod_name), fn_name)(spec)
    kind = spec.get("kind")
    if kind not in MODEL_BUILDERS:
        raise ValueError(f"unknown model kind {kind!r} "
                         f"(have {sorted(MODEL_BUILDERS)})")
    return MODEL_BUILDERS[kind](spec)


# ---- process entry ----------------------------------------------------------

def run_worker(cfg: dict):
    """Build the engine, join the pool, serve until SIGTERM.

    Config keys: ``replica_id``, ``role``, ``store`` (TCPStore
    host:port), ``world_size``, ``job_id``, ``ttl``, ``host``/``port``,
    ``model`` (builder spec), ``engine`` (ContinuousBatchEngine kwargs),
    ``platform`` (jax platform override), ``compile_cache`` (persistent
    XLA cache dir), ``kv_capacity_mb``, ``incident_dir``.
    """
    platform = cfg.get("platform")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    cache_dir = cfg.get("compile_cache")
    if cache_dir:
        import jax

        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", 0)
        except Exception as e:  # older jax without the knobs: run uncached
            get_logger().debug("worker: compile cache unavailable "
                               "(%s: %s)", type(e).__name__, e)
    from ..serving import ContinuousBatchEngine

    replica_id = int(cfg.get("replica_id", 0))
    role = cfg.get("role", "unified")
    job_id = cfg.get("job_id", "serve")
    ttl = float(cfg.get("ttl", 5.0))
    if cfg.get("incident_dir"):
        from ..observability.flightrecorder import install_reporter

        install_reporter(cfg["incident_dir"])
    # chaos: a plan exported by the launcher/dryrun installs here with
    # this worker's scope, arming the in-process injection points
    # (kv_handoff.send, worker.request, worker.step)
    injector = _chaos.install_from_env(scope=f"worker:{replica_id}")

    model = build_model(cfg.get("model", {}))
    engine = ContinuousBatchEngine(model, **cfg.get("engine", {}))
    # the sentinel records the model spec into divergence bundles so
    # scripts/replay_divergence.py can rebuild the model offline
    engine.sentinel.model_spec = cfg.get("model", {})
    if injector is not None:
        _chaos.arm_engine(engine, injector)
    if cfg.get("deathnote"):
        # supervised worker: arm the pre-dispatch blame record so a
        # crash mid-dispatch names exactly the rids it died stepping
        from .supervisor import Deathnote

        engine.deathnote = Deathnote(cfg["deathnote"])

    kv_receiver = None
    if role in ("decode", "unified"):
        kv_receiver = make_receiver(
            name=f"/pdtpu_kv_{job_id}_{replica_id}_{os.getpid()}",
            capacity_mb=int(cfg.get("kv_capacity_mb", 64)))

    elastic = ElasticManager(endpoint=cfg["store"], rank=replica_id,
                             world_size=int(cfg.get("world_size", 1)),
                             ttl=ttl, job_id=job_id)
    srv = WorkerServer(engine, role=role, replica_id=replica_id,
                       elastic=elastic, kv_receiver=kv_receiver,
                       handoff_wait_s=float(cfg.get("handoff_wait_s",
                                                    30.0)),
                       model_name=cfg.get("model_name", "paddle-tpu"),
                       host=cfg.get("host", "127.0.0.1"),
                       port=int(cfg.get("port", 0)),
                       # correctness-sentinel knobs (None defers to the
                       # PDTPU_AUDIT_RATE / PDTPU_CANARY_INTERVAL_S /
                       # PDTPU_DIVERGENCE_DIR environment)
                       audit_rate=cfg.get("audit_rate"),
                       canary_interval_s=cfg.get("canary_interval_s"),
                       divergence_dir=cfg.get("divergence_dir"))
    srv.start()
    host, port = srv.address
    # lease first, metadata second: the pool only reads metadata for
    # ranks whose lease is already fresh, so a half-registered worker is
    # invisible rather than half-visible
    meta = {
        "host": host, "port": port, "role": role, "pid": os.getpid(),
        "kv_channel": kv_receiver.name if kv_receiver else None,
    }

    def _kv_meta():
        # prefix-hash summary + headroom for the router: the
        # prefix-affinity / capacity feedstock (ROADMAP items 3a, 4)
        atlas = getattr(engine, "kvatlas", None)
        return atlas.cluster_summary() if atlas is not None else None

    elastic.register()
    elastic.register_metadata(dict(meta, kv=_kv_meta()))
    get_logger().info("cluster worker %s (%s) serving on %s:%s",
                      replica_id, role, host, port)

    done = threading.Event()

    def _republish():
        # register_metadata is a plain store set, so the kv summary can
        # refresh on the lease cadence; the pool re-reads metadata for
        # alive ranks every refresh()
        while not done.wait(max(1.0, ttl / 2.0)):
            try:
                elastic.register_metadata(dict(meta, kv=_kv_meta()))
            except Exception:  # pdlint: disable=silent-exception -- a metadata refresh must never kill the serving worker; the stale summary just ages out
                pass

    threading.Thread(target=_republish, daemon=True,
                     name="kv-meta-republish").start()

    def _term(signum, frame):
        # clean teardown: deregister (peers must not read this exit as a
        # lapsed lease), stop serving, leave
        elastic.mark_done()
        done.set()

    signal.signal(signal.SIGTERM, _term)
    try:
        done.wait()
    except KeyboardInterrupt:
        elastic.mark_done()
    srv.close()


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: python -m paddle_tpu.serving_cluster.worker "
              "'<json config>' | <config.json>", file=sys.stderr)
        return 2
    raw = argv[0]
    if raw.lstrip().startswith("{"):
        cfg = json.loads(raw)
    else:
        with open(raw, encoding="utf-8") as f:
            cfg = json.load(f)
    run_worker(cfg)
    return 0


if __name__ == "__main__":
    sys.exit(main())
