"""Disaggregated serving tier: prefill/decode workers behind a
queue-aware router.

The single-process ``CompletionServer`` scaled out (ROADMAP item 1):

- :mod:`worker` — a ``ContinuousBatchEngine`` per process in a role
  (``prefill`` | ``decode`` | ``unified``), joining the pool through
  ``distributed/elastic.py``'s lease/heartbeat + metadata;
- :mod:`pool` — the router's membership + occupancy view (lease
  freshness, ``/health`` polls, pending placements);
- :mod:`router` — the front door: queue-depth-aware least-loaded
  placement, SSE relay, bounded-retry failover, live-migration
  continuations, ``POST /drain`` graceful-drain orchestration,
  cross-process ``traceparent`` propagation;
- :mod:`kv_handoff` — prefill→decode KV shipping over
  ``io/shm_channel`` (device collectives pluggable); migration bundles
  ride the same transport;
- :mod:`launcher` — config → running tier (``scripts/serve_cluster.py``
  is the CLI);
- :mod:`supervisor` — self-healing: worker restart with backoff + a
  per-worker circuit breaker, deathnote-precise poison-request
  quarantine, cluster-level incident indexing.

See docs/SERVING.md "Disaggregated deployment" and "Failure domains &
migration runbook"; :mod:`paddle_tpu.chaos` injects the failures this
tier claims to absorb.
"""
from .kv_handoff import KvHandoffReceiver, KvHandoffSender  # noqa: F401
from .launcher import Cluster, launch_cluster, load_config  # noqa: F401
from .pool import WorkerInfo, WorkerPool                    # noqa: F401
from .router import RouterServer                            # noqa: F401
from .supervisor import (CircuitBreaker, Deathnote,         # noqa: F401
                         QuarantineLedger, RestartBackoff,
                         WorkerSupervisor)
from .worker import WorkerServer, run_worker                # noqa: F401

__all__ = [
    "CircuitBreaker", "Cluster", "Deathnote", "KvHandoffReceiver",
    "KvHandoffSender", "QuarantineLedger", "RestartBackoff",
    "RouterServer", "WorkerInfo", "WorkerPool", "WorkerServer",
    "WorkerSupervisor", "launch_cluster", "load_config", "run_worker",
]
