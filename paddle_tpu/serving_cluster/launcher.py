"""Cluster launcher: one call from config to a serving tier.

Spawns N worker processes (each ``python -m
paddle_tpu.serving_cluster.worker`` with a JSON config — real processes,
so a worker death is a process death, exactly what the pool's lease
watch and the router's retry path are built for), stands up the TCPStore
the leases rendezvous on, and runs the WorkerPool + RouterServer in the
calling process. ``scripts/serve_cluster.py`` is the CLI over this; the
tier-1 multi-engine dryrun gate drives it directly.

Config shape (TOML or JSON; see docs/SERVING.md "Disaggregated
deployment")::

    [cluster]
    host = "127.0.0.1"   # router bind
    port = 0             # 0 = ephemeral
    job_id = "serve"
    ttl = 5.0            # lease ttl seconds
    max_retries = 2

    [model]
    kind = "tiny_llama"  # or factory = "pkg.module:fn"
    seed = 0

    [engine]
    max_batch = 4
    max_len = 64
    page_size = 8

    [[workers]]
    role = "unified"
    count = 2
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import List, Optional

from ..distributed.log_utils import get_logger
from ..distributed.store import TCPStore
from .pool import WorkerPool
from .router import RouterServer

__all__ = ["Cluster", "launch_cluster", "load_config", "expand_workers"]


def load_config(path: str) -> dict:
    """TOML (via tomllib, python >= 3.11) or JSON config file."""
    if path.endswith(".toml"):
        try:
            import tomllib
        except ImportError as e:
            raise RuntimeError(
                "TOML configs need python >= 3.11 (tomllib); use a JSON "
                "config on this interpreter") from e
        with open(path, "rb") as f:
            return tomllib.load(f)
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def expand_workers(cfg: dict) -> List[dict]:
    """The ``workers`` section expanded to one role entry per process
    (``count`` multiplies); defaults to two unified workers."""
    specs = cfg.get("workers") or [{"role": "unified", "count": 2}]
    out = []
    for spec in specs:
        for _ in range(int(spec.get("count", 1))):
            out.append({k: v for k, v in spec.items() if k != "count"})
    return out


class Cluster:
    """A running tier: router (in-process) + worker subprocesses."""

    def __init__(self, cfg: dict, wait: bool = True,
                 wait_timeout: float = 180.0):
        cluster = dict(cfg.get("cluster") or {})
        host = cluster.get("host", "127.0.0.1")
        job_id = cluster.get("job_id", "serve")
        ttl = float(cluster.get("ttl", 5.0))
        worker_specs = expand_workers(cfg)
        self.processes: List[subprocess.Popen] = []
        self._replica_pids = {}
        # the lease/metadata rendezvous point: master in THIS process so
        # the router outliving every worker also owns the store
        self.store = TCPStore(host, 0, is_master=True,
                              world_size=len(worker_specs) + 1)
        endpoint = f"{host}:{self.store.port}"
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = (repo_root + os.pathsep
                             + env.get("PYTHONPATH", ""))
        for replica_id, spec in enumerate(worker_specs):
            wcfg = {
                "replica_id": replica_id,
                "role": spec.get("role", "unified"),
                "store": endpoint,
                "world_size": len(worker_specs),
                "job_id": job_id,
                "ttl": ttl,
                "host": host,
                "port": int(spec.get("port", 0)),
                "model": cfg.get("model") or {},
                "engine": cfg.get("engine") or {},
                "model_name": cluster.get("model_name", "paddle-tpu"),
                "platform": cluster.get("platform"),
                "compile_cache": cluster.get("compile_cache"),
                "incident_dir": cluster.get("incident_dir"),
                "handoff_wait_s": cluster.get("handoff_wait_s", 30.0),
            }
            # -c (not -m): runpy warns when the module is already in
            # sys.modules via the package import, and the entry is the
            # same main() either way
            p = subprocess.Popen(
                [sys.executable, "-c",
                 "import sys; "
                 "from paddle_tpu.serving_cluster.worker import main; "
                 "sys.exit(main(sys.argv[1:]))",
                 json.dumps(wcfg)], env=env, cwd=repo_root)
            self.processes.append(p)
            self._replica_pids[replica_id] = p
        self.pool = WorkerPool(store=self.store,
                               world_size=len(worker_specs),
                               job_id=job_id, ttl=ttl)
        self.router: Optional[RouterServer] = None
        try:
            if wait and not self.pool.wait_for_workers(
                    len(worker_specs), timeout=wait_timeout):
                raise RuntimeError(
                    f"cluster: only {self.pool.alive_count()} of "
                    f"{len(worker_specs)} workers joined within "
                    f"{wait_timeout}s")
            self.pool.start()
            self.router = RouterServer(
                self.pool, host=host, port=int(cluster.get("port", 0)),
                model_name=cluster.get("model_name", "paddle-tpu"),
                max_retries=int(cluster.get("max_retries", 2))).start()
        except BaseException:
            self.close()
            raise

    # ---- operations ------------------------------------------------------
    @property
    def address(self):
        return self.router.address

    def kill_worker(self, replica_id: int):
        """SIGKILL one worker (crash simulation — no clean deregistration,
        the lease must lapse / sockets must break for anyone to notice)."""
        self._replica_pids[replica_id].kill()

    def close(self):
        if self.router is not None:
            self.router.close()
        self.pool.close()
        for p in self.processes:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + 10
        for p in self.processes:
            remain = max(0.1, deadline - time.monotonic())
            try:
                p.wait(timeout=remain)
            except subprocess.TimeoutExpired:
                get_logger().warning(
                    "cluster: worker pid %s ignored SIGTERM; killing",
                    p.pid)
                p.kill()
                p.wait(timeout=5)
        self.store.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def launch_cluster(cfg: dict, **kw) -> Cluster:
    """Spawn workers + pool + router from a parsed config dict."""
    return Cluster(cfg, **kw)
