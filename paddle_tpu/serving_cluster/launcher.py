"""Cluster launcher: one call from config to a serving tier.

Spawns N worker processes (each ``python -m
paddle_tpu.serving_cluster.worker`` with a JSON config — real processes,
so a worker death is a process death, exactly what the pool's lease
watch and the router's retry path are built for), stands up the TCPStore
the leases rendezvous on, and runs the WorkerPool + RouterServer in the
calling process. ``scripts/serve_cluster.py`` is the CLI over this; the
tier-1 multi-engine dryrun gate drives it directly.

Since the self-healing PR the launcher does not spawn-and-forget: worker
subprocesses are OWNED by a :class:`~.supervisor.WorkerSupervisor`
(``supervise=False`` opts out) that restarts dead workers with backoff +
a per-worker circuit breaker, blames crashes through the deathnote /
quarantine ledger, and sweeps incident bundles into a cluster-level
index. Teardown is total: ``close()`` is idempotent (atexit-armed),
propagates SIGTERM to every worker and REAPS it — a torn-down cluster
leaves no zombies — and SIGTERM/SIGINT on the launcher process itself
propagate to the workers before the previous handler runs.

Config shape (TOML or JSON; see docs/SERVING.md "Disaggregated
deployment")::

    [cluster]
    host = "127.0.0.1"   # router bind
    port = 0             # 0 = ephemeral
    job_id = "serve"
    ttl = 5.0            # lease ttl seconds
    max_retries = 2
    incident_dir = "incidents"   # also the supervisor's state dir

    [supervisor]         # optional overrides (see WorkerSupervisor)
    backoff_base_s = 0.5
    breaker_threshold = 5
    breaker_window_s = 60.0

    [model]
    kind = "tiny_llama"  # or factory = "pkg.module:fn"
    seed = 0

    [engine]
    max_batch = 4
    max_len = 64
    page_size = 8

    [[workers]]
    role = "unified"
    count = 2
"""
from __future__ import annotations

import atexit
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import List, Optional

from ..chaos.inject import ENV_INCARNATION
from ..distributed.log_utils import get_logger
from ..distributed.store import TCPStore
from .pool import WorkerPool
from .router import RouterServer
from .supervisor import WorkerSupervisor

__all__ = ["Cluster", "launch_cluster", "load_config", "expand_workers"]


def load_config(path: str) -> dict:
    """TOML (via tomllib, python >= 3.11) or JSON config file."""
    if path.endswith(".toml"):
        try:
            import tomllib
        except ImportError as e:
            raise RuntimeError(
                "TOML configs need python >= 3.11 (tomllib); use a JSON "
                "config on this interpreter") from e
        with open(path, "rb") as f:
            return tomllib.load(f)
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def expand_workers(cfg: dict) -> List[dict]:
    """The ``workers`` section expanded to one role entry per process
    (``count`` multiplies); defaults to two unified workers."""
    specs = cfg.get("workers") or [{"role": "unified", "count": 2}]
    out = []
    for spec in specs:
        for _ in range(int(spec.get("count", 1))):
            out.append({k: v for k, v in spec.items() if k != "count"})
    return out


class Cluster:
    """A running tier: router (in-process) + supervised worker
    subprocesses."""

    def __init__(self, cfg: dict, wait: bool = True,
                 wait_timeout: float = 180.0, supervise: bool = True,
                 install_signal_handlers: bool = True):
        cluster = dict(cfg.get("cluster") or {})
        host = cluster.get("host", "127.0.0.1")
        job_id = cluster.get("job_id", "serve")
        ttl = float(cluster.get("ttl", 5.0))
        worker_specs = expand_workers(cfg)
        self.processes: List[subprocess.Popen] = []  # first incarnations
        self._replica_pids = {}
        self._closed = False
        self._prev_signals = {}
        # the lease/metadata rendezvous point: master in THIS process so
        # the router outliving every worker also owns the store
        self.store = TCPStore(host, 0, is_master=True,
                              world_size=len(worker_specs) + 1)
        endpoint = f"{host}:{self.store.port}"
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = (repo_root + os.pathsep
                             + env.get("PYTHONPATH", ""))
        self.supervisor: Optional[WorkerSupervisor] = None
        if supervise:
            incident_dir = cluster.get("incident_dir")
            state_dir = incident_dir or tempfile.mkdtemp(
                prefix="pdtpu-cluster-")
            self.supervisor = WorkerSupervisor(
                incident_dir=incident_dir, state_dir=state_dir,
                **dict(cfg.get("supervisor") or {}))
        for replica_id, spec in enumerate(worker_specs):
            wcfg = {
                "replica_id": replica_id,
                "role": spec.get("role", "unified"),
                "store": endpoint,
                "world_size": len(worker_specs),
                "job_id": job_id,
                "ttl": ttl,
                "host": host,
                "port": int(spec.get("port", 0)),
                "model": cfg.get("model") or {},
                "engine": cfg.get("engine") or {},
                "model_name": cluster.get("model_name", "paddle-tpu"),
                "platform": cluster.get("platform"),
                "compile_cache": cluster.get("compile_cache"),
                "incident_dir": cluster.get("incident_dir"),
                "handoff_wait_s": cluster.get("handoff_wait_s", 30.0),
            }
            if self.supervisor is not None:
                wcfg["deathnote"] = self.supervisor.deathnote_path(
                    replica_id)
            spawn = self._make_spawn(wcfg, env, repo_root)
            p = spawn(replica_id, 0)
            self.processes.append(p)
            self._replica_pids[replica_id] = p
            if self.supervisor is not None:
                self.supervisor.adopt(replica_id, spawn, p)
        self.pool = WorkerPool(store=self.store,
                               world_size=len(worker_specs),
                               job_id=job_id, ttl=ttl)
        self.router: Optional[RouterServer] = None
        # cluster.ts_interval_s is a SCOPED cadence override: remember
        # the process store's interval so close() restores it — a
        # gate-speed cluster (0.25s sampling) torn down inside a larger
        # process must not leave 4 Hz background sampling behind
        from ..observability.timeseries import get_store
        self._prev_ts_interval = get_store().interval_s
        # teardown must run even on an unhandled exit: atexit-armed and
        # idempotent (a second close(), from atexit after an explicit
        # close or a signal, is a no-op)
        atexit.register(self.close)
        if install_signal_handlers:
            self._install_signals()
        try:
            if wait and not self.pool.wait_for_workers(
                    len(worker_specs), timeout=wait_timeout):
                raise RuntimeError(
                    f"cluster: only {self.pool.alive_count()} of "
                    f"{len(worker_specs)} workers joined within "
                    f"{wait_timeout}s")
            self.pool.start()
            self.router = RouterServer(
                self.pool, host=host, port=int(cluster.get("port", 0)),
                model_name=cluster.get("model_name", "paddle-tpu"),
                max_retries=int(cluster.get("max_retries", 2)),
                # cluster watchtower knobs: sampler cadence and the
                # alert-window scale (the chaos dryrun runs second-scale
                # windows so fire->resolve is observable in one gate)
                ts_interval_s=cluster.get("ts_interval_s"),
                alert_time_scale=float(
                    cluster.get("alert_time_scale", 1.0)),
                supervisor=self.supervisor).start()
            if self.supervisor is not None:
                # the router's in-flight journal is the supervisor's
                # whole-batch blame fallback; wired here because the
                # router needs the pool first
                self.supervisor.inflight_fn = self.router.inflight_on
                self.supervisor.start()
        except BaseException:
            self.close()
            raise

    def _make_spawn(self, wcfg: dict, env: dict, repo_root: str):
        """One worker's spawn closure — re-invoked by the supervisor on
        restart with a bumped incarnation (the chaos injector scopes
        faults by it, so a planned kill does not re-fire in the respawn
        it caused)."""

        def spawn(replica_id: int, incarnation: int) -> subprocess.Popen:
            child_env = dict(env)
            child_env[ENV_INCARNATION] = str(int(incarnation))
            # -c (not -m): runpy warns when the module is already in
            # sys.modules via the package import, and the entry is the
            # same main() either way
            return subprocess.Popen(
                [sys.executable, "-c",
                 "import sys; "
                 "from paddle_tpu.serving_cluster.worker import main; "
                 "sys.exit(main(sys.argv[1:]))",
                 json.dumps(wcfg)], env=child_env, cwd=repo_root)

        return spawn

    # ---- signals ---------------------------------------------------------
    def _install_signals(self):
        """Propagate SIGTERM/SIGINT to the worker subprocesses: the
        launcher dying must not orphan the tier. The previous handler
        (KeyboardInterrupt for SIGINT, the default death for SIGTERM)
        still runs AFTER the teardown. No-op off the main thread —
        signal wiring is impossible there, and close()/atexit still
        reap."""
        if threading.current_thread() is not threading.main_thread():
            return

        def handler(signum, frame):
            self.close()
            prev = self._prev_signals.get(signum)
            if callable(prev):
                prev(signum, frame)
            elif prev != signal.SIG_IGN:
                signal.signal(signum, signal.SIG_DFL)
                os.kill(os.getpid(), signum)

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                self._prev_signals[sig] = signal.signal(sig, handler)
            except (ValueError, OSError) as e:
                get_logger().debug("cluster: signal %s not hooked (%s)",
                                   sig, e)

    def _restore_signals(self):
        for sig, prev in self._prev_signals.items():
            try:
                if signal.getsignal(sig) is not prev:
                    signal.signal(sig, prev)
            except (ValueError, OSError, TypeError):
                pass  # teardown off the main thread cannot rewire signals; the process is exiting anyway
        self._prev_signals = {}

    # ---- operations ------------------------------------------------------
    @property
    def address(self):
        return self.router.address

    def kill_worker(self, replica_id: int):
        """SIGKILL one worker's CURRENT incarnation (crash simulation —
        no clean deregistration, the lease must lapse / sockets must
        break for anyone to notice; under supervision the worker then
        restarts on the backoff ladder)."""
        if self.supervisor is not None:
            self.supervisor.kill(replica_id)
        else:
            self._replica_pids[replica_id].kill()

    def close(self):
        """Tear the tier down: stop routing, stop supervising, SIGTERM
        every worker and REAP it. Idempotent — the atexit hook, a signal
        handler and an explicit close can all race here safely."""
        if self._closed:
            return
        self._closed = True
        self._restore_signals()
        try:
            atexit.unregister(self.close)
        except Exception:  # pdlint: disable=silent-exception -- interpreter shutdown may have torn atexit down already; closing proceeds regardless
            pass
        if self.router is not None:
            self.router.close()
        from ..observability.timeseries import get_store

        get_store().set_interval(self._prev_ts_interval)
        self.pool.close()
        if self.supervisor is not None:
            # the supervisor owns the children now: terminate + reap
            # (and stop the monitor FIRST so nothing respawns what the
            # teardown just killed)
            self.supervisor.close()
        else:
            for p in self.processes:
                if p.poll() is None:
                    p.terminate()
            deadline = time.monotonic() + 10
            for p in self.processes:
                remain = max(0.1, deadline - time.monotonic())
                try:
                    p.wait(timeout=remain)
                except subprocess.TimeoutExpired:
                    get_logger().warning(
                        "cluster: worker pid %s ignored SIGTERM; killing",
                        p.pid)
                    p.kill()
                    p.wait(timeout=5)
        self.store.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def launch_cluster(cfg: dict, **kw) -> Cluster:
    """Spawn workers + pool + router from a parsed config dict."""
    return Cluster(cfg, **kw)
