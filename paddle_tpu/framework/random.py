"""Stateful-looking RNG over JAX's functional PRNG.

The reference framework exposes a global seed (`paddle.seed`) plus per-mesh
RNG state trackers for parallel layers
(`python/paddle/distributed/fleet/layers/mpu/random.py::RNGStatesTracker`).
We reproduce that surface:

- Eager mode: a process-global key that is split on every draw.
- Traced mode (inside ``paddle_tpu.jit``-compiled functions): random ops draw
  from a *traced* key installed via :func:`rng_context`, so each compiled step
  gets fresh randomness as an explicit input instead of baking a constant.
- :class:`RNGStatesTracker` gives named RNG streams for tensor-parallel
  regions (same-seed-in-replicated-regions / different-seed-per-mp-rank).
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional

import jax
import numpy as np

_state = threading.local()


def _global():
    if not hasattr(_state, "key"):
        _state.key = jax.random.key(0)
        _state.seed_value = 0
    return _state


def seed(value: int):
    """paddle.seed parity: reseed the global generator."""
    st = _global()
    st.key = jax.random.key(int(value))
    st.seed_value = int(value)
    return st.key


def get_rng_state():
    return _global().key


def set_rng_state(key):
    _global().key = key


@contextlib.contextmanager
def rng_context(key):
    """Install a (possibly traced) key that next_key() draws from.

    Used by the jit bridge: the compiled train step takes an explicit key
    argument and installs it here so dropout etc. stays fresh per step.
    """
    st = _global()
    prev = getattr(st, "ctx_key", None)
    prev_count = getattr(st, "ctx_count", 0)
    st.ctx_key = key
    st.ctx_count = 0
    try:
        yield
    finally:
        st.ctx_key = prev
        st.ctx_count = prev_count


def in_rng_context() -> bool:
    return getattr(_global(), "ctx_key", None) is not None


def next_key():
    """Return a fresh PRNG key (functional split under the hood)."""
    st = _global()
    ctx = getattr(st, "ctx_key", None)
    if ctx is not None:
        # Traced context: fold in a per-draw counter so multiple draws in one
        # trace differ, while the key itself remains a traced value.
        st.ctx_count += 1
        return jax.random.fold_in(ctx, st.ctx_count)
    st.key, sub = jax.random.split(st.key)
    return sub


def host_rng():
    """Host-side numpy Generator derived from the framework key stream, so
    host-eager sampling ops (graph sampling, class_center_sample) are
    reproducible under paddle.seed like device ops."""
    import numpy as np

    key_data = np.asarray(jax.random.key_data(next_key()))
    return np.random.default_rng(int(key_data.reshape(-1)[-1]) & 0x7FFFFFFF)


class RNGStatesTracker:
    """Named RNG streams, parity with the reference's mpu RNGStatesTracker
    (fleet/layers/mpu/random.py): tensor-parallel dropout needs one stream
    shared across mp ranks and one unique per rank."""

    def __init__(self):
        self.states = {}

    def reset(self):
        self.states = {}

    def add(self, name: str, seed_: int):
        if name in self.states:
            raise ValueError(f"rng state {name} already exists")
        self.states[name] = jax.random.key(int(seed_))

    @contextlib.contextmanager
    def rng_state(self, name: str = "global_seed"):
        if name not in self.states:
            raise ValueError(f"rng state {name} does not exist")
        st = _global()
        prev = st.key
        st.key = self.states[name]
        try:
            yield
        finally:
            self.states[name] = st.key
            st.key = prev


_TRACKER = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _TRACKER
