"""Device abstraction.

Reference parity: paddle.device (python/paddle/device/__init__.py) +
phi Place types. On this stack a "place" is a jax.Device; the default device
is the first TPU chip when present, else CPU.
"""
from __future__ import annotations

import jax

_current = [None]


class Place:
    def __init__(self, device: "jax.Device"):
        self._device = device

    @property
    def platform(self):
        return self._device.platform

    def __repr__(self):
        return f"Place({self._device})"

    def __eq__(self, other):
        return isinstance(other, Place) and self._device == other._device


def _resolve(device):
    if device is None:
        return get_device_object()
    if isinstance(device, Place):
        return device._device
    if hasattr(device, "platform"):
        return device
    if isinstance(device, str):
        spec = device.lower()
        if ":" in spec:
            kind, idx = spec.split(":")
            idx = int(idx)
        else:
            kind, idx = spec, 0
        kind = {"gpu": "tpu", "xpu": "tpu", "cuda": "tpu"}.get(kind, kind)  # accelerator aliases
        devs = [d for d in jax.devices() if d.platform.startswith(kind)] or (
            jax.devices("cpu") if kind == "cpu" else []
        )
        if not devs:
            raise ValueError(f"no device matching {device!r}; available: {jax.devices()}")
        return devs[idx]
    raise TypeError(f"cannot resolve device from {device!r}")


def set_device(device: str):
    _current[0] = _resolve(device)
    return get_device()


def get_device() -> str:
    d = get_device_object()
    return f"{d.platform}:{d.id}"


def get_device_object():
    if _current[0] is None:
        _current[0] = jax.devices()[0]
    return _current[0]


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return any(d.platform == "tpu" for d in jax.devices())


def device_count() -> int:
    return jax.device_count()


def CPUPlace():
    return Place(jax.devices("cpu")[0])


def TPUPlace(idx=0):
    return Place(jax.devices()[idx])


CUDAPlace = TPUPlace  # API-compat alias: "the accelerator place"
CUDAPinnedPlace = CPUPlace  # pinned host memory: host-side arrays on TPU


def synchronize():
    """Block until all dispatched device work completes."""
    (jax.device_put(0) + 0).block_until_ready()


# ---- memory stats (paddle/phi/core/memory/stats.cc parity: live + peak
# trackers exposed as paddle.device.cuda.max_memory_allocated etc.; on TPU
# the numbers come from the runtime's per-device memory_stats()) -------------

def _mem_stats(device=None):
    dev = _resolve(device)  # None → the device selected via set_device
    stats = getattr(dev, "memory_stats", lambda: None)()
    return stats or {}


def memory_stats(device=None) -> dict:
    """The runtime's raw per-device allocator stats, as a plain dict
    (keys are runtime-dependent: bytes_in_use / peak_bytes_in_use /
    bytes_limit on TPU; {} on backends that don't track). The
    observability StepTimer publishes its memory gauges from this."""
    return dict(_mem_stats(device))


def memory_allocated(device=None) -> int:
    """Live bytes in use on the device (stats.cc Allocated stat)."""
    return int(_mem_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    """Peak bytes in use (stats.cc peak tracker)."""
    s = _mem_stats(device)
    return int(s.get("peak_bytes_in_use", s.get("bytes_in_use", 0)))


def memory_reserved(device=None) -> int:
    """Pool reservation (bytes_limit on TPU: HBM the runtime owns)."""
    s = _mem_stats(device)
    return int(s.get("bytes_reservable_limit", s.get("bytes_limit", 0)))


def max_memory_reserved(device=None) -> int:
    return memory_reserved(device)


def empty_cache():
    """paddle.device.cuda.empty_cache parity: no-op on TPU (XLA owns HBM;
    nothing user-facing to release)."""


# ---------------------------------------------------------------------------
# Round-3 device-surface tail (python/paddle/device/__init__.py parity)
# ---------------------------------------------------------------------------

XPUPlace = TPUPlace    # accelerator aliases: one device class serves all
IPUPlace = CPUPlace


def get_cudnn_version():
    """None — not a CUDA build (reference returns None without cudnn)."""
    return None


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    """The XLA compiler plays CINN's role and is always present."""
    return True


def is_compiled_with_distribute() -> bool:
    return True


def is_compiled_with_custom_device(device_type: str = None) -> bool:
    """TPU rides jax's pluggable-backend mechanism — the custom-device
    analog — so 'tpu' reports True."""
    return device_type in (None, "tpu")


def get_all_device_type():
    import jax

    try:
        return sorted({d.platform for d in jax.devices()})
    except RuntimeError:
        return ["cpu"]


def get_all_custom_device_type():
    return [t for t in get_all_device_type() if t not in ("cpu", "gpu")]


def get_available_device():
    import jax

    try:
        return [f"{d.platform}:{d.id}" for d in jax.devices()]
    except RuntimeError:
        return ["cpu:0"]


def get_available_custom_device():
    return [d for d in get_available_device()
            if not d.startswith(("cpu", "gpu"))]


class Stream:
    """paddle.device.Stream parity. XLA runs one ordered stream per device
    (async dispatch); separate user streams do not exist, so every Stream
    maps to the device's implicit stream and synchronize() drains it."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        synchronize()

    def wait_stream(self, stream):
        synchronize()

    def record_event(self, event=None):
        ev = event or Event()
        ev.record(self)
        return ev


class Event:
    """paddle.device.Event parity over the single-stream model: record
    snapshots a sync point; query/elapsed ride block_until_ready."""

    def __init__(self, device=None, enable_timing=False, blocking=False,
                 interprocess=False):
        self._time = None

    def record(self, stream=None):
        import time as _time

        synchronize()
        self._time = _time.perf_counter()

    def query(self) -> bool:
        return True

    def synchronize(self):
        synchronize()

    def elapsed_time(self, end_event) -> float:
        if self._time is None or end_event._time is None:
            return 0.0
        return (end_event._time - self._time) * 1000.0


_CURRENT_STREAM = Stream()


def current_stream(device=None) -> Stream:
    return _CURRENT_STREAM


def set_stream(stream: Stream):
    global _CURRENT_STREAM
    prev = _CURRENT_STREAM
    _CURRENT_STREAM = stream
    return prev


class stream_guard:
    """Context manager parity; the guarded region still executes on the
    device's single ordered stream."""

    def __init__(self, stream):
        self._stream = stream
        self._prev = None

    def __enter__(self):
        self._prev = set_stream(self._stream)
        return self._stream

    def __exit__(self, *exc):
        set_stream(self._prev)
        return False


class _CudaNamespace:
    """paddle.device.cuda parity (python/paddle/device/cuda/__init__.py):
    the CUDA-named device-management surface, served by the TPU runtime
    (one accelerator namespace, reference-compatible names)."""

    Stream = None           # bound below (classes defined above)
    Event = None

    @staticmethod
    def current_stream(device=None):
        return current_stream(device)

    @staticmethod
    def synchronize(device=None):
        return synchronize()

    @staticmethod
    def device_count():
        """Accelerator count — 0 on CPU-only hosts (reference semantics:
        guard code relies on 0 meaning 'no accelerator')."""
        import jax

        try:
            return len([d for d in jax.devices() if d.platform != "cpu"])
        except RuntimeError:
            return 0

    empty_cache = staticmethod(lambda: empty_cache())
    stream_guard = staticmethod(lambda stream: stream_guard(stream))

    @staticmethod
    def memory_allocated(device=None):
        return memory_allocated(device)

    @staticmethod
    def max_memory_allocated(device=None):
        return max_memory_allocated(device)

    @staticmethod
    def memory_reserved(device=None):
        return memory_reserved(device)

    @staticmethod
    def max_memory_reserved(device=None):
        return max_memory_reserved(device)

    @staticmethod
    def get_device_properties(device=None):
        import jax

        d = jax.devices()[0]
        import types

        return types.SimpleNamespace(
            name=f"{d.platform}:{d.device_kind}",
            total_memory=memory_reserved(device),
            major=0, minor=0, multi_processor_count=1)

    @staticmethod
    def get_device_name(device=None):
        import jax

        return jax.devices()[0].device_kind

    @staticmethod
    def get_device_capability(device=None):
        return (0, 0)


cuda = _CudaNamespace()
cuda.Stream = Stream
cuda.Event = Event
