from . import dtype, random, device
from .dtype import (
    set_default_dtype,
    get_default_dtype,
    convert_dtype,
)
from .random import seed, get_rng_state, set_rng_state, get_rng_state_tracker
