"""Dtype system for paddle_tpu.

Mirrors the dtype surface of the reference framework (paddle's
``paddle/phi/common/data_type.h`` and ``python/paddle/framework/dtype.py``)
but is a thin veneer over numpy/jax dtypes: on TPU the canonical compute
dtype is bfloat16 and the canonical accumulate dtype is float32.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtypes (exported at top level as paddle_tpu.float32 etc.)
bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128
float8_e4m3fn = jnp.float8_e4m3fn
float8_e5m2 = jnp.float8_e5m2

_ALIASES = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "fp32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn,
    "float8_e5m2": float8_e5m2,
}

_DEFAULT_DTYPE = [jnp.float32]


def convert_dtype(dtype):
    """Normalise any dtype spec (str, np.dtype, jnp dtype, Tensor dtype) to a
    numpy dtype object usable by jax.

    Reference parity: ``python/paddle/base/data_feeder.py::convert_dtype``.
    """
    if dtype is None:
        return None
    if isinstance(dtype, str):
        name = dtype.replace("paddle.", "").replace("paddle_tpu.", "")
        if name in _ALIASES:
            return _canonical(np.dtype(_ALIASES[name]))
        return _canonical(np.dtype(name))
    return _canonical(np.dtype(dtype))


def _canonical(d: "np.dtype") -> "np.dtype":
    """Map 64-bit dtypes to their 32-bit TPU-native forms unless jax x64 is
    enabled (TPUs have no fast 64-bit path; this mirrors jax canonicalization
    without the per-op warning)."""
    import jax

    if jax.config.jax_enable_x64:
        return d
    if d == np.dtype(np.int64):
        return np.dtype(np.int32)
    if d == np.dtype(np.uint64):
        return np.dtype(np.uint32)
    if d == np.dtype(np.float64):
        return np.dtype(np.float32)
    if d == np.dtype(np.complex128):
        return np.dtype(np.complex64)
    return d


def dtype_name(dtype) -> str:
    return np.dtype(dtype).name


def set_default_dtype(d):
    """paddle.set_default_dtype parity (python/paddle/framework/framework.py)."""
    d = convert_dtype(d)
    if d not in (np.dtype(jnp.float16), np.dtype(jnp.bfloat16), np.dtype(jnp.float32), np.dtype(jnp.float64)):
        raise TypeError(f"set_default_dtype only supports floating dtypes, got {d}")
    _DEFAULT_DTYPE[0] = d


def get_default_dtype():
    return np.dtype(_DEFAULT_DTYPE[0]).name


class dtype_guard:
    """Scoped default-dtype override (PaddleNLP ``dtype_guard`` pattern):
    layers created inside the block default their parameters to ``d`` —
    how a bf16 model is constructed with bf16 storage (params in HBM at
    2 bytes) while the global default stays float32."""

    def __init__(self, d):
        self._d = d
        self._prev = None

    def __enter__(self):
        self._prev = _DEFAULT_DTYPE[0]
        set_default_dtype(self._d)
        return self

    def __exit__(self, *exc):
        _DEFAULT_DTYPE[0] = self._prev
        return False


def default_float_dtype():
    return _DEFAULT_DTYPE[0]


def is_floating_point_dtype(dtype) -> bool:
    return np.issubdtype(convert_dtype(dtype), np.floating) or convert_dtype(dtype) in (
        np.dtype(jnp.bfloat16),
        np.dtype(jnp.float8_e4m3fn),
        np.dtype(jnp.float8_e5m2),
    )


def is_integer_dtype(dtype) -> bool:
    return np.issubdtype(convert_dtype(dtype), np.integer)


def is_complex_dtype(dtype) -> bool:
    return np.issubdtype(convert_dtype(dtype), np.complexfloating)


def is_inexact_dtype(dtype) -> bool:
    """True if gradients can flow through values of this dtype."""
    return is_floating_point_dtype(dtype) or is_complex_dtype(dtype)
