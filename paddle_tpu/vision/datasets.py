"""Vision datasets.

Reference parity: python/paddle/vision/datasets/ (MNIST, FashionMNIST,
Cifar10/100, Flowers, VOC2012, DatasetFolder, ImageFolder). The TPU image
has no egress, so ``download=True`` with missing files raises with
instructions instead of fetching; all loaders accept pre-downloaded files
via the same paths/formats the reference uses.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Callable, List, Optional

import numpy as np

from ..io.dataset import Dataset

_NO_EGRESS = ("{name}: data file not found at {path} and this environment "
              "has no network egress; place the standard {name} files there "
              "(same format as the reference's cached download) or pass "
              "the path explicitly")


class MNIST(Dataset):
    """datasets/mnist.py parity: idx-ubyte files."""

    NAME = "mnist"
    _IMG = {"train": "train-images-idx3-ubyte.gz",
            "test": "t10k-images-idx3-ubyte.gz"}
    _LBL = {"train": "train-labels-idx1-ubyte.gz",
            "test": "t10k-labels-idx1-ubyte.gz"}

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="cv2"):
        mode = mode.lower()
        root = os.path.expanduser(f"~/.cache/paddle/dataset/{self.NAME}")
        self.image_path = image_path or os.path.join(root, self._IMG[mode])
        self.label_path = label_path or os.path.join(root, self._LBL[mode])
        self.transform = transform
        if not os.path.exists(self.image_path):
            raise RuntimeError(_NO_EGRESS.format(name=self.NAME,
                                                 path=self.image_path))
        self.images, self.labels = self._parse()

    def _open(self, path):
        return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")

    def _parse(self):
        with self._open(self.image_path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
        with self._open(self.label_path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
        return images, labels

    def __getitem__(self, idx):
        img = self.images[idx][:, :, None]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """datasets/cifar.py parity: the python-pickle tar."""

    _META = dict(name="cifar-10-python.tar.gz", prefix="cifar-10-batches-py",
                 label_key=b"labels",
                 train=[f"data_batch_{i}" for i in range(1, 6)],
                 test=["test_batch"])

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="cv2"):
        mode = mode.lower()
        root = os.path.expanduser("~/.cache/paddle/dataset/cifar")
        self.data_file = data_file or os.path.join(root, self._META["name"])
        self.transform = transform
        if not os.path.exists(self.data_file):
            raise RuntimeError(_NO_EGRESS.format(name="cifar",
                                                 path=self.data_file))
        names = self._META[mode]
        images, labels = [], []
        with tarfile.open(self.data_file) as tf:
            for n in names:
                with tf.extractfile(f"{self._META['prefix']}/{n}") as f:
                    d = pickle.load(f, encoding="bytes")
                images.append(d[b"data"].reshape(-1, 3, 32, 32))
                labels.extend(d[self._META["label_key"]])
        self.images = np.concatenate(images).transpose(0, 2, 3, 1)  # HWC
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    _META = dict(name="cifar-100-python.tar.gz", prefix="cifar-100-python",
                 label_key=b"fine_labels", train=["train"], test=["test"])


_IMG_EXTS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")


def _default_loader(path):
    if path.endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image

        return np.asarray(Image.open(path).convert("RGB"))
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(f"no image backend available for {path}") from e


class DatasetFolder(Dataset):
    """datasets/folder.py parity: root/class_x/xxx.ext layout."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        self.transform = transform
        extensions = extensions or _IMG_EXTS
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"no class folders under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            for dirpath, _, files in sorted(os.walk(os.path.join(root, c))):
                for fn in sorted(files):
                    path = os.path.join(dirpath, fn)
                    ok = (is_valid_file(path) if is_valid_file
                          else fn.lower().endswith(extensions))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"no valid files found under {root}")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """datasets/folder.py ImageFolder parity: flat dir, no labels."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.loader = loader or _default_loader
        self.transform = transform
        extensions = extensions or _IMG_EXTS
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fn in sorted(files):
                path = os.path.join(dirpath, fn)
                ok = (is_valid_file(path) if is_valid_file
                      else fn.lower().endswith(extensions))
                if ok:
                    self.samples.append(path)
        if not self.samples:
            raise RuntimeError(f"no valid files found under {root}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """datasets/flowers.py parity: 102flowers.tgz + imagelabels.mat +
    setid.mat (the reference's cached-download triple)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        base = os.path.expanduser("~/.cache/paddle/dataset/flowers/")
        data_file = data_file or base + "102flowers.tgz"
        label_file = label_file or base + "imagelabels.mat"
        setid_file = setid_file or base + "setid.mat"
        for p, n in [(data_file, "Flowers"), (label_file, "Flowers labels"),
                     (setid_file, "Flowers setid")]:
            if not os.path.exists(p):
                raise RuntimeError(_NO_EGRESS.format(name=n, path=p))
        import scipy.io as sio

        labels = sio.loadmat(label_file)["labels"].reshape(-1)
        setid = sio.loadmat(setid_file)
        key = {"train": "trnid", "valid": "valid", "test": "tstid"}[mode]
        self._ids = setid[key].reshape(-1)
        self._labels = labels
        self._tar = data_file
        self.transform = transform
        # index tar members once
        with tarfile.open(data_file) as tf:
            self._names = {os.path.basename(m.name): m.name
                           for m in tf.getmembers() if m.name.endswith(".jpg")}

    def __getitem__(self, idx):
        from PIL import Image
        import io as _io

        img_id = int(self._ids[idx])
        name = self._names[f"image_{img_id:05d}.jpg"]
        with tarfile.open(self._tar) as tf:
            data = tf.extractfile(name).read()
        img = np.asarray(Image.open(_io.BytesIO(data)).convert("RGB"))
        label = int(self._labels[img_id - 1])
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([label])

    def __len__(self):
        return len(self._ids)


class VOC2012(Dataset):
    """datasets/voc2012.py parity: VOCtrainval_11-May-2012.tar with
    JPEGImages + SegmentationClass + ImageSets/Segmentation splits."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        data_file = data_file or os.path.expanduser(
            "~/.cache/paddle/dataset/voc2012/VOCtrainval_11-May-2012.tar")
        if not os.path.exists(data_file):
            raise RuntimeError(_NO_EGRESS.format(name="VOC2012",
                                                 path=data_file))
        self._tar = data_file
        self.transform = transform
        split = {"train": "train", "valid": "val", "test": "val",
                 "trainval": "trainval"}[mode]
        with tarfile.open(data_file) as tf:
            prefix = None
            for m in tf.getmembers():
                if m.name.endswith(
                        f"ImageSets/Segmentation/{split}.txt"):
                    prefix = m.name.rsplit("ImageSets/", 1)[0]
                    ids = tf.extractfile(m).read().decode().split()
                    break
            else:
                raise RuntimeError("VOC2012: split list not found in tar")
        self._prefix = prefix
        self._ids = ids

    def __getitem__(self, idx):
        from PIL import Image
        import io as _io

        name = self._ids[idx]
        with tarfile.open(self._tar) as tf:
            img = np.asarray(Image.open(_io.BytesIO(tf.extractfile(
                self._prefix + f"JPEGImages/{name}.jpg").read()))
                .convert("RGB"))
            lbl = np.asarray(Image.open(_io.BytesIO(tf.extractfile(
                self._prefix + f"SegmentationClass/{name}.png").read())))
        if self.transform is not None:
            img = self.transform(img)
        return img, lbl

    def __len__(self):
        return len(self._ids)
