"""Vision datasets.

Reference parity: python/paddle/vision/datasets/ (MNIST, FashionMNIST,
Cifar10/100, Flowers, VOC2012, DatasetFolder, ImageFolder). The TPU image
has no egress, so ``download=True`` with missing files raises with
instructions instead of fetching; all loaders accept pre-downloaded files
via the same paths/formats the reference uses.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Callable, List, Optional

import numpy as np

from ..io.dataset import Dataset

_NO_EGRESS = ("{name}: data file not found at {path} and this environment "
              "has no network egress; place the standard {name} files there "
              "(same format as the reference's cached download) or pass "
              "the path explicitly")


class MNIST(Dataset):
    """datasets/mnist.py parity: idx-ubyte files."""

    NAME = "mnist"
    _IMG = {"train": "train-images-idx3-ubyte.gz",
            "test": "t10k-images-idx3-ubyte.gz"}
    _LBL = {"train": "train-labels-idx1-ubyte.gz",
            "test": "t10k-labels-idx1-ubyte.gz"}

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="cv2"):
        mode = mode.lower()
        root = os.path.expanduser(f"~/.cache/paddle/dataset/{self.NAME}")
        self.image_path = image_path or os.path.join(root, self._IMG[mode])
        self.label_path = label_path or os.path.join(root, self._LBL[mode])
        self.transform = transform
        if not os.path.exists(self.image_path):
            raise RuntimeError(_NO_EGRESS.format(name=self.NAME,
                                                 path=self.image_path))
        self.images, self.labels = self._parse()

    def _open(self, path):
        return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")

    def _parse(self):
        with self._open(self.image_path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
        with self._open(self.label_path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
        return images, labels

    def __getitem__(self, idx):
        img = self.images[idx][:, :, None]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """datasets/cifar.py parity: the python-pickle tar."""

    _META = dict(name="cifar-10-python.tar.gz", prefix="cifar-10-batches-py",
                 label_key=b"labels",
                 train=[f"data_batch_{i}" for i in range(1, 6)],
                 test=["test_batch"])

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="cv2"):
        mode = mode.lower()
        root = os.path.expanduser("~/.cache/paddle/dataset/cifar")
        self.data_file = data_file or os.path.join(root, self._META["name"])
        self.transform = transform
        if not os.path.exists(self.data_file):
            raise RuntimeError(_NO_EGRESS.format(name="cifar",
                                                 path=self.data_file))
        names = self._META[mode]
        images, labels = [], []
        with tarfile.open(self.data_file) as tf:
            for n in names:
                with tf.extractfile(f"{self._META['prefix']}/{n}") as f:
                    d = pickle.load(f, encoding="bytes")
                images.append(d[b"data"].reshape(-1, 3, 32, 32))
                labels.extend(d[self._META["label_key"]])
        self.images = np.concatenate(images).transpose(0, 2, 3, 1)  # HWC
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    _META = dict(name="cifar-100-python.tar.gz", prefix="cifar-100-python",
                 label_key=b"fine_labels", train=["train"], test=["test"])


_IMG_EXTS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")


def _default_loader(path):
    if path.endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image

        return np.asarray(Image.open(path).convert("RGB"))
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(f"no image backend available for {path}") from e


class DatasetFolder(Dataset):
    """datasets/folder.py parity: root/class_x/xxx.ext layout."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        self.transform = transform
        extensions = extensions or _IMG_EXTS
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"no class folders under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            for dirpath, _, files in sorted(os.walk(os.path.join(root, c))):
                for fn in sorted(files):
                    path = os.path.join(dirpath, fn)
                    ok = (is_valid_file(path) if is_valid_file
                          else fn.lower().endswith(extensions))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"no valid files found under {root}")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """datasets/folder.py ImageFolder parity: flat dir, no labels."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.loader = loader or _default_loader
        self.transform = transform
        extensions = extensions or _IMG_EXTS
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fn in sorted(files):
                path = os.path.join(dirpath, fn)
                ok = (is_valid_file(path) if is_valid_file
                      else fn.lower().endswith(extensions))
                if ok:
                    self.samples.append(path)
        if not self.samples:
            raise RuntimeError(f"no valid files found under {root}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)
