"""Vision ops (python/paddle/vision/ops.py parity): boxes, NMS (greedy +
matrix), RoI align/pool/PSRoI, anchors (prior_box), box_coder, the YOLOv3
pair (yolo_box/yolo_loss), RPN generate_proposals, FPN distribution,
deformable conv, and host-side image IO."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.layer import Layer
from ..ops.registry import apply
from ..tensor_class import Tensor, unwrap, wrap


def box_area(boxes):
    b = unwrap(boxes)
    return wrap((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]))


def box_iou(boxes1, boxes2):
    """IoU matrix [N, M] for xyxy boxes."""

    def fn(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter)

    return apply("box_iou", fn, boxes1, boxes2, differentiable=False)


def nms(boxes, iou_threshold: float = 0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """vision/ops.py nms parity. Greedy NMS; returns kept indices sorted by
    score. Runs on host (data-dependent output size cannot live under jit —
    the reference's GPU kernel has the same host-sync property at its
    boundary)."""
    b = np.asarray(unwrap(boxes))
    s = (np.asarray(unwrap(scores)) if scores is not None
         else np.arange(len(b), 0, -1, dtype=np.float32))
    if category_idxs is not None:
        cat = np.asarray(unwrap(category_idxs))
        # class-aware: offset boxes per category so cross-class boxes never
        # suppress each other (standard batched-NMS trick)
        offset = (cat.astype(np.float32) * (b.max() + 1.0))[:, None]
        b = b + offset
    order = np.argsort(-s)
    keep = []
    while order.size:
        i = order[0]
        keep.append(int(i))
        if order.size == 1:
            break
        rest = order[1:]
        lt = np.maximum(b[i, :2], b[rest, :2])
        rb = np.minimum(b[i, 2:], b[rest, 2:])
        wh = np.clip(rb - lt, 0, None)
        inter = wh[:, 0] * wh[:, 1]
        area_i = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
        area_r = (b[rest, 2] - b[rest, 0]) * (b[rest, 3] - b[rest, 1])
        iou = inter / (area_i + area_r - inter)
        order = rest[iou <= iou_threshold]
    if top_k is not None:
        keep = keep[:top_k]
    import paddle_tpu as paddle

    return paddle.to_tensor(np.asarray(keep, np.int64))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """vision/ops.py roi_align parity (bilinear-sampled RoI pooling).

    x: [N, C, H, W]; boxes: [R, 4] xyxy in input coords; boxes_num: [N]
    rois per image. Static output [R, C, oh, ow] — jit-friendly.
    """
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def fn(x, boxes, boxes_num):
        n, c, h, w = x.shape
        r = boxes.shape[0]
        # image index per roi from boxes_num
        img_idx = jnp.repeat(jnp.arange(n), boxes_num, total_repeat_length=r)
        off = 0.5 if aligned else 0.0
        x1 = boxes[:, 0] * spatial_scale - off
        y1 = boxes[:, 1] * spatial_scale - off
        x2 = boxes[:, 2] * spatial_scale - off
        y2 = boxes[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
        sr = sampling_ratio if sampling_ratio > 0 else 2
        # sample grid: [R, oh*sr] y coords, [R, ow*sr] x coords
        ys = (y1[:, None] + (jnp.arange(oh * sr) + 0.5)[None, :] *
              (rh / (oh * sr))[:, None])
        xs = (x1[:, None] + (jnp.arange(ow * sr) + 0.5)[None, :] *
              (rw / (ow * sr))[:, None])

        y0 = jnp.clip(jnp.floor(ys), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
        wy = jnp.clip(ys - y0, 0, 1)
        wx = jnp.clip(xs - x0, 0, 1)
        y0 = y0.astype(jnp.int32)
        x0 = x0.astype(jnp.int32)

        feat = x[img_idx]  # [R, C, H, W]

        def gather(yi, xi):
            # feat[r, :, yi[r, a], xi[r, b]] → [R, C, A, B]
            g = jax.vmap(lambda f, yy, xx: f[:, yy][:, :, xx])(feat, yi, xi)
            return g

        v00 = gather(y0, x0)
        v01 = gather(y0, x1i)
        v10 = gather(y1i, x0)
        v11 = gather(y1i, x1i)
        wy_ = wy[:, None, :, None]
        wx_ = wx[:, None, None, :]
        val = (v00 * (1 - wy_) * (1 - wx_) + v01 * (1 - wy_) * wx_
               + v10 * wy_ * (1 - wx_) + v11 * wy_ * wx_)  # [R, C, oh*sr, ow*sr]
        val = val.reshape(r, c, oh, sr, ow, sr).mean(axis=(3, 5))
        return val

    return apply("roi_align", fn, x, boxes, boxes_num)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Max-pool RoI variant: implemented as roi_align with dense sampling
    then max — parity of semantics, TPU-friendly static shapes."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    return roi_align(x, boxes, boxes_num, output_size, spatial_scale,
                     sampling_ratio=2, aligned=False)


class RoIAlign(Layer):
    """vision.ops.RoIAlign layer over roi_align."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        o, s = self._args
        return roi_align(x, boxes, boxes_num, o, s)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        o, s = self._args
        return roi_pool(x, boxes, boxes_num, o, s)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """vision.ops.psroi_pool (ops.yaml `psroi_pool`): position-sensitive ROI
    pooling — output channel (c, i, j) averages input channel
    c*k*k + i*k + j over the (i, j) bin."""
    import numpy as np

    k = output_size if isinstance(output_size, int) else output_size[0]
    a = unwrap(x)
    bx = np.asarray(unwrap(boxes))
    # boxes_num assigns each box to its batch image
    bn = np.asarray(unwrap(boxes_num)).reshape(-1)
    img_of = np.repeat(np.arange(bn.size), bn)
    C = a.shape[1]
    out_c = C // (k * k)
    outs = []
    for b in range(bx.shape[0]):
        img = int(img_of[b]) if b < img_of.size else 0
        x1, y1, x2, y2 = [float(v) * spatial_scale for v in bx[b]]
        bin_h = max(y2 - y1, 0.1) / k
        bin_w = max(x2 - x1, 0.1) / k
        grid = jnp.zeros((out_c, k, k), a.dtype)
        for i in range(k):
            for j in range(k):
                ys = int(np.floor(y1 + i * bin_h))
                ye = max(int(np.ceil(y1 + (i + 1) * bin_h)), ys + 1)
                xs = int(np.floor(x1 + j * bin_w))
                xe = max(int(np.ceil(x1 + (j + 1) * bin_w)), xs + 1)
                ys, ye = np.clip([ys, ye], 0, a.shape[2])
                xs, xe = np.clip([xs, xe], 0, a.shape[3])
                if ye <= ys or xe <= xs:
                    continue
                chans = jnp.arange(out_c) * k * k + i * k + j
                region = a[img, chans, ys:ye, xs:xe]
                grid = grid.at[:, i, j].set(region.mean((-2, -1)))
        outs.append(grid)
    return wrap(jnp.stack(outs))


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        o, s = self._args
        return psroi_pool(x, boxes, boxes_num, o, s)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """vision.ops.prior_box (ops.yaml `prior_box`): SSD anchor generation."""
    import numpy as np

    fh, fw = unwrap(input).shape[2:]
    ih, iw = unwrap(image).shape[2:]
    step_h = steps[1] or ih / fh
    step_w = steps[0] or iw / fw
    ars = list(aspect_ratios)
    if flip:
        ars += [1.0 / a for a in aspect_ratios if a != 1.0]
    boxes = []
    for y in range(fh):
        for x in range(fw):
            cx = (x + offset) * step_w
            cy = (y + offset) * step_h
            cell = []
            for s_i, ms in enumerate(min_sizes):
                cell.append((cx, cy, ms, ms))
                ar_boxes = [(cx, cy, ms * math.sqrt(a), ms / math.sqrt(a))
                            for a in ars if abs(a - 1.0) > 1e-6]
                max_box = []
                if max_sizes:
                    big = math.sqrt(ms * max_sizes[s_i])
                    max_box = [(cx, cy, big, big)]
                # default order: [min, ARs..., max]; flag flips to
                # [min, max, ARs...] (reference min_max_aspect_ratios_order)
                if min_max_aspect_ratios_order:
                    cell.extend(max_box + ar_boxes)
                else:
                    cell.extend(ar_boxes + max_box)
            boxes.extend(cell)
    n_priors = len(boxes) // (fh * fw)
    arr = np.asarray(boxes, np.float32).reshape(fh, fw, n_priors, 4)
    out = np.stack([
        (arr[..., 0] - arr[..., 2] / 2) / iw,
        (arr[..., 1] - arr[..., 3] / 2) / ih,
        (arr[..., 0] + arr[..., 2] / 2) / iw,
        (arr[..., 1] + arr[..., 3] / 2) / ih], -1)
    if clip:
        out = out.clip(0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32), out.shape).copy()
    return wrap(jnp.asarray(out)), wrap(jnp.asarray(var))


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    """vision.ops.box_coder (ops.yaml `box_coder`): encode targets against
    priors or decode deltas back to boxes."""
    def fn(pb, pbv, tb):
        norm = 0.0 if box_normalized else 1.0
        pw = pb[..., 2] - pb[..., 0] + norm
        ph = pb[..., 3] - pb[..., 1] + norm
        pcx = pb[..., 0] + pw / 2
        pcy = pb[..., 1] + ph / 2
        if code_type in ("encode_center_size", "encode"):
            tw = tb[..., 2] - tb[..., 0] + norm
            th = tb[..., 3] - tb[..., 1] + norm
            tcx = tb[..., 0] + tw / 2
            tcy = tb[..., 1] + th / 2
            dx = (tcx[:, None] - pcx[None]) / pw[None]
            dy = (tcy[:, None] - pcy[None]) / ph[None]
            dw = jnp.log(tw[:, None] / pw[None])
            dh = jnp.log(th[:, None] / ph[None])
            out = jnp.stack([dx, dy, dw, dh], -1)
            return out / pbv[None] if pbv is not None else out
        # decode_center_size: tb [N, M, 4] deltas
        if axis == 0:
            pw_, ph_, pcx_, pcy_ = (pw[None], ph[None], pcx[None], pcy[None])
            pbv_ = pbv[None] if pbv is not None else None
        else:
            pw_, ph_, pcx_, pcy_ = (pw[:, None], ph[:, None], pcx[:, None],
                                    pcy[:, None])
            pbv_ = pbv[:, None] if pbv is not None else None
        d = tb * pbv_ if pbv_ is not None else tb
        cx = d[..., 0] * pw_ + pcx_
        cy = d[..., 1] * ph_ + pcy_
        w = jnp.exp(d[..., 2]) * pw_
        h = jnp.exp(d[..., 3]) * ph_
        return jnp.stack([cx - w / 2, cy - h / 2,
                          cx + w / 2 - norm, cy + h / 2 - norm], -1)

    from ..ops.registry import apply

    if prior_box_var is None:
        return apply("box_coder",
                     lambda pb, tb: fn(pb, None, tb), prior_box, target_box)
    return apply("box_coder", fn, prior_box, prior_box_var, target_box)


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """vision.ops.yolo_box (ops.yaml `yolo_box`): decode a YOLOv3 head into
    boxes + per-class scores."""
    def fn(a, imsz):
        n, _, h, w = a.shape
        na = len(anchors) // 2
        a5 = a.reshape(n, na, 5 + class_num, h, w)
        gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
        sig = jax.nn.sigmoid
        bx = (gx + sig(a5[:, :, 0]) * scale_x_y
              - (scale_x_y - 1) / 2) / w
        by = (gy + sig(a5[:, :, 1]) * scale_x_y
              - (scale_x_y - 1) / 2) / h
        aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
        ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]
        input_w = w * downsample_ratio
        input_h = h * downsample_ratio
        bw = jnp.exp(a5[:, :, 2]) * aw / input_w
        bh = jnp.exp(a5[:, :, 3]) * ah / input_h
        conf = sig(a5[:, :, 4])
        probs = sig(a5[:, :, 5:]) * conf[:, :, None]
        imh = imsz[:, 0].astype(jnp.float32)[:, None, None, None]
        imw = imsz[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * imw
        y1 = (by - bh / 2) * imh
        x2 = (bx + bw / 2) * imw
        y2 = (by + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(n, -1, 4)
        keep = (conf > conf_thresh).astype(boxes.dtype)
        boxes = boxes * keep.reshape(n, -1)[..., None]
        scores = (probs * keep[:, :, None]).transpose(0, 1, 3, 4, 2)
        return boxes, scores.reshape(n, -1, class_num)

    from ..ops.registry import apply

    return apply("yolo_box", fn, x, img_size)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """vision.ops.yolo_loss (ops.yaml `yolov3_loss`): YOLOv3 training loss
    (coordinate + objectness + classification terms, best-anchor matching,
    ignore mask from IoU against any gt)."""
    import numpy as np

    a = unwrap(x)
    boxes = np.asarray(unwrap(gt_box))      # [N, B, 4] cx,cy,w,h normalized
    labels = np.asarray(unwrap(gt_label))   # [N, B]
    n, _, h, w = a.shape
    na = len(anchor_mask)
    a5 = a.reshape(n, na, 5 + class_num, h, w)
    input_size = downsample_ratio * h
    all_anchors = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask_anchors = all_anchors[np.asarray(anchor_mask)]

    # build targets host-side (data-dependent matching like the reference)
    tobj = np.zeros((n, na, h, w), np.float32)
    tcoord = np.zeros((n, na, 4, h, w), np.float32)
    tcls = np.zeros((n, na, class_num, h, w), np.float32)
    coord_w = np.zeros((n, na, h, w), np.float32)
    # ignore mask: predicted boxes with IoU > ignore_thresh vs ANY gt are
    # excluded from the no-object term (reference yolov3_loss semantics)
    av_np = np.asarray(a).reshape(n, na, 5 + class_num, h, w)
    noobj_mask = np.ones((n, na, h, w), np.float32)
    sig_np = lambda z: 1.0 / (1.0 + np.exp(-z))
    gx_grid = np.arange(w, dtype=np.float32)[None, None, :]
    gy_grid = np.arange(h, dtype=np.float32)[None, :, None]
    for b in range(n):
        gts = [g for g in range(boxes.shape[1])
               if boxes[b, g, 2] > 0 and boxes[b, g, 3] > 0]
        if gts:
            px = (gx_grid + sig_np(av_np[b, :, 0])) / w
            py = (gy_grid + sig_np(av_np[b, :, 1])) / h
            pw_ = np.exp(np.clip(av_np[b, :, 2], None, 10)) \
                * mask_anchors[:, 0, None, None] / input_size
            ph_ = np.exp(np.clip(av_np[b, :, 3], None, 10)) \
                * mask_anchors[:, 1, None, None] / input_size
            best_iou = np.zeros((na, h, w), np.float32)
            for g in gts:
                gx0 = boxes[b, g, 0] - boxes[b, g, 2] / 2
                gy0 = boxes[b, g, 1] - boxes[b, g, 3] / 2
                gx1 = boxes[b, g, 0] + boxes[b, g, 2] / 2
                gy1 = boxes[b, g, 1] + boxes[b, g, 3] / 2
                ix0 = np.maximum(px - pw_ / 2, gx0)
                iy0 = np.maximum(py - ph_ / 2, gy0)
                ix1 = np.minimum(px + pw_ / 2, gx1)
                iy1 = np.minimum(py + ph_ / 2, gy1)
                inter = (np.clip(ix1 - ix0, 0, None)
                         * np.clip(iy1 - iy0, 0, None))
                union = (pw_ * ph_ + boxes[b, g, 2] * boxes[b, g, 3]
                         - inter)
                best_iou = np.maximum(best_iou,
                                      inter / np.maximum(union, 1e-10))
            noobj_mask[b][best_iou > ignore_thresh] = 0.0
    for b in range(n):
        for g in range(boxes.shape[1]):
            bw = boxes[b, g, 2] * input_size
            bh = boxes[b, g, 3] * input_size
            if bw <= 0 or bh <= 0:
                continue
            # best anchor by IoU at origin
            inter = np.minimum(bw, all_anchors[:, 0]) * np.minimum(
                bh, all_anchors[:, 1])
            union = bw * bh + all_anchors.prod(-1) - inter
            best = int((inter / union).argmax())
            if best not in anchor_mask:
                continue
            k = anchor_mask.index(best)
            gi = min(int(boxes[b, g, 0] * w), w - 1)
            gj = min(int(boxes[b, g, 1] * h), h - 1)
            tobj[b, k, gj, gi] = 1.0
            tcoord[b, k, 0, gj, gi] = boxes[b, g, 0] * w - gi
            tcoord[b, k, 1, gj, gi] = boxes[b, g, 1] * h - gj
            tcoord[b, k, 2, gj, gi] = np.log(
                max(bw / mask_anchors[k, 0], 1e-9))
            tcoord[b, k, 3, gj, gi] = np.log(
                max(bh / mask_anchors[k, 1], 1e-9))
            coord_w[b, k, gj, gi] = 2.0 - boxes[b, g, 2] * boxes[b, g, 3]
            c = int(labels[b, g])
            smooth = 1.0 / class_num if use_label_smooth else 0.0
            tcls[b, k, :, gj, gi] = smooth
            tcls[b, k, c, gj, gi] = 1.0 - smooth if use_label_smooth else 1.0

    def fn(av):
        a5v = av.reshape(n, na, 5 + class_num, h, w)
        sig = jax.nn.sigmoid
        to = jnp.asarray(tobj)
        tc = jnp.asarray(tcoord)
        tk = jnp.asarray(tcls)
        cw = jnp.asarray(coord_w)
        bce = lambda z, t: jnp.maximum(z, 0) - z * t + jnp.log1p(
            jnp.exp(-jnp.abs(z)))
        loss_xy = (bce(a5v[:, :, 0], tc[:, :, 0]) * to * cw
                   + bce(a5v[:, :, 1], tc[:, :, 1]) * to * cw)
        loss_wh = ((a5v[:, :, 2] - tc[:, :, 2]) ** 2 * to * cw * 0.5
                   + (a5v[:, :, 3] - tc[:, :, 3]) ** 2 * to * cw * 0.5)
        loss_obj = bce(a5v[:, :, 4], to) * to
        # negatives: only where no gt is placed AND not ignored (IoU below
        # ignore_thresh against every gt)
        nm = jnp.asarray(noobj_mask)
        loss_noobj = bce(a5v[:, :, 4], to) * (1.0 - to) * nm
        loss_cls = (bce(a5v[:, :, 5:], tk) * to[:, :, None]).sum(2)
        total = (loss_xy + loss_wh + loss_obj + loss_noobj
                 + loss_cls).sum((1, 2, 3))
        return total

    from ..ops.registry import apply

    return apply("yolo_loss", fn, x)


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """vision.ops.matrix_nms (ops.yaml `matrix_nms`): parallel soft-NMS via
    the pairwise-IoU decay matrix (SOLOv2) — one [K, K] matrix instead of a
    sequential suppression loop (TPU-friendly)."""
    import numpy as np

    bx = np.asarray(unwrap(bboxes))    # [N, M, 4]
    sc = np.asarray(unwrap(scores))    # [N, C, M]
    outs, indices, nums = [], [], []
    for b in range(bx.shape[0]):
        dets = []
        idxs = []
        for c in range(sc.shape[1]):
            if c == background_label:
                continue
            s = sc[b, c]
            keep = np.where(s > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-s[keep])][:nms_top_k]
            boxes_c = bx[b, order]
            s_c = s[order]
            # pairwise IoU (upper triangle)
            x1 = np.maximum(boxes_c[:, None, 0], boxes_c[None, :, 0])
            y1 = np.maximum(boxes_c[:, None, 1], boxes_c[None, :, 1])
            x2 = np.minimum(boxes_c[:, None, 2], boxes_c[None, :, 2])
            y2 = np.minimum(boxes_c[:, None, 3], boxes_c[None, :, 3])
            inter = np.clip(x2 - x1, 0, None) * np.clip(y2 - y1, 0, None)
            area = ((boxes_c[:, 2] - boxes_c[:, 0])
                    * (boxes_c[:, 3] - boxes_c[:, 1]))
            iou = inter / np.maximum(area[:, None] + area[None] - inter,
                                     1e-10)
            iou = np.triu(iou, 1)
            iou_cmax = iou.max(0)
            if use_gaussian:
                decay = np.exp((iou_cmax[:, None]**2 - iou**2)
                               / gaussian_sigma)
            else:
                # SOLOv2 decay: suppression by i is discounted by how much
                # i itself was suppressed (iou_cmax of the ROW)
                decay = (1 - iou) / np.maximum(1 - iou_cmax, 1e-10)[:, None]
            decay = decay.min(0)
            s_dec = s_c * decay
            ok = s_dec >= post_threshold
            for i in np.where(ok)[0]:
                dets.append([c, s_dec[i], *boxes_c[i]])
                idxs.append(order[i])
        dets = np.asarray(dets, np.float32).reshape(-1, 6)
        top = np.argsort(-dets[:, 1])[:keep_top_k]
        outs.append(dets[top])
        indices.append(np.asarray(idxs)[top] if top.size else
                       np.empty(0, np.int64))
        nums.append(top.size)
    out = wrap(jnp.asarray(np.concatenate(outs) if outs
                           else np.empty((0, 6), np.float32)))
    rois_num = wrap(jnp.asarray(np.asarray(nums, np.int32)))
    if return_index:
        idx = wrap(jnp.asarray(np.concatenate(indices).astype(np.int64)))
        return (out, idx, rois_num) if return_rois_num else (out, idx)
    return (out, rois_num) if return_rois_num else out


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """vision.ops.distribute_fpn_proposals (ops.yaml): assign each RoI to an
    FPN level by sqrt-area heuristic."""
    import numpy as np

    rois = np.asarray(unwrap(fpn_rois))
    off = 1.0 if pixel_offset else 0.0
    scale = np.sqrt(np.clip((rois[:, 2] - rois[:, 0] + off)
                            * (rois[:, 3] - rois[:, 1] + off), 0, None))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    # image attribution of each roi (rois_num = per-image counts)
    if rois_num is not None:
        rn = np.asarray(unwrap(rois_num)).reshape(-1)
        img_of = np.repeat(np.arange(rn.size), rn)
        n_img = rn.size
    else:
        img_of = np.zeros(rois.shape[0], np.int64)
        n_img = 1
    outs, idx_restore = [], np.empty(rois.shape[0], np.int64)
    nums = []
    order = []
    for level in range(min_level, max_level + 1):
        sel = np.where(lvl == level)[0]
        # keep rois grouped by image within the level (reference layout)
        sel = sel[np.argsort(img_of[sel], kind="stable")]
        outs.append(wrap(jnp.asarray(rois[sel])))
        per_img = np.bincount(img_of[sel], minlength=n_img).astype(np.int32)
        nums.append(wrap(jnp.asarray(per_img)))
        order.extend(sel.tolist())
    for new_i, old_i in enumerate(order):
        idx_restore[old_i] = new_i
    return outs, wrap(jnp.asarray(idx_restore)), nums


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False, name=None):
    """vision.ops.generate_proposals (ops.yaml `generate_proposals`): RPN
    box decoding + clip + min-size filter + NMS."""
    import numpy as np

    sc = np.asarray(unwrap(scores))          # [N, A, H, W]
    bd = np.asarray(unwrap(bbox_deltas))     # [N, A*4, H, W]
    ims = np.asarray(unwrap(img_size))       # [N, 2]
    an = np.asarray(unwrap(anchors)).reshape(-1, 4)   # [H*W*A, 4]
    va = np.asarray(unwrap(variances)).reshape(-1, 4)
    n = sc.shape[0]
    outs, out_scores, nums = [], [], []
    for b in range(n):
        s = sc[b].transpose(1, 2, 0).reshape(-1)
        d = bd[b].reshape(-1, 4, sc.shape[2], sc.shape[3]) \
            .transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s_o, d_o, an_o, va_o = s[order], d[order], an[order], va[order]
        off = 1.0 if pixel_offset else 0.0
        aw = an_o[:, 2] - an_o[:, 0] + off
        ah = an_o[:, 3] - an_o[:, 1] + off
        acx = an_o[:, 0] + aw / 2
        acy = an_o[:, 1] + ah / 2
        cx = va_o[:, 0] * d_o[:, 0] * aw + acx
        cy = va_o[:, 1] * d_o[:, 1] * ah + acy
        wbox = np.exp(np.clip(va_o[:, 2] * d_o[:, 2], None, 10)) * aw
        hbox = np.exp(np.clip(va_o[:, 3] * d_o[:, 3], None, 10)) * ah
        x1 = np.clip(cx - wbox / 2, 0, ims[b, 1] - 1)
        y1 = np.clip(cy - hbox / 2, 0, ims[b, 0] - 1)
        x2 = np.clip(cx + wbox / 2, 0, ims[b, 1] - 1)
        y2 = np.clip(cy + hbox / 2, 0, ims[b, 0] - 1)
        keep = np.where((x2 - x1 >= min_size) & (y2 - y1 >= min_size))[0]
        props = np.stack([x1, y1, x2, y2], -1)[keep]
        s_k = s_o[keep]
        # greedy NMS
        order2 = np.argsort(-s_k)
        chosen = []
        while order2.size and len(chosen) < post_nms_top_n:
            i = order2[0]
            chosen.append(i)
            xx1 = np.maximum(props[i, 0], props[order2[1:], 0])
            yy1 = np.maximum(props[i, 1], props[order2[1:], 1])
            xx2 = np.minimum(props[i, 2], props[order2[1:], 2])
            yy2 = np.minimum(props[i, 3], props[order2[1:], 3])
            inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
            a_i = (props[i, 2] - props[i, 0]) * (props[i, 3] - props[i, 1])
            a_r = ((props[order2[1:], 2] - props[order2[1:], 0])
                   * (props[order2[1:], 3] - props[order2[1:], 1]))
            iou = inter / np.maximum(a_i + a_r - inter, 1e-10)
            order2 = order2[1:][iou <= nms_thresh]
        outs.append(props[chosen])
        out_scores.append(s_k[chosen])
        nums.append(len(chosen))
    rois = wrap(jnp.asarray(np.concatenate(outs).astype(np.float32)))
    rscores = wrap(jnp.asarray(np.concatenate(out_scores).astype(np.float32)))
    rnum = wrap(jnp.asarray(np.asarray(nums, np.int32)))
    if return_rois_num:
        return rois, rscores, rnum
    return rois, rscores


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """vision.ops.deform_conv2d (ops.yaml `deformable_conv`): deformable
    convolution v1/v2 — bilinear sampling at offset positions then a dense
    matmul over the gathered patches (gather + MXU, no scatter)."""
    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    sh, sw = pair(stride)
    ph, pw = pair(padding)
    dh, dw = pair(dilation)

    def fn(a, off, wgt, *rest):
        msk = rest[0] if (mask is not None and len(rest) > 0) else None
        bia = None
        if bias is not None:
            bia = rest[-1]
        n, cin, h, w = a.shape
        cout, cin_g, kh, kw = wgt.shape
        oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        ow = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        # base sampling grid [oh, ow, kh, kw]
        by = (jnp.arange(oh) * sh - ph)[:, None, None, None] \
            + (jnp.arange(kh) * dh)[None, None, :, None]
        bx = (jnp.arange(ow) * sw - pw)[None, :, None, None] \
            + (jnp.arange(kw) * dw)[None, None, None, :]
        off = off.reshape(n, deformable_groups, kh * kw, 2, oh, ow)
        oy = off[:, :, :, 0].transpose(0, 1, 3, 4, 2).reshape(
            n, deformable_groups, oh, ow, kh, kw)
        ox = off[:, :, :, 1].transpose(0, 1, 3, 4, 2).reshape(
            n, deformable_groups, oh, ow, kh, kw)
        sy = by[None, None] + oy
        sx = bx[None, None] + ox

        def bilinear(img, yy, xx):
            # img [C, H, W]; yy/xx [oh, ow, kh, kw]
            y0 = jnp.floor(yy)
            x0 = jnp.floor(xx)
            wy = yy - y0
            wx = xx - x0

            def at(yi, xi):
                inside = ((yi >= 0) & (yi < h) & (xi >= 0) & (xi < w))
                yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
                xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
                return img[:, yc, xc] * inside.astype(img.dtype)[None]

            return (at(y0, x0) * ((1 - wy) * (1 - wx))[None]
                    + at(y0, x0 + 1) * ((1 - wy) * wx)[None]
                    + at(y0 + 1, x0) * (wy * (1 - wx))[None]
                    + at(y0 + 1, x0 + 1) * (wy * wx)[None])

        cpg = cin // deformable_groups
        outs = []
        for b in range(n):
            groups_samples = []
            for g in range(deformable_groups):
                img = a[b, g * cpg:(g + 1) * cpg]
                patch = bilinear(img, sy[b, g], sx[b, g])
                if msk is not None:
                    m = msk[b].reshape(deformable_groups, kh * kw, oh, ow)
                    m = m[g].transpose(1, 2, 0).reshape(oh, ow, kh, kw)
                    patch = patch * m[None]
                groups_samples.append(patch)
            patches = jnp.concatenate(groups_samples, 0)  # [cin, oh, ow, kh, kw]
            col = patches.transpose(1, 2, 0, 3, 4).reshape(
                oh * ow, cin * kh * kw)
            wcol = wgt.reshape(cout, cin_g * kh * kw)
            if groups == 1:
                res = col @ wcol.T
            else:
                cols = col.reshape(oh * ow, groups, (cin // groups) * kh * kw)
                wg = wcol.reshape(groups, cout // groups, -1)
                res = jnp.concatenate(
                    [cols[:, g] @ wg[g].T for g in range(groups)], -1)
            outs.append(res.T.reshape(cout, oh, ow))
        out = jnp.stack(outs)
        if bia is not None:
            out = out + bia[None, :, None, None]
        return out

    from ..ops.registry import apply

    args = [x, offset, weight]
    if mask is not None:
        args.append(mask)
    if bias is not None:
        args.append(bias)
    return apply("deform_conv2d", fn, *args)


class DeformConv2D(Layer):
    """vision.ops.DeformConv2D layer."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        k = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        from ..nn.initializer_core import Uniform

        bound = 1.0 / math.sqrt(in_channels * k[0] * k[1])
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, k[0], k[1]],
            attr=weight_attr, default_initializer=Uniform(-bound, bound))
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True,
            default_initializer=Uniform(-bound, bound))
        self._args = (stride, padding, dilation, deformable_groups, groups)

    def forward(self, x, offset, mask=None):
        s, p, d, dg, g = self._args
        return deform_conv2d(x, offset, self.weight, self.bias, s, p, d, dg,
                             g, mask)


def read_file(filename, name=None):
    """vision.ops.read_file: file bytes as a uint8 tensor."""
    import numpy as np

    with open(filename, "rb") as f:
        data = f.read()
    return wrap(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """vision.ops.decode_jpeg via PIL (the reference uses nvjpeg on GPU;
    image IO is host-side on TPU by design)."""
    import io

    import numpy as np
    from PIL import Image

    data = bytes(np.asarray(unwrap(x)).astype(np.uint8))
    img = Image.open(io.BytesIO(data))
    if mode == "gray":
        img = img.convert("L")
    elif mode in ("rgb", "RGB"):
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return wrap(jnp.asarray(arr))
