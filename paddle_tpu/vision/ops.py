"""Vision ops: boxes, NMS, RoI align.

Reference parity: python/paddle/vision/ops.py (nms, box_coder, roi_align,
roi_pool, deform_conv2d, PSRoIPool, yolo ops). The TPU build implements the
detection primitives used by the model zoo; deform_conv/yolo remain gaps
(tracked for a later round).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.registry import apply
from ..tensor_class import Tensor, unwrap, wrap


def box_area(boxes):
    b = unwrap(boxes)
    return wrap((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]))


def box_iou(boxes1, boxes2):
    """IoU matrix [N, M] for xyxy boxes."""

    def fn(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter)

    return apply("box_iou", fn, boxes1, boxes2, differentiable=False)


def nms(boxes, iou_threshold: float = 0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """vision/ops.py nms parity. Greedy NMS; returns kept indices sorted by
    score. Runs on host (data-dependent output size cannot live under jit —
    the reference's GPU kernel has the same host-sync property at its
    boundary)."""
    b = np.asarray(unwrap(boxes))
    s = (np.asarray(unwrap(scores)) if scores is not None
         else np.arange(len(b), 0, -1, dtype=np.float32))
    if category_idxs is not None:
        cat = np.asarray(unwrap(category_idxs))
        # class-aware: offset boxes per category so cross-class boxes never
        # suppress each other (standard batched-NMS trick)
        offset = (cat.astype(np.float32) * (b.max() + 1.0))[:, None]
        b = b + offset
    order = np.argsort(-s)
    keep = []
    while order.size:
        i = order[0]
        keep.append(int(i))
        if order.size == 1:
            break
        rest = order[1:]
        lt = np.maximum(b[i, :2], b[rest, :2])
        rb = np.minimum(b[i, 2:], b[rest, 2:])
        wh = np.clip(rb - lt, 0, None)
        inter = wh[:, 0] * wh[:, 1]
        area_i = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
        area_r = (b[rest, 2] - b[rest, 0]) * (b[rest, 3] - b[rest, 1])
        iou = inter / (area_i + area_r - inter)
        order = rest[iou <= iou_threshold]
    if top_k is not None:
        keep = keep[:top_k]
    import paddle_tpu as paddle

    return paddle.to_tensor(np.asarray(keep, np.int64))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """vision/ops.py roi_align parity (bilinear-sampled RoI pooling).

    x: [N, C, H, W]; boxes: [R, 4] xyxy in input coords; boxes_num: [N]
    rois per image. Static output [R, C, oh, ow] — jit-friendly.
    """
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def fn(x, boxes, boxes_num):
        n, c, h, w = x.shape
        r = boxes.shape[0]
        # image index per roi from boxes_num
        img_idx = jnp.repeat(jnp.arange(n), boxes_num, total_repeat_length=r)
        off = 0.5 if aligned else 0.0
        x1 = boxes[:, 0] * spatial_scale - off
        y1 = boxes[:, 1] * spatial_scale - off
        x2 = boxes[:, 2] * spatial_scale - off
        y2 = boxes[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
        sr = sampling_ratio if sampling_ratio > 0 else 2
        # sample grid: [R, oh*sr] y coords, [R, ow*sr] x coords
        ys = (y1[:, None] + (jnp.arange(oh * sr) + 0.5)[None, :] *
              (rh / (oh * sr))[:, None])
        xs = (x1[:, None] + (jnp.arange(ow * sr) + 0.5)[None, :] *
              (rw / (ow * sr))[:, None])

        y0 = jnp.clip(jnp.floor(ys), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
        wy = jnp.clip(ys - y0, 0, 1)
        wx = jnp.clip(xs - x0, 0, 1)
        y0 = y0.astype(jnp.int32)
        x0 = x0.astype(jnp.int32)

        feat = x[img_idx]  # [R, C, H, W]

        def gather(yi, xi):
            # feat[r, :, yi[r, a], xi[r, b]] → [R, C, A, B]
            g = jax.vmap(lambda f, yy, xx: f[:, yy][:, :, xx])(feat, yi, xi)
            return g

        v00 = gather(y0, x0)
        v01 = gather(y0, x1i)
        v10 = gather(y1i, x0)
        v11 = gather(y1i, x1i)
        wy_ = wy[:, None, :, None]
        wx_ = wx[:, None, None, :]
        val = (v00 * (1 - wy_) * (1 - wx_) + v01 * (1 - wy_) * wx_
               + v10 * wy_ * (1 - wx_) + v11 * wy_ * wx_)  # [R, C, oh*sr, ow*sr]
        val = val.reshape(r, c, oh, sr, ow, sr).mean(axis=(3, 5))
        return val

    return apply("roi_align", fn, x, boxes, boxes_num)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Max-pool RoI variant: implemented as roi_align with dense sampling
    then max — parity of semantics, TPU-friendly static shapes."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    return roi_align(x, boxes, boxes_num, output_size, spatial_scale,
                     sampling_ratio=2, aligned=False)
