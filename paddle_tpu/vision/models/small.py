"""LeNet / AlexNet / VGG / MobileNetV1+V2 / SqueezeNet.

Reference parity: python/paddle/vision/models/{lenet,alexnet,vgg,
mobilenetv1,mobilenetv2,squeezenet}.py — same layer graphs and factory
function names.
"""
from __future__ import annotations

from ... import nn


class LeNet(nn.Layer):
    """lenet.py parity (MNIST 1x28x28)."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1), nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0), nn.ReLU(),
            nn.MaxPool2D(2, 2))
        if num_classes > 0:
            self.fc = nn.Sequential(
                nn.Linear(400, 120), nn.Linear(120, 84),
                nn.Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


class AlexNet(nn.Layer):
    """alexnet.py parity."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2))
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5), nn.Linear(256 * 6 * 6, 4096), nn.ReLU(),
                nn.Dropout(0.5), nn.Linear(4096, 4096), nn.ReLU(),
                nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def alexnet(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return AlexNet(**kwargs)


_VGG_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
          512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
          512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


def _vgg_features(cfg, batch_norm):
    layers, in_c = [], 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(2, 2))
        else:
            layers.append(nn.Conv2D(in_c, v, 3, padding=1))
            if batch_norm:
                layers.append(nn.BatchNorm2D(v))
            layers.append(nn.ReLU())
            in_c = v
    return nn.Sequential(*layers)


class VGG(nn.Layer):
    """vgg.py parity."""

    def __init__(self, features, num_classes=1000, with_pool=True):
        super().__init__()
        self.features = features
        self.num_classes = num_classes
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def _no_pretrained(pretrained):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights need a download backend (no egress); use "
            "model.set_state_dict")


def vgg11(pretrained=False, batch_norm=False, **kwargs):
    _no_pretrained(pretrained)
    return VGG(_vgg_features(_VGG_CFGS["A"], batch_norm), **kwargs)


def vgg13(pretrained=False, batch_norm=False, **kwargs):
    _no_pretrained(pretrained)
    return VGG(_vgg_features(_VGG_CFGS["B"], batch_norm), **kwargs)


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    _no_pretrained(pretrained)
    return VGG(_vgg_features(_VGG_CFGS["D"], batch_norm), **kwargs)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    _no_pretrained(pretrained)
    return VGG(_vgg_features(_VGG_CFGS["E"], batch_norm), **kwargs)


class _ConvBNReLU(nn.Sequential):
    def __init__(self, in_c, out_c, kernel, stride=1, groups=1, relu6=False):
        pad = (kernel - 1) // 2
        super().__init__(
            nn.Conv2D(in_c, out_c, kernel, stride=stride, padding=pad,
                      groups=groups, bias_attr=False),
            nn.BatchNorm2D(out_c),
            nn.ReLU6() if relu6 else nn.ReLU())


class MobileNetV1(nn.Layer):
    """mobilenetv1.py parity (depthwise-separable stacks)."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(1, int(ch * scale))

        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2), *[(512, 512, 1)] * 5,
               (512, 1024, 2), (1024, 1024, 1)]
        layers = [_ConvBNReLU(3, c(32), 3, stride=2)]
        for in_c, out_c, stride in cfg:
            layers.append(_ConvBNReLU(c(in_c), c(in_c), 3, stride=stride,
                                      groups=c(in_c)))  # depthwise
            layers.append(_ConvBNReLU(c(in_c), c(out_c), 1))  # pointwise
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


class _InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand_ratio):
        super().__init__()
        hidden = int(round(in_c * expand_ratio))
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNReLU(in_c, hidden, 1, relu6=True))
        layers += [
            _ConvBNReLU(hidden, hidden, 3, stride=stride, groups=hidden,
                        relu6=True),
            nn.Conv2D(hidden, out_c, 1, bias_attr=False),
            nn.BatchNorm2D(out_c)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    """mobilenetv2.py parity."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]

        def c(ch):
            return max(8, int(ch * scale + 4) // 8 * 8)

        in_c = c(32)
        last = c(1280) if scale > 1.0 else 1280
        layers = [_ConvBNReLU(3, in_c, 3, stride=2, relu6=True)]
        for t, ch, n, s in cfg:
            out_c = c(ch)
            for i in range(n):
                layers.append(_InvertedResidual(in_c, out_c,
                                                s if i == 0 else 1, t))
                in_c = out_c
        layers.append(_ConvBNReLU(in_c, last, 1, relu6=True))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool2d_avg = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(last, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    _no_pretrained(pretrained)
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    _no_pretrained(pretrained)
    return MobileNetV2(scale=scale, **kwargs)
