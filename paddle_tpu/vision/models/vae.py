"""AutoencoderKL — the latent-diffusion VAE (the encode/decode half of the
DiT / SD3 pipeline in BASELINE.json; PaddleMIX ppdiffusers AutoencoderKL).

Architecture (SD family): Encoder = conv-in → N down blocks (ResNet blocks
+ strided-conv downsample) → mid (ResNet + single-head attention + ResNet)
→ GroupNorm/SiLU → conv-out to 2·latent channels (mean ‖ logvar);
DiagonalGaussian posterior; Decoder mirrors with nearest-neighbour
upsample + conv. Trains with reconstruction + KL.

TPU-native: everything is static-shape convs/GroupNorm (XLA lowers convs
onto the MXU); the mid-block attention flattens HW into a token axis and
rides the same SDPA path as the transformers, so one ``jit.TrainStep``
compiles the whole autoencoder step.
"""
from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp

from ... import nn
from ...framework import random as _random
from ...nn.layer import Layer
from ...tensor_class import unwrap, wrap


@dataclasses.dataclass
class VAEConfig:
    """Defaults are the SD1.x/DiT 4-channel VAE; use :meth:`sd3` for the
    16-channel SD3 VAE that pairs with ``models.sd3.MMDiTConfig`` defaults
    (``MMDiTConfig.in_channels == 16``)."""

    in_channels: int = 3
    latent_channels: int = 4
    base_channels: int = 128
    channel_mults: Sequence[int] = (1, 2, 4, 4)
    layers_per_block: int = 2
    norm_groups: int = 32
    scaling_factor: float = 0.18215   # SD latent scaling
    shift_factor: float = 0.0         # SD3 shifts latents before scaling

    @staticmethod
    def sd3(**kw):
        """The SD3 pairing: 16 latent channels, z' = (z - shift) * scale."""
        base = dict(latent_channels=16, scaling_factor=1.5305,
                    shift_factor=0.0609)
        base.update(kw)
        return VAEConfig(**base)

    @staticmethod
    def tiny(**kw):
        base = dict(base_channels=16, channel_mults=(1, 2),
                    layers_per_block=1, norm_groups=4, latent_channels=4)
        base.update(kw)
        return VAEConfig(**base)


class _ResnetBlock(Layer):
    def __init__(self, cin, cout, groups):
        super().__init__()
        self.norm1 = nn.GroupNorm(groups, cin, epsilon=1e-6)
        self.conv1 = nn.Conv2D(cin, cout, 3, padding=1)
        self.norm2 = nn.GroupNorm(groups, cout, epsilon=1e-6)
        self.conv2 = nn.Conv2D(cout, cout, 3, padding=1)
        self.skip = nn.Conv2D(cin, cout, 1) if cin != cout else None

    def forward(self, x):
        h = self.conv1(nn.functional.silu(self.norm1(x)))
        h = self.conv2(nn.functional.silu(self.norm2(h)))
        s = self.skip(x) if self.skip is not None else x
        return s + h


class _MidAttention(Layer):
    """Single-head self-attention over the HW grid (SD mid-block)."""

    def __init__(self, channels, groups):
        super().__init__()
        self.norm = nn.GroupNorm(groups, channels, epsilon=1e-6)
        self.q = nn.Conv2D(channels, channels, 1)
        self.k = nn.Conv2D(channels, channels, 1)
        self.v = nn.Conv2D(channels, channels, 1)
        self.proj = nn.Conv2D(channels, channels, 1)

    def forward(self, x):
        h = self.norm(x)
        q, k, v = self.q(h), self.k(h), self.v(h)

        def attend(qa, ka, va):
            b, c, hh, ww = qa.shape
            # [B, HW, 1 head, C] tokens through the shared SDPA path
            def tok(a):
                return a.reshape(b, c, hh * ww).transpose(0, 2, 1)[:, :, None, :]
            out = unwrap(nn.functional.scaled_dot_product_attention(
                wrap(tok(qa)), wrap(tok(ka)), wrap(tok(va)), is_causal=False))
            return out[:, :, 0, :].transpose(0, 2, 1).reshape(b, c, hh, ww)

        o = wrap(attend(unwrap(q), unwrap(k), unwrap(v)))
        return x + self.proj(o)


class Encoder(Layer):
    def __init__(self, cfg: VAEConfig):
        super().__init__()
        ch = cfg.base_channels
        self.conv_in = nn.Conv2D(cfg.in_channels, ch, 3, padding=1)
        blocks, downs = [], []
        cur = ch
        for i, m in enumerate(cfg.channel_mults):
            out = ch * m
            stage = [_ResnetBlock(cur if j == 0 else out, out,
                                  cfg.norm_groups)
                     for j in range(cfg.layers_per_block)]
            blocks.append(nn.LayerList(stage))
            last = i == len(cfg.channel_mults) - 1
            downs.append(None if last
                         else nn.Conv2D(out, out, 3, stride=2, padding=1))
            cur = out
        self.blocks = nn.LayerList(blocks)
        self.downs = nn.LayerList([d for d in downs if d is not None])
        self._down_mask = [d is not None for d in downs]
        self.mid1 = _ResnetBlock(cur, cur, cfg.norm_groups)
        self.mid_attn = _MidAttention(cur, cfg.norm_groups)
        self.mid2 = _ResnetBlock(cur, cur, cfg.norm_groups)
        self.norm_out = nn.GroupNorm(cfg.norm_groups, cur, epsilon=1e-6)
        self.conv_out = nn.Conv2D(cur, 2 * cfg.latent_channels, 3, padding=1)

    def forward(self, x):
        h = self.conv_in(x)
        di = 0
        for stage, has_down in zip(self.blocks, self._down_mask):
            for blk in stage:
                h = blk(h)
            if has_down:
                h = self.downs[di](h)
                di += 1
        h = self.mid2(self.mid_attn(self.mid1(h)))
        return self.conv_out(nn.functional.silu(self.norm_out(h)))


class Decoder(Layer):
    def __init__(self, cfg: VAEConfig):
        super().__init__()
        ch = cfg.base_channels
        mults = list(cfg.channel_mults)
        cur = ch * mults[-1]
        self.conv_in = nn.Conv2D(cfg.latent_channels, cur, 3, padding=1)
        self.mid1 = _ResnetBlock(cur, cur, cfg.norm_groups)
        self.mid_attn = _MidAttention(cur, cfg.norm_groups)
        self.mid2 = _ResnetBlock(cur, cur, cfg.norm_groups)
        blocks, ups = [], []
        for i, m in enumerate(reversed(mults)):
            out = ch * m
            stage = [_ResnetBlock(cur if j == 0 else out, out,
                                  cfg.norm_groups)
                     for j in range(cfg.layers_per_block + 1)]
            blocks.append(nn.LayerList(stage))
            last = i == len(mults) - 1
            ups.append(None if last else nn.Conv2D(out, out, 3, padding=1))
            cur = out
        self.blocks = nn.LayerList(blocks)
        self.ups = nn.LayerList([u for u in ups if u is not None])
        self._up_mask = [u is not None for u in ups]
        self.norm_out = nn.GroupNorm(cfg.norm_groups, cur, epsilon=1e-6)
        self.conv_out = nn.Conv2D(cur, cfg.in_channels, 3, padding=1)

    def forward(self, z):
        h = self.conv_in(z)
        h = self.mid2(self.mid_attn(self.mid1(h)))
        ui = 0
        for stage, has_up in zip(self.blocks, self._up_mask):
            for blk in stage:
                h = blk(h)
            if has_up:
                a = unwrap(h)
                a = jnp.repeat(jnp.repeat(a, 2, axis=2), 2, axis=3)
                h = self.ups[ui](wrap(a))
                ui += 1
        return self.conv_out(nn.functional.silu(self.norm_out(h)))


class DiagonalGaussian:
    """Posterior q(z|x) = N(mean, diag(exp(logvar)))."""

    def __init__(self, params):
        a = unwrap(params)
        self.mean, logvar = jnp.split(a, 2, axis=1)
        self.logvar = jnp.clip(logvar, -30.0, 20.0)

    def sample(self, key=None):
        key = key if key is not None else _random.next_key()
        std = jnp.exp(0.5 * self.logvar)
        return wrap(self.mean + std * jax.random.normal(
            key, self.mean.shape, self.mean.dtype))

    def mode(self):
        return wrap(self.mean)

    def kl(self):
        """KL(q ‖ N(0, I)) per sample, summed over latent dims."""
        v = jnp.sum(0.5 * (self.mean ** 2 + jnp.exp(self.logvar)
                           - 1.0 - self.logvar), axis=(1, 2, 3))
        return wrap(v)


class AutoencoderKL(Layer):
    """encode(x) → DiagonalGaussian; decode(z) → reconstruction."""

    def __init__(self, config: VAEConfig = None, **kw):
        super().__init__()
        self.config = config or VAEConfig(**kw)
        self.encoder = Encoder(self.config)
        self.decoder = Decoder(self.config)

    def encode(self, x) -> DiagonalGaussian:
        return DiagonalGaussian(self.encoder(x))

    def decode(self, z):
        return self.decoder(z)

    def forward(self, x, sample_posterior=True):
        post = self.encode(x)
        z = post.sample() if sample_posterior else post.mode()
        return self.decode(z), post

    def loss(self, x, kl_weight=1e-6):
        """Reconstruction (L1, the SD recipe's pixel term) + weighted KL."""
        recon, post = self.forward(x)
        rec = jnp.mean(jnp.abs(unwrap(recon) - unwrap(x)))
        kl = jnp.mean(unwrap(post.kl()))
        return wrap(rec + kl_weight * kl)

    def scale_latents(self, z):
        cfg = self.config
        return wrap((unwrap(z) - cfg.shift_factor) * cfg.scaling_factor)

    def unscale_latents(self, z):
        cfg = self.config
        return wrap(unwrap(z) / cfg.scaling_factor + cfg.shift_factor)
