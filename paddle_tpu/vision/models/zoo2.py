"""Vision model zoo, part 2 (python/paddle/vision/models/: densenet.py,
googlenet.py, inceptionv3.py, mobilenetv3.py, shufflenetv2.py,
squeezenet.py). Canonical published architectures implemented directly on
the nn layer surface; weight layouts follow the reference so state_dicts
line up name-for-name.
"""
from __future__ import annotations

import math

from ... import nn


def _no_pretrained(pretrained):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights require the paddle hub download toolchain; "
            "load a converted state_dict via set_state_dict instead")


# ---------------------------------------------------------------------------
# MobileNetV3
# ---------------------------------------------------------------------------

def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _SqueezeExcite(nn.Layer):
    def __init__(self, channels, reduction=4):
        super().__init__()
        squeeze = _make_divisible(channels // reduction)
        self.avg_pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(channels, squeeze, 1)
        self.fc2 = nn.Conv2D(squeeze, channels, 1)

    def forward(self, x):
        s = self.avg_pool(x)
        s = nn.functional.relu(self.fc1(s))
        s = nn.functional.hardsigmoid(self.fc2(s))
        return x * s


class _MBV3Block(nn.Layer):
    def __init__(self, in_c, exp_c, out_c, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        self.expand = in_c != exp_c
        if self.expand:
            self.expand_conv = nn.Conv2D(in_c, exp_c, 1, bias_attr=False)
            self.expand_bn = nn.BatchNorm2D(exp_c)
        self.dw_conv = nn.Conv2D(exp_c, exp_c, kernel, stride=stride,
                                 padding=kernel // 2, groups=exp_c,
                                 bias_attr=False)
        self.dw_bn = nn.BatchNorm2D(exp_c)
        self.se = _SqueezeExcite(exp_c) if use_se else None
        self.project_conv = nn.Conv2D(exp_c, out_c, 1, bias_attr=False)
        self.project_bn = nn.BatchNorm2D(out_c)
        self.act = (nn.functional.hardswish if act == "hardswish"
                    else nn.functional.relu)

    def forward(self, x):
        h = x
        if self.expand:
            h = self.act(self.expand_bn(self.expand_conv(h)))
        h = self.act(self.dw_bn(self.dw_conv(h)))
        if self.se is not None:
            h = self.se(h)
        h = self.project_bn(self.project_conv(h))
        return x + h if self.use_res else h


# (kernel, expansion, out, use_se, activation, stride) per block
_MBV3_LARGE = [
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2), (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1), (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1), (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2), (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]
_MBV3_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1), (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1), (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2), (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, config, last_channels, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(16 * scale)
        self.conv = nn.Conv2D(3, in_c, 3, stride=2, padding=1,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(in_c)
        blocks = []
        for k, exp, out, se, act, s in config:
            exp_c = _make_divisible(exp * scale)
            out_c = _make_divisible(out * scale)
            blocks.append(_MBV3Block(in_c, exp_c, out_c, k, s, se, act))
            in_c = out_c
        self.blocks = nn.Sequential(*blocks)
        last_exp = _make_divisible(config[-1][1] * scale)
        self.last_conv = nn.Conv2D(in_c, last_exp, 1, bias_attr=False)
        self.last_bn = nn.BatchNorm2D(last_exp)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_exp, last_channels), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_channels, num_classes))

    def forward(self, x):
        x = nn.functional.hardswish(self.bn(self.conv(x)))
        x = self.blocks(x)
        x = nn.functional.hardswish(self.last_bn(self.last_conv(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_MBV3_LARGE, 1280, scale, num_classes, with_pool)


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_MBV3_SMALL, 1024, scale, num_classes, with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    _no_pretrained(pretrained)
    return MobileNetV3Large(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    _no_pretrained(pretrained)
    return MobileNetV3Small(scale=scale, **kwargs)


# ---------------------------------------------------------------------------
# DenseNet
# ---------------------------------------------------------------------------

class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth_rate, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_c)
        self.conv1 = nn.Conv2D(in_c, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.dropout = dropout

    def forward(self, x):
        from ... import concat

        h = self.conv1(nn.functional.relu(self.bn1(x)))
        h = self.conv2(nn.functional.relu(self.bn2(h)))
        if self.dropout:
            h = nn.functional.dropout(h, self.dropout,
                                      training=self.training)
        return concat([x, h], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_c, out_c):
        super().__init__()
        self.bn = nn.BatchNorm2D(in_c)
        self.conv = nn.Conv2D(in_c, out_c, 1, bias_attr=False)

    def forward(self, x):
        x = self.conv(nn.functional.relu(self.bn(x)))
        return nn.functional.avg_pool2d(x, 2, 2)


_DENSENET_CFG = {
    121: (6, 12, 24, 16), 161: (6, 12, 36, 24), 169: (6, 12, 32, 32),
    201: (6, 12, 48, 32), 264: (6, 12, 64, 48),
}


class DenseNet(nn.Layer):
    """paddle.vision.models.DenseNet (densenet.py)."""

    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True, growth_rate=None):
        super().__init__()
        block_cfg = _DENSENET_CFG[layers]
        growth = growth_rate or (48 if layers == 161 else 32)
        init_c = 2 * growth
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv = nn.Conv2D(3, init_c, 7, stride=2, padding=3,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(init_c)
        blocks = []
        c = init_c
        for i, n in enumerate(block_cfg):
            for _ in range(n):
                blocks.append(_DenseLayer(c, growth, bn_size, dropout))
                c += growth
            if i != len(block_cfg) - 1:
                blocks.append(_Transition(c, c // 2))
                c //= 2
        self.features = nn.Sequential(*blocks)
        self.final_bn = nn.BatchNorm2D(c)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(c, num_classes)

    def forward(self, x):
        x = nn.functional.max_pool2d(
            nn.functional.relu(self.bn(self.conv(x))), 3, 2, 1)
        x = nn.functional.relu(self.final_bn(self.features(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def _densenet(layers, pretrained, **kwargs):
    _no_pretrained(pretrained)
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)


# ---------------------------------------------------------------------------
# SqueezeNet
# ---------------------------------------------------------------------------

class _Fire(nn.Layer):
    def __init__(self, in_c, squeeze_c, e1_c, e3_c):
        super().__init__()
        self.squeeze = nn.Conv2D(in_c, squeeze_c, 1)
        self.expand1 = nn.Conv2D(squeeze_c, e1_c, 1)
        self.expand3 = nn.Conv2D(squeeze_c, e3_c, 3, padding=1)

    def forward(self, x):
        from ... import concat

        s = nn.functional.relu(self.squeeze(x))
        return concat([nn.functional.relu(self.expand1(s)),
                       nn.functional.relu(self.expand3(s))], axis=1)


class SqueezeNet(nn.Layer):
    """paddle.vision.models.SqueezeNet (squeezenet.py); version '1.0'/'1.1'."""

    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        v11 = str(version) in ("1.1", "squeezenet1_1")
        if v11:
            self.conv = nn.Conv2D(3, 64, 3, stride=2)
            fires = [_Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64), "pool",
                     _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                     "pool", _Fire(256, 48, 192, 192),
                     _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                     _Fire(512, 64, 256, 256)]
        else:
            self.conv = nn.Conv2D(3, 96, 7, stride=2)
            fires = [_Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                     _Fire(128, 32, 128, 128), "pool",
                     _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                     _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                     "pool", _Fire(512, 64, 256, 256)]
        self._fires = fires
        mods = [f for f in fires if not isinstance(f, str)]
        self.fires = nn.LayerList(mods)
        self.final_conv = nn.Conv2D(512, num_classes, 1)

    def forward(self, x):
        x = nn.functional.max_pool2d(nn.functional.relu(self.conv(x)), 3, 2)
        it = iter(self.fires)
        for f in self._fires:
            if isinstance(f, str):
                x = nn.functional.max_pool2d(x, 3, 2)
            else:
                x = next(it)(x)
        x = nn.functional.relu(self.final_conv(
            nn.functional.dropout(x, 0.5, training=self.training)))
        if self.with_pool:
            x = nn.functional.adaptive_avg_pool2d(x, 1)
        return x.flatten(1)


def squeezenet1_0(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return SqueezeNet("1.1", **kwargs)


# ---------------------------------------------------------------------------
# GoogLeNet
# ---------------------------------------------------------------------------

class _Inception(nn.Layer):
    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = nn.Conv2D(in_c, c1, 1)
        self.b2_1 = nn.Conv2D(in_c, c3r, 1)
        self.b2_2 = nn.Conv2D(c3r, c3, 3, padding=1)
        self.b3_1 = nn.Conv2D(in_c, c5r, 1)
        self.b3_2 = nn.Conv2D(c5r, c5, 5, padding=2)
        self.b4 = nn.Conv2D(in_c, proj, 1)

    def forward(self, x):
        from ... import concat

        relu = nn.functional.relu
        y1 = relu(self.b1(x))
        y2 = relu(self.b2_2(relu(self.b2_1(x))))
        y3 = relu(self.b3_2(relu(self.b3_1(x))))
        y4 = relu(self.b4(nn.functional.max_pool2d(x, 3, 1, 1)))
        return concat([y1, y2, y3, y4], axis=1)


class GoogLeNet(nn.Layer):
    """paddle.vision.models.GoogLeNet (googlenet.py). Returns (main, aux1,
    aux2) logits like the reference."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = nn.Conv2D(3, 64, 7, stride=2, padding=3)
        self.conv2 = nn.Conv2D(64, 64, 1)
        self.conv3 = nn.Conv2D(64, 192, 3, padding=1)
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if num_classes > 0:
            self.fc = nn.Linear(1024, num_classes)
            self.aux1_conv = nn.Conv2D(512, 128, 1)
            self.aux1_fc1 = nn.Linear(128 * 4 * 4, 1024)
            self.aux1_fc2 = nn.Linear(1024, num_classes)
            self.aux2_conv = nn.Conv2D(528, 128, 1)
            self.aux2_fc1 = nn.Linear(128 * 4 * 4, 1024)
            self.aux2_fc2 = nn.Linear(1024, num_classes)

    def _aux(self, x, conv, fc1, fc2):
        a = nn.functional.adaptive_avg_pool2d(x, 4)
        a = nn.functional.relu(conv(a)).flatten(1)
        a = nn.functional.relu(fc1(a))
        a = nn.functional.dropout(a, 0.7, training=self.training)
        return fc2(a)

    def forward(self, x):
        relu = nn.functional.relu
        mp = nn.functional.max_pool2d
        x = mp(relu(self.conv1(x)), 3, 2, 1)
        x = mp(relu(self.conv3(relu(self.conv2(x)))), 3, 2, 1)
        x = mp(self.i3b(self.i3a(x)), 3, 2, 1)
        x = self.i4a(x)
        aux1 = (self._aux(x, self.aux1_conv, self.aux1_fc1, self.aux1_fc2)
                if self.num_classes > 0 else None)
        x = self.i4d(self.i4c(self.i4b(x)))
        aux2 = (self._aux(x, self.aux2_conv, self.aux2_fc1, self.aux2_fc2)
                if self.num_classes > 0 else None)
        x = mp(self.i4e(x), 3, 2, 1)
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = nn.functional.adaptive_avg_pool2d(x, 1)
        if self.num_classes > 0:
            x = nn.functional.dropout(x.flatten(1), 0.4,
                                      training=self.training)
            x = self.fc(x)
            return x, aux1, aux2
        return x


def googlenet(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return GoogLeNet(**kwargs)


# ---------------------------------------------------------------------------
# InceptionV3
# ---------------------------------------------------------------------------

class _BNConv(nn.Layer):
    def __init__(self, in_c, out_c, kernel, **kw):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, kernel, bias_attr=False, **kw)
        self.bn = nn.BatchNorm2D(out_c)

    def forward(self, x):
        return nn.functional.relu(self.bn(self.conv(x)))


class _InceptionA(nn.Layer):
    def __init__(self, in_c, pool_c):
        super().__init__()
        self.b1 = _BNConv(in_c, 64, 1)
        self.b5_1 = _BNConv(in_c, 48, 1)
        self.b5_2 = _BNConv(48, 64, 5, padding=2)
        self.b3_1 = _BNConv(in_c, 64, 1)
        self.b3_2 = _BNConv(64, 96, 3, padding=1)
        self.b3_3 = _BNConv(96, 96, 3, padding=1)
        self.bp = _BNConv(in_c, pool_c, 1)

    def forward(self, x):
        from ... import concat

        return concat([
            self.b1(x), self.b5_2(self.b5_1(x)),
            self.b3_3(self.b3_2(self.b3_1(x))),
            self.bp(nn.functional.avg_pool2d(x, 3, 1, 1))], axis=1)


class _InceptionB(nn.Layer):
    """Grid reduction 35→17."""

    def __init__(self, in_c):
        super().__init__()
        self.b3 = _BNConv(in_c, 384, 3, stride=2)
        self.bd_1 = _BNConv(in_c, 64, 1)
        self.bd_2 = _BNConv(64, 96, 3, padding=1)
        self.bd_3 = _BNConv(96, 96, 3, stride=2)

    def forward(self, x):
        from ... import concat

        return concat([self.b3(x), self.bd_3(self.bd_2(self.bd_1(x))),
                       nn.functional.max_pool2d(x, 3, 2)], axis=1)


class _InceptionC(nn.Layer):
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = _BNConv(in_c, 192, 1)
        self.b7_1 = _BNConv(in_c, c7, 1)
        self.b7_2 = _BNConv(c7, c7, (1, 7), padding=(0, 3))
        self.b7_3 = _BNConv(c7, 192, (7, 1), padding=(3, 0))
        self.b77_1 = _BNConv(in_c, c7, 1)
        self.b77_2 = _BNConv(c7, c7, (7, 1), padding=(3, 0))
        self.b77_3 = _BNConv(c7, c7, (1, 7), padding=(0, 3))
        self.b77_4 = _BNConv(c7, c7, (7, 1), padding=(3, 0))
        self.b77_5 = _BNConv(c7, 192, (1, 7), padding=(0, 3))
        self.bp = _BNConv(in_c, 192, 1)

    def forward(self, x):
        from ... import concat

        return concat([
            self.b1(x), self.b7_3(self.b7_2(self.b7_1(x))),
            self.b77_5(self.b77_4(self.b77_3(self.b77_2(self.b77_1(x))))),
            self.bp(nn.functional.avg_pool2d(x, 3, 1, 1))], axis=1)


class _InceptionD(nn.Layer):
    """Grid reduction 17→8."""

    def __init__(self, in_c):
        super().__init__()
        self.b3_1 = _BNConv(in_c, 192, 1)
        self.b3_2 = _BNConv(192, 320, 3, stride=2)
        self.b7_1 = _BNConv(in_c, 192, 1)
        self.b7_2 = _BNConv(192, 192, (1, 7), padding=(0, 3))
        self.b7_3 = _BNConv(192, 192, (7, 1), padding=(3, 0))
        self.b7_4 = _BNConv(192, 192, 3, stride=2)

    def forward(self, x):
        from ... import concat

        return concat([self.b3_2(self.b3_1(x)),
                       self.b7_4(self.b7_3(self.b7_2(self.b7_1(x)))),
                       nn.functional.max_pool2d(x, 3, 2)], axis=1)


class _InceptionE(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _BNConv(in_c, 320, 1)
        self.b3_1 = _BNConv(in_c, 384, 1)
        self.b3_2a = _BNConv(384, 384, (1, 3), padding=(0, 1))
        self.b3_2b = _BNConv(384, 384, (3, 1), padding=(1, 0))
        self.b33_1 = _BNConv(in_c, 448, 1)
        self.b33_2 = _BNConv(448, 384, 3, padding=1)
        self.b33_3a = _BNConv(384, 384, (1, 3), padding=(0, 1))
        self.b33_3b = _BNConv(384, 384, (3, 1), padding=(1, 0))
        self.bp = _BNConv(in_c, 192, 1)

    def forward(self, x):
        from ... import concat

        y3 = self.b3_1(x)
        y33 = self.b33_2(self.b33_1(x))
        return concat([
            self.b1(x),
            concat([self.b3_2a(y3), self.b3_2b(y3)], axis=1),
            concat([self.b33_3a(y33), self.b33_3b(y33)], axis=1),
            self.bp(nn.functional.avg_pool2d(x, 3, 1, 1))], axis=1)


class InceptionV3(nn.Layer):
    """paddle.vision.models.InceptionV3 (inceptionv3.py)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _BNConv(3, 32, 3, stride=2), _BNConv(32, 32, 3),
            _BNConv(32, 64, 3, padding=1))
        self.stem2 = nn.Sequential(_BNConv(64, 80, 1), _BNConv(80, 192, 3))
        self.mixed = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64), _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160),
            _InceptionC(768, 160), _InceptionC(768, 192),
            _InceptionD(768),
            _InceptionE(1280), _InceptionE(2048))
        if num_classes > 0:
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = nn.functional.max_pool2d(self.stem(x), 3, 2)
        x = nn.functional.max_pool2d(self.stem2(x), 3, 2)
        x = self.mixed(x)
        if self.with_pool:
            x = nn.functional.adaptive_avg_pool2d(x, 1)
        if self.num_classes > 0:
            x = nn.functional.dropout(x.flatten(1), 0.5,
                                      training=self.training)
            x = self.fc(x)
        return x


def inception_v3(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return InceptionV3(**kwargs)


# ---------------------------------------------------------------------------
# ShuffleNetV2
# ---------------------------------------------------------------------------

class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride, act):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        self.act_name = act
        if stride == 2:
            self.short_dw = nn.Conv2D(in_c, in_c, 3, stride=2, padding=1,
                                      groups=in_c, bias_attr=False)
            self.short_dw_bn = nn.BatchNorm2D(in_c)
            self.short_pw = nn.Conv2D(in_c, branch_c, 1, bias_attr=False)
            self.short_pw_bn = nn.BatchNorm2D(branch_c)
            main_in = in_c
        else:
            main_in = in_c // 2
        self.pw1 = nn.Conv2D(main_in, branch_c, 1, bias_attr=False)
        self.pw1_bn = nn.BatchNorm2D(branch_c)
        self.dw = nn.Conv2D(branch_c, branch_c, 3, stride=stride, padding=1,
                            groups=branch_c, bias_attr=False)
        self.dw_bn = nn.BatchNorm2D(branch_c)
        self.pw2 = nn.Conv2D(branch_c, branch_c, 1, bias_attr=False)
        self.pw2_bn = nn.BatchNorm2D(branch_c)

    def _act(self, x):
        return (nn.functional.swish(x) if self.act_name == "swish"
                else nn.functional.relu(x))

    def forward(self, x):
        from ... import concat

        if self.stride == 2:
            short = self._act(self.short_pw_bn(self.short_pw(
                self.short_dw_bn(self.short_dw(x)))))
            main = x
        else:
            c = x.shape[1] // 2
            short, main = x[:, :c], x[:, c:]
        h = self._act(self.pw1_bn(self.pw1(main)))
        h = self.dw_bn(self.dw(h))
        h = self._act(self.pw2_bn(self.pw2(h)))
        out = concat([short, h], axis=1)
        return nn.functional.channel_shuffle(out, 2)


_SHUFFLE_CFG = {
    0.25: (24, (24, 48, 96), 512), 0.33: (24, (32, 64, 128), 512),
    0.5: (24, (48, 96, 192), 1024), 1.0: (24, (116, 232, 464), 1024),
    1.5: (24, (176, 352, 704), 1024), 2.0: (24, (244, 488, 976), 2048),
}


class ShuffleNetV2(nn.Layer):
    """paddle.vision.models.ShuffleNetV2 (shufflenetv2.py)."""

    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        stem_c, stage_cs, last_c = _SHUFFLE_CFG[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = nn.Conv2D(3, stem_c, 3, stride=2, padding=1,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(stem_c)
        units = []
        in_c = stem_c
        for out_c, repeat in zip(stage_cs, (4, 8, 4)):
            units.append(_ShuffleUnit(in_c, out_c, 2, act))
            for _ in range(repeat - 1):
                units.append(_ShuffleUnit(out_c, out_c, 1, act))
            in_c = out_c
        self.units = nn.Sequential(*units)
        self.conv_last = nn.Conv2D(in_c, last_c, 1, bias_attr=False)
        self.bn_last = nn.BatchNorm2D(last_c)
        if num_classes > 0:
            self.fc = nn.Linear(last_c, num_classes)

    def forward(self, x):
        x = nn.functional.relu(self.bn1(self.conv1(x)))
        x = nn.functional.max_pool2d(x, 3, 2, 1)
        x = self.units(x)
        x = nn.functional.relu(self.bn_last(self.conv_last(x)))
        if self.with_pool:
            x = nn.functional.adaptive_avg_pool2d(x, 1)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def _shufflenet(scale, act, pretrained, **kwargs):
    _no_pretrained(pretrained)
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, "relu", pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, "swish", pretrained, **kwargs)
