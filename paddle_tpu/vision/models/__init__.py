"""paddle.vision.models parity (python/paddle/vision/models/__init__.py).

Implemented: LeNet, AlexNet, VGG (11/13/16/19), ResNet family (18-152,
resnext, wide), MobileNetV1/V2. Remaining reference zoo entries (densenet,
googlenet, inception, shufflenet, squeezenet, mobilenetv3) are tracked
gaps for a later round.
"""
from .resnet import (  # noqa: F401
    BasicBlock, BottleneckBlock, ResNet, resnet18, resnet34, resnet50,
    resnet101, resnet152, resnext50_32x4d, resnext50_64x4d, resnext101_32x4d,
    resnext101_64x4d, resnext152_32x4d, resnext152_64x4d, wide_resnet50_2,
    wide_resnet101_2)
from .small import (  # noqa: F401
    AlexNet, LeNet, MobileNetV1, MobileNetV2, VGG, alexnet, mobilenet_v1,
    mobilenet_v2, vgg11, vgg13, vgg16, vgg19)
from .dit import DiT, DiTConfig, dit_xl_2  # noqa: F401
