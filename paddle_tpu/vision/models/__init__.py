"""paddle.vision.models parity (python/paddle/vision/models/__init__.py):
LeNet, AlexNet, VGG, ResNet/ResNeXt/WideResNet, MobileNetV1/V2/V3,
DenseNet, GoogLeNet, InceptionV3, SqueezeNet, ShuffleNetV2, DiT.
"""
from .resnet import (  # noqa: F401
    BasicBlock, BottleneckBlock, ResNet, resnet18, resnet34, resnet50,
    resnet101, resnet152, resnext50_32x4d, resnext50_64x4d, resnext101_32x4d,
    resnext101_64x4d, resnext152_32x4d, resnext152_64x4d, wide_resnet50_2,
    wide_resnet101_2)
from .small import (  # noqa: F401
    AlexNet, LeNet, MobileNetV1, MobileNetV2, VGG, alexnet, mobilenet_v1,
    mobilenet_v2, vgg11, vgg13, vgg16, vgg19)
from .dit import DiT, DiTConfig, dit_xl_2  # noqa: F401
from .vae import AutoencoderKL, DiagonalGaussian, VAEConfig  # noqa: F401
from .zoo2 import (  # noqa: F401
    MobileNetV3Small, MobileNetV3Large, mobilenet_v3_small,
    mobilenet_v3_large, DenseNet, densenet121, densenet161, densenet169,
    densenet201, densenet264, InceptionV3, inception_v3, SqueezeNet,
    squeezenet1_0, squeezenet1_1, GoogLeNet, googlenet, ShuffleNetV2,
    shufflenet_v2_x0_25, shufflenet_v2_x0_33, shufflenet_v2_x0_5,
    shufflenet_v2_x1_0, shufflenet_v2_x1_5, shufflenet_v2_x2_0,
    shufflenet_v2_swish)
