"""DiT — Diffusion Transformer (the DiT / Stable-Diffusion-3 family in
BASELINE.json, trained on the reference platform via PaddleMIX).

Architecture (DiT paper / PaddleMIX ppdiffusers DiTTransformer2DModel):
patchify the latent image → add fixed sin-cos position embeddings →
N adaLN-Zero transformer blocks conditioned on (timestep, class) embeddings
→ adaLN final layer → unpatchify to noise (+ sigma) prediction.

TPU-native: the whole forward is jit-friendly (static shapes, no Python
control flow on data); attention is plain SDPA over full (bidirectional)
patch sequences, which XLA maps straight onto the MXU; the adaLN modulation
is elementwise and fuses into the surrounding matmuls.
"""
from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np

from ... import nn
from ...nn.layer import Layer
from ...nn.initializer import Constant, Normal, XavierUniform
from ...ops.registry import apply
from ...tensor_class import Tensor, unwrap, wrap


def _sincos_pos_embed(dim: int, grid: int) -> np.ndarray:
    """Fixed 2-D sin-cos position embedding [grid*grid, dim] (DiT)."""
    def one_dim(d, pos):
        omega = 1.0 / (10000 ** (np.arange(d // 2) / (d / 2.0)))
        out = np.einsum("p,f->pf", pos, omega)
        return np.concatenate([np.sin(out), np.cos(out)], axis=1)

    coords = np.arange(grid, dtype=np.float64)
    gy, gx = np.meshgrid(coords, coords, indexing="ij")
    emb = np.concatenate([one_dim(dim // 2, gy.reshape(-1)),
                          one_dim(dim // 2, gx.reshape(-1))], axis=1)
    return emb.astype(np.float32)


class TimestepEmbedder(Layer):
    """Sinusoidal timestep features → 2-layer MLP (DiT TimestepEmbedder)."""

    def __init__(self, hidden_size: int, freq_dim: int = 256):
        super().__init__()
        self.freq_dim = freq_dim
        self.mlp1 = nn.Linear(freq_dim, hidden_size)
        self.mlp2 = nn.Linear(hidden_size, hidden_size)

    def forward(self, t):
        half = self.freq_dim // 2

        def feats(tt):
            freqs = jnp.exp(-math.log(10000.0)
                            * jnp.arange(half, dtype=jnp.float32) / half)
            args = tt.astype(jnp.float32)[:, None] * freqs[None]
            return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)

        f = apply("dit_t_feats", feats, t, differentiable=False)
        return self.mlp2(nn.functional.silu(self.mlp1(f)))


class LabelEmbedder(Layer):
    """Class-label embedding with a null class for classifier-free
    guidance (DiT LabelEmbedder)."""

    def __init__(self, num_classes: int, hidden_size: int):
        super().__init__()
        self.embedding_table = nn.Embedding(num_classes + 1, hidden_size)
        self.num_classes = num_classes

    def forward(self, y):
        return self.embedding_table(y)


class DiTBlock(Layer):
    """adaLN-Zero block: conditioning regresses per-block shift/scale/gate
    for both the attention and MLP branches; gates start at zero so the
    block begins as identity."""

    def __init__(self, hidden_size: int, num_heads: int, mlp_ratio: float = 4.0):
        super().__init__()
        self.norm1 = nn.LayerNorm(hidden_size, epsilon=1e-6,
                                  weight_attr=False, bias_attr=False)
        self.attn = nn.MultiHeadAttention(hidden_size, num_heads)
        self.norm2 = nn.LayerNorm(hidden_size, epsilon=1e-6,
                                  weight_attr=False, bias_attr=False)
        inner = int(hidden_size * mlp_ratio)
        self.mlp_fc1 = nn.Linear(hidden_size, inner)
        self.mlp_fc2 = nn.Linear(inner, hidden_size)
        self.adaLN = nn.Linear(hidden_size, 6 * hidden_size)
        # adaLN-Zero init: modulation starts as zeros → identity block
        self.adaLN.weight._array = jnp.zeros_like(self.adaLN.weight._array)
        self.adaLN.bias._array = jnp.zeros_like(self.adaLN.bias._array)

    def forward(self, x, c):
        mod = self.adaLN(nn.functional.silu(c))

        def split6(m):
            return tuple(jnp.split(m, 6, axis=-1))

        sa, ga, ba, sm, gm, bm = apply("dit_modulation", split6, mod)

        def modulate(h, shift, scale):
            return apply(
                "dit_modulate",
                lambda hh, sh, sc: hh * (1 + sc[:, None]) + sh[:, None],
                h, shift, scale)

        h = modulate(self.norm1(x), sa, ga)
        attn_out = self.attn(h, h, h)
        x = x + apply("dit_gate", lambda a, g: a * g[:, None], attn_out, ba)
        h = modulate(self.norm2(x), sm, gm)
        h = self.mlp_fc2(nn.functional.gelu(self.mlp_fc1(h),
                                            approximate=True))
        return x + apply("dit_gate", lambda a, g: a * g[:, None], h, bm)


class FinalLayer(Layer):
    def __init__(self, hidden_size: int, patch_size: int, out_channels: int):
        super().__init__()
        self.norm = nn.LayerNorm(hidden_size, epsilon=1e-6,
                                 weight_attr=False, bias_attr=False)
        self.linear = nn.Linear(hidden_size,
                                patch_size * patch_size * out_channels)
        self.adaLN = nn.Linear(hidden_size, 2 * hidden_size)
        self.adaLN.weight._array = jnp.zeros_like(self.adaLN.weight._array)
        self.adaLN.bias._array = jnp.zeros_like(self.adaLN.bias._array)
        self.linear.weight._array = jnp.zeros_like(self.linear.weight._array)
        self.linear.bias._array = jnp.zeros_like(self.linear.bias._array)

    def forward(self, x, c):
        mod = self.adaLN(nn.functional.silu(c))
        shift, scale = apply(
            "dit_final_mod", lambda m: tuple(jnp.split(m, 2, axis=-1)), mod)
        x = apply("dit_modulate",
                  lambda hh, sh, sc: hh * (1 + sc[:, None]) + sh[:, None],
                  self.norm(x), shift, scale)
        return self.linear(x)


@dataclasses.dataclass
class DiTConfig:
    input_size: int = 32          # latent spatial size
    patch_size: int = 2
    in_channels: int = 4
    hidden_size: int = 1152
    num_layers: int = 28
    num_heads: int = 16
    mlp_ratio: float = 4.0
    num_classes: int = 1000
    learn_sigma: bool = True

    @staticmethod
    def dit_xl_2(**kw):
        return DiTConfig(**kw)

    @staticmethod
    def tiny(**kw):
        base = dict(input_size=8, patch_size=2, in_channels=4,
                    hidden_size=64, num_layers=2, num_heads=4,
                    num_classes=10)
        base.update(kw)
        return DiTConfig(**base)


class DiT(Layer):
    """DiT noise-prediction network: forward(x_t, t, y) → eps(+sigma)."""

    def __init__(self, config: DiTConfig):
        super().__init__()
        self.config = config
        c = config
        self.out_channels = c.in_channels * (2 if c.learn_sigma else 1)
        self.x_embedder = nn.Conv2D(c.in_channels, c.hidden_size,
                                    kernel_size=c.patch_size,
                                    stride=c.patch_size)
        grid = c.input_size // c.patch_size
        self.num_patches = grid * grid
        self._pos = jnp.asarray(_sincos_pos_embed(c.hidden_size, grid))
        self.t_embedder = TimestepEmbedder(c.hidden_size)
        self.y_embedder = LabelEmbedder(c.num_classes, c.hidden_size)
        self.blocks = nn.LayerList(
            [DiTBlock(c.hidden_size, c.num_heads, c.mlp_ratio)
             for _ in range(c.num_layers)])
        self.final_layer = FinalLayer(c.hidden_size, c.patch_size,
                                      self.out_channels)

    def unpatchify(self, x):
        c = self.config
        p = c.patch_size
        grid = c.input_size // p
        oc = self.out_channels

        def un(arr):
            b = arr.shape[0]
            arr = arr.reshape(b, grid, grid, p, p, oc)
            arr = jnp.einsum("bhwpqc->bchpwq", arr)
            return arr.reshape(b, oc, grid * p, grid * p)

        return apply("dit_unpatchify", un, x)

    def forward(self, x, t, y):
        """x [B, C, H, W] latents; t [B] timesteps; y [B] class ids."""
        patches = self.x_embedder(x)  # [B, hidden, gh, gw]
        tokens = apply(
            "dit_patchify",
            lambda ph, pos: ph.reshape(ph.shape[0], ph.shape[1], -1)
            .swapaxes(1, 2) + pos[None],
            patches, self._pos)
        c = self.t_embedder(t) + self.y_embedder(y)
        for block in self.blocks:
            tokens = block(tokens, c)
        out = self.final_layer(tokens, c)
        return self.unpatchify(out)


def dit_xl_2(**kwargs) -> DiT:
    return DiT(DiTConfig.dit_xl_2(**kwargs))
