"""paddle.vision parity (python/paddle/vision/)."""
from . import datasets, models, ops, transforms  # noqa: F401
from .models import *  # noqa: F401,F403


def set_image_backend(backend: str):
    """API parity; the numpy pipeline ignores the hint."""
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unknown image backend {backend!r}")


def get_image_backend() -> str:
    return "cv2"


def image_load(path, backend=None):
    from .datasets import _default_loader

    return _default_loader(path)
