"""Class-style transforms.

Reference parity: python/paddle/vision/transforms/transforms.py (Compose,
BaseTransform and the standard augmentation set).
"""
from __future__ import annotations

import numbers
import random
from typing import Callable, List, Optional, Sequence

import numpy as np

from . import functional as F


class Compose:
    def __init__(self, transforms: Sequence[Callable]):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    """Reference BaseTransform: keys-aware apply_* dispatch; the numpy build
    applies _apply_image to the input directly (label passthrough happens in
    dataset code)."""

    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):  # pragma: no cover - abstract
        raise NotImplementedError


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size, self.interpolation = size, interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.padding, self.pad_if_needed = padding, pad_if_needed
        self.fill, self.padding_mode = fill, padding_mode

    def _apply_image(self, img):
        a = np.asarray(img)
        if self.padding is not None:
            a = F.pad(a, self.padding, self.fill, self.padding_mode)
        th, tw = self.size
        h, w = a.shape[:2]
        if self.pad_if_needed and (h < th or w < tw):
            a = F.pad(a, (0, 0, max(0, tw - w), max(0, th - h)), self.fill,
                      self.padding_mode)
            h, w = a.shape[:2]
        top = random.randint(0, max(0, h - th))
        left = random.randint(0, max(0, w - tw))
        return F.crop(a, top, left, th, tw)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.hflip(img) if random.random() < self.prob else np.asarray(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.vflip(img) if random.random() < self.prob else np.asarray(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.scale, self.ratio, self.interpolation = scale, ratio, interpolation

    def _apply_image(self, img):
        a = np.asarray(img)
        h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if cw <= w and ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                return F.resize(F.crop(a, top, left, ch, cw), self.size,
                                self.interpolation)
        return F.resize(F.center_crop(a, min(h, w)), self.size, self.interpolation)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.interpolation, self.expand = interpolation, expand
        self.center, self.fill = center, fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return F.rotate(img, angle, self.interpolation, self.expand,
                        self.center, self.fill)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        super().__init__(keys)
        self.brightness, self.contrast = brightness, contrast
        self.saturation, self.hue = saturation, hue

    def _apply_image(self, img):
        a = np.asarray(img)
        ops = []
        if self.brightness:
            ops.append(lambda x: F.adjust_brightness(
                x, random.uniform(max(0, 1 - self.brightness), 1 + self.brightness)))
        if self.contrast:
            ops.append(lambda x: F.adjust_contrast(
                x, random.uniform(max(0, 1 - self.contrast), 1 + self.contrast)))
        if self.saturation:
            ops.append(lambda x: F.adjust_saturation(
                x, random.uniform(max(0, 1 - self.saturation), 1 + self.saturation)))
        if self.hue:
            ops.append(lambda x: F.adjust_hue(x, random.uniform(-self.hue, self.hue)))
        random.shuffle(ops)
        for op in ops:
            a = op(a)
        return a


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding, self.fill, self.padding_mode = padding, fill, padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        a = np.asarray(img)
        if a.ndim == 2:
            a = a[:, :, None]
        return np.transpose(a, self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        return F.adjust_brightness(
            img, random.uniform(max(0, 1 - self.value), 1 + self.value))


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("contrast value should be non-negative")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        return F.adjust_contrast(
            img, random.uniform(max(0, 1 - self.value), 1 + self.value))


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        return F.adjust_saturation(
            img, random.uniform(max(0, 1 - self.value), 1 + self.value))


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value should be in [0, 0.5]")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        return F.adjust_hue(img, random.uniform(-self.value, self.value))


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob, self.scale, self.ratio, self.value = prob, scale, ratio, value

    def _apply_image(self, img):
        a = np.array(img)
        if random.random() >= self.prob:
            return a
        h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target / ar)))
            ew = int(round(np.sqrt(target * ar)))
            if eh < h and ew < w:
                top = random.randint(0, h - eh)
                left = random.randint(0, w - ew)
                a[top:top + eh, left:left + ew] = self.value
                return a
        return a


class RandomAffine(BaseTransform):
    """transforms.RandomAffine (vision/transforms/transforms.py)."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.translate, self.scale_rng, self.shear = translate, scale, shear
        self.interpolation, self.fill, self.center = interpolation, fill, center

    def _apply_image(self, img):
        a = F._as_np(img)
        h, w = a.shape[:2]
        angle = random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = random.uniform(-self.translate[0], self.translate[0]) * w
            ty = random.uniform(-self.translate[1], self.translate[1]) * h
        sc = random.uniform(*self.scale_rng) if self.scale_rng else 1.0
        if self.shear is None:
            sh = (0.0, 0.0)
        elif isinstance(self.shear, numbers.Number):
            sh = (random.uniform(-self.shear, self.shear), 0.0)
        elif len(self.shear) == 2:
            sh = (random.uniform(self.shear[0], self.shear[1]), 0.0)
        else:
            sh = (random.uniform(self.shear[0], self.shear[1]),
                  random.uniform(self.shear[2], self.shear[3]))
        return F.affine(img, angle, (tx, ty), sc, sh, self.interpolation,
                        self.center, self.fill)


class RandomPerspective(BaseTransform):
    """transforms.RandomPerspective."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation, self.fill = interpolation, fill

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return img
        a = F._as_np(img)
        h, w = a.shape[:2]
        d = self.distortion_scale
        half_h, half_w = h // 2, w // 2
        tl = (random.randint(0, int(d * half_w)), random.randint(0, int(d * half_h)))
        tr = (w - 1 - random.randint(0, int(d * half_w)), random.randint(0, int(d * half_h)))
        br = (w - 1 - random.randint(0, int(d * half_w)), h - 1 - random.randint(0, int(d * half_h)))
        bl = (random.randint(0, int(d * half_w)), h - 1 - random.randint(0, int(d * half_h)))
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [tl, tr, br, bl]
        return F.perspective(img, start, end, self.interpolation, self.fill)
