"""Functional image transforms on numpy HWC arrays (or Tensors).

Reference parity: python/paddle/vision/transforms/functional.py (+ the
cv2/pil backends in functional_cv2.py / functional_pil.py). The TPU build
standardises on the numpy backend: images are HWC uint8/float arrays;
ToTensor produces CHW float32 — tensor work happens in the model under
jit, keeping the input pipeline on host (SURVEY.md §7: minimise host↔device
transfers by batching them at the loader boundary).
"""
from __future__ import annotations

import numbers
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np


def _as_np(img):
    from ...tensor_class import Tensor

    if isinstance(img, Tensor):
        return img.numpy()
    return np.asarray(img)


def to_tensor(pic, data_format: str = "CHW"):
    """HWC uint8/float image → float32 Tensor scaled to [0,1] (CHW)."""
    import paddle_tpu as paddle

    a = _as_np(pic)
    if a.ndim == 2:
        a = a[:, :, None]
    if a.dtype == np.uint8:
        a = a.astype(np.float32) / 255.0
    else:
        a = a.astype(np.float32)
    if data_format == "CHW":
        a = np.transpose(a, (2, 0, 1))
    return paddle.to_tensor(a)


def normalize(img, mean, std, data_format: str = "CHW", to_rgb: bool = False):
    from ...tensor_class import Tensor

    is_tensor = isinstance(img, Tensor)
    a = _as_np(img).astype(np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    shape = (-1, 1, 1) if data_format == "CHW" else (1, 1, -1)
    a = (a - mean.reshape(shape)) / std.reshape(shape)
    if is_tensor:
        import paddle_tpu as paddle

        return paddle.to_tensor(a)
    return a


def _size_pair(size) -> Tuple[int, int]:
    if isinstance(size, numbers.Number):
        return int(size), int(size)
    return int(size[0]), int(size[1])


def resize(img, size, interpolation: str = "bilinear"):
    """Resize HWC image. size: int (short side) or (h, w)."""
    a = _as_np(img)
    squeeze = a.ndim == 2
    if squeeze:
        a = a[:, :, None]
    h, w = a.shape[:2]
    if isinstance(size, numbers.Number):
        short = int(size)
        if h <= w:
            nh, nw = short, max(1, int(round(w * short / h)))
        else:
            nh, nw = max(1, int(round(h * short / w))), short
    else:
        nh, nw = _size_pair(size)
    if (nh, nw) == (h, w):
        return a[:, :, 0] if squeeze else a

    dtype = a.dtype
    af = a.astype(np.float32)
    if interpolation in ("nearest",):
        ri = (np.arange(nh) * h / nh).astype(int).clip(0, h - 1)
        ci = (np.arange(nw) * w / nw).astype(int).clip(0, w - 1)
        out = af[ri][:, ci]
    else:  # bilinear (align_corners=False convention)
        ys = (np.arange(nh) + 0.5) * h / nh - 0.5
        xs = (np.arange(nw) + 0.5) * w / nw - 0.5
        y0 = np.floor(ys).clip(0, h - 1).astype(int)
        x0 = np.floor(xs).clip(0, w - 1).astype(int)
        y1 = np.minimum(y0 + 1, h - 1)
        x1 = np.minimum(x0 + 1, w - 1)
        wy = (ys - y0).clip(0, 1)[:, None, None]
        wx = (xs - x0).clip(0, 1)[None, :, None]
        out = (af[y0][:, x0] * (1 - wy) * (1 - wx) + af[y0][:, x1] * (1 - wy) * wx
               + af[y1][:, x0] * wy * (1 - wx) + af[y1][:, x1] * wy * wx)
    if np.issubdtype(dtype, np.integer):
        out = np.round(out).clip(np.iinfo(dtype).min, np.iinfo(dtype).max)
    out = out.astype(dtype)
    return out[:, :, 0] if squeeze else out


def crop(img, top: int, left: int, height: int, width: int):
    a = _as_np(img)
    return a[top:top + height, left:left + width]


def center_crop(img, output_size):
    a = _as_np(img)
    th, tw = _size_pair(output_size)
    h, w = a.shape[:2]
    top = max(0, (h - th) // 2)
    left = max(0, (w - tw) // 2)
    return crop(a, top, left, th, tw)


def hflip(img):
    return _as_np(img)[:, ::-1]


def vflip(img):
    return _as_np(img)[::-1]


def pad(img, padding, fill=0, padding_mode: str = "constant"):
    a = _as_np(img)
    if isinstance(padding, numbers.Number):
        pl = pt = pr = pb = int(padding)
    elif len(padding) == 2:
        pl, pt = int(padding[0]), int(padding[1])
        pr, pb = pl, pt
    else:
        pl, pt, pr, pb = (int(p) for p in padding)
    width = [(pt, pb), (pl, pr)] + [(0, 0)] * (a.ndim - 2)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(a, width, mode=mode, **kw)


def adjust_brightness(img, brightness_factor: float):
    a = _as_np(img)
    dtype = a.dtype
    out = a.astype(np.float32) * brightness_factor
    if np.issubdtype(dtype, np.integer):
        out = out.clip(0, 255)
    return out.astype(dtype)


def adjust_contrast(img, contrast_factor: float):
    a = _as_np(img)
    dtype = a.dtype
    af = a.astype(np.float32)
    mean = af.mean()
    out = (af - mean) * contrast_factor + mean
    if np.issubdtype(dtype, np.integer):
        out = out.clip(0, 255)
    return out.astype(dtype)


def adjust_saturation(img, saturation_factor: float):
    a = _as_np(img)
    dtype = a.dtype
    af = a.astype(np.float32)
    gray = af @ np.array([0.299, 0.587, 0.114], np.float32) if a.ndim == 3 else af
    gray = gray[..., None] if a.ndim == 3 else gray
    out = af * saturation_factor + gray * (1 - saturation_factor)
    if np.issubdtype(dtype, np.integer):
        out = out.clip(0, 255)
    return out.astype(dtype)


def adjust_hue(img, hue_factor: float):
    """Rotate hue by hue_factor (fraction of the full cycle, [-0.5, 0.5])."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    a = _as_np(img)
    dtype = a.dtype
    af = a.astype(np.float32) / (255.0 if np.issubdtype(dtype, np.integer) else 1.0)
    # RGB→HSV hue rotation via the YIQ-ish matrix trick is lossy; do real HSV
    mx, mn = af.max(-1), af.min(-1)
    diff = mx - mn
    r, g, b = af[..., 0], af[..., 1], af[..., 2]
    h = np.zeros_like(mx)
    m = diff > 0
    idx = m & (mx == r)
    h[idx] = ((g - b)[idx] / diff[idx]) % 6
    idx = m & (mx == g)
    h[idx] = (b - r)[idx] / diff[idx] + 2
    idx = m & (mx == b)
    h[idx] = (r - g)[idx] / diff[idx] + 4
    h = (h / 6.0 + hue_factor) % 1.0
    s = np.where(mx > 0, diff / np.maximum(mx, 1e-12), 0.0)
    v = mx
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = i.astype(int) % 6
    out = np.select(
        [i[..., None] == k for k in range(6)],
        [np.stack(c, -1) for c in
         [(v, t, p), (q, v, p), (p, v, t), (p, q, v), (t, p, v), (v, p, q)]])
    if np.issubdtype(dtype, np.integer):
        out = (out * 255.0).round().clip(0, 255)
    return out.astype(dtype)


def to_grayscale(img, num_output_channels: int = 1):
    a = _as_np(img).astype(np.float32)
    gray = a @ np.array([0.299, 0.587, 0.114], np.float32)
    out = np.repeat(gray[..., None], num_output_channels, axis=-1)
    return out.astype(_as_np(img).dtype)


def rotate(img, angle: float, interpolation="nearest", expand=False,
           center=None, fill=0):
    """Rotate by angle degrees (nearest-neighbour grid sample)."""
    a = _as_np(img)
    squeeze = a.ndim == 2
    if squeeze:
        a = a[:, :, None]
    h, w = a.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None else (center[1], center[0])
    rad = np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    # inverse map: output pixel ← input pixel
    sx = cos * (xs - cx) + sin * (ys - cy) + cx
    sy = -sin * (xs - cx) + cos * (ys - cy) + cy
    valid = (sx >= 0) & (sx <= w - 1) & (sy >= 0) & (sy <= h - 1)
    sxi = np.round(sx).clip(0, w - 1).astype(int)
    syi = np.round(sy).clip(0, h - 1).astype(int)
    out = a[syi, sxi]
    out[~valid] = fill
    return out[:, :, 0] if squeeze else out


def _inverse_affine_matrix(center, angle, translate, scale, shear):
    """Inverse of the composed affine map (python/paddle/vision/transforms
    functional.affine): out←in sampling matrix."""
    rot = np.deg2rad(angle)
    sx, sy = [np.deg2rad(s) for s in (shear if isinstance(shear, (list, tuple))
                                      else (shear, 0.0))]
    cx, cy = center
    tx, ty = translate
    # forward: T(center) R(angle) Shear Scale T(-center) + translate
    a = np.cos(rot - sy) / np.cos(sy)
    b = -np.cos(rot - sy) * np.tan(sx) / np.cos(sy) - np.sin(rot)
    c = np.sin(rot - sy) / np.cos(sy)
    d = -np.sin(rot - sy) * np.tan(sx) / np.cos(sy) + np.cos(rot)
    m = np.array([[a * scale, b * scale, 0.0], [c * scale, d * scale, 0.0]])
    m[0, 2] = cx + tx - (m[0, 0] * cx + m[0, 1] * cy)
    m[1, 2] = cy + ty - (m[1, 0] * cx + m[1, 1] * cy)
    # invert the 2x3 affine
    det = m[0, 0] * m[1, 1] - m[0, 1] * m[1, 0]
    inv = np.array([[m[1, 1], -m[0, 1], 0.0], [-m[1, 0], m[0, 0], 0.0]]) / det
    inv[0, 2] = -(inv[0, 0] * m[0, 2] + inv[0, 1] * m[1, 2])
    inv[1, 2] = -(inv[1, 0] * m[0, 2] + inv[1, 1] * m[1, 2])
    return inv


def _sample_inverse(a, sx, sy, fill):
    h, w = a.shape[:2]
    valid = (sx >= 0) & (sx <= w - 1) & (sy >= 0) & (sy <= h - 1)
    sxi = np.round(sx).clip(0, w - 1).astype(int)
    syi = np.round(sy).clip(0, h - 1).astype(int)
    out = a[syi, sxi].copy()
    out[~valid] = fill
    return out


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           center=None, fill=0):
    """F.affine (vision/transforms/functional.py affine): rotation +
    translation + scale + shear, nearest sampling."""
    a = _as_np(img)
    squeeze = a.ndim == 2
    if squeeze:
        a = a[:, :, None]
    h, w = a.shape[:2]
    c = ((w - 1) / 2.0, (h - 1) / 2.0) if center is None else tuple(center)
    inv = _inverse_affine_matrix(c, angle, translate, scale, shear)
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    sx = inv[0, 0] * xs + inv[0, 1] * ys + inv[0, 2]
    sy = inv[1, 0] * xs + inv[1, 1] * ys + inv[1, 2]
    out = _sample_inverse(a, sx, sy, fill)
    return out[:, :, 0] if squeeze else out


def _perspective_coeffs(startpoints, endpoints):
    """Solve the 8-dof homography mapping endpoints → startpoints."""
    A = []
    B = []
    for (xs, ys), (xd, yd) in zip(startpoints, endpoints):
        A.append([xd, yd, 1, 0, 0, 0, -xs * xd, -xs * yd])
        A.append([0, 0, 0, xd, yd, 1, -ys * xd, -ys * yd])
        B.extend([xs, ys])
    coeffs = np.linalg.solve(np.asarray(A, np.float64),
                             np.asarray(B, np.float64))
    return coeffs


def perspective(img, startpoints, endpoints, interpolation="nearest", fill=0):
    """F.perspective: 4-point homography warp (inverse nearest sampling)."""
    a = _as_np(img)
    squeeze = a.ndim == 2
    if squeeze:
        a = a[:, :, None]
    h, w = a.shape[:2]
    co = _perspective_coeffs(startpoints, endpoints)
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    den = co[6] * xs + co[7] * ys + 1.0
    sx = (co[0] * xs + co[1] * ys + co[2]) / den
    sy = (co[3] * xs + co[4] * ys + co[5]) / den
    out = _sample_inverse(a, sx, sy, fill)
    return out[:, :, 0] if squeeze else out


def erase(img, i, j, h, w, v, inplace=False):
    """F.erase (vision/transforms functional.erase): fill img[i:i+h, j:j+w]
    with v. Accepts HWC numpy/PIL or CHW Tensor (the reference's contract)."""
    from ...tensor_class import Tensor, unwrap, wrap

    if isinstance(img, Tensor):
        import jax.numpy as jnp

        a = unwrap(img)
        val = jnp.asarray(unwrap(v) if isinstance(v, Tensor) else v, a.dtype)
        patch = jnp.broadcast_to(val, a[..., i:i + h, j:j + w].shape)
        return wrap(a.at[..., i:i + h, j:j + w].set(patch))
    a = _as_np(img)
    out = a if inplace else a.copy()
    out[i:i + h, j:j + w] = v
    return out
