"""paddle_tpu.Tensor — eager tensor wrapping an immutable jax.Array.

Reference parity: ``phi::DenseTensor`` + Python ``paddle.Tensor``
(paddle/phi/core/dense_tensor.h:1-296, python/paddle/tensor/). TPU-native
design: the payload is a ``jax.Array`` (device-resident, possibly sharded
across a mesh — so DistTensor parity comes for free via jax.sharding), the
wrapper adds Paddle eager semantics: ``stop_gradient``, ``.grad``,
``backward()``, in-place variants, and ~the full method surface, with every
differentiable op recorded on the autograd tape (see autograd/tape.py).

Tensor is registered as a jax pytree node, so it can flow directly through
``jax.jit`` / ``jax.grad`` / ``shard_map``.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .framework import dtype as _dtype_mod
from .autograd import tape as _tape


class Tensor:
    __slots__ = (
        "_array",
        "stop_gradient",
        "_grad",
        "_grad_node",
        "name",
        "persistable",
        "_backward_hooks",
        "_dist_attr",  # (ProcessMesh, placements) for the semi-auto-parallel API
        "__weakref__",
    )

    def __init__(self, data=None, dtype=None, stop_gradient=True, name=None):
        if data is None:
            arr = jnp.zeros((), dtype=dtype or _dtype_mod.default_float_dtype())
        elif isinstance(data, Tensor):
            arr = data._array
        elif isinstance(data, jax.Array):
            arr = data
        else:
            np_arr = np.asarray(data)
            if dtype is None and np_arr.dtype == np.float64:
                np_arr = np_arr.astype(_dtype_mod.default_float_dtype())
            arr = jnp.asarray(np_arr)
        if dtype is not None:
            arr = arr.astype(_dtype_mod.convert_dtype(dtype))
        self._array = arr
        self.stop_gradient = stop_gradient
        self._grad = None
        self._grad_node = None
        self.name = name
        self.persistable = False
        self._backward_hooks = []
        self._dist_attr = None

    # ---- construction helpers -------------------------------------------------
    @classmethod
    def _wrap(cls, arr, stop_gradient=True):
        t = cls.__new__(cls)
        t._array = arr
        t.stop_gradient = stop_gradient
        t._grad = None
        t._grad_node = None
        t.name = None
        t.persistable = False
        t._backward_hooks = []
        t._dist_attr = None
        return t

    # ---- core properties ------------------------------------------------------
    @property
    def shape(self):
        return list(self._array.shape)

    @property
    def ndim(self):
        return self._array.ndim

    ndimension = ndim

    @property
    def dtype(self):
        return self._array.dtype

    @property
    def size(self):
        return int(self._array.size)

    @property
    def place(self):
        devs = getattr(self._array, "devices", None)
        if devs is None:
            return "unknown"
        ds = list(self._array.devices())
        return str(ds[0]) if len(ds) == 1 else f"sharded({len(ds)} devices)"

    @property
    def is_leaf(self):
        return self._grad_node is None

    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, value):
        self._grad = value

    @property
    def T(self):
        from . import ops

        return ops.manipulation.transpose(self, list(range(self.ndim))[::-1])

    @property
    def mT(self):
        from . import ops

        perm = list(range(self.ndim))
        perm[-1], perm[-2] = perm[-2], perm[-1]
        return ops.manipulation.transpose(self, perm)

    @property
    def sharding(self):
        return getattr(self._array, "sharding", None)

    # jax interop: jnp.* accepts Tensor transparently
    def __jax_array__(self):
        return self._array

    # ---- conversion -----------------------------------------------------------
    def numpy(self):
        return np.asarray(jax.device_get(self._array))

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        if self._array.size != 1:
            raise ValueError(
                "The truth value of a Tensor with more than one element is ambiguous"
            )
        return bool(self.item())

    def __index__(self):
        return int(self.item())

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self._array.shape[0]

    def __repr__(self):
        grad_str = "" if self.stop_gradient else ", stop_gradient=False"
        return (
            f"Tensor(shape={self.shape}, dtype={_dtype_mod.dtype_name(self.dtype)}"
            f"{grad_str},\n       {np.array2string(self.numpy(), prefix='       ')})"
        )

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __array__(self, dtype=None):
        arr = self.numpy()
        return arr.astype(dtype) if dtype is not None else arr

    # ---- autograd surface -----------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        _tape.backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def _clear_grad_internal(self):
        self._grad = None

    def _set_grad_internal(self, g):
        self._grad = g

    def _accumulate_grad(self, g_arr):
        if isinstance(g_arr, Tensor):
            g_arr = g_arr._array
        if g_arr is None:
            return
        if getattr(g_arr, "dtype", None) is not None and g_arr.dtype == jax.dtypes.float0:
            return
        if self._grad is None:
            self._grad = Tensor._wrap(jnp.asarray(g_arr))
        else:
            self._grad = Tensor._wrap(self._grad._array + g_arr)

    def register_hook(self, hook):
        self._backward_hooks.append(hook)

        class _Removable:
            def remove(inner):
                try:
                    self._backward_hooks.remove(hook)
                except ValueError:
                    pass

        return _Removable()

    def detach(self):
        t = Tensor._wrap(self._array, stop_gradient=True)
        t.name = self.name
        return t

    def detach_(self):
        self.stop_gradient = True
        self._grad_node = None
        return self

    def clone(self):
        from . import ops

        return ops.registry.apply("clone", lambda x: x + 0, self)

    # ---- data movement / mutation --------------------------------------------
    def to(self, *args, **kwargs):
        """Supports to(dtype), to(device_str), to(device, dtype)."""
        dtype = kwargs.get("dtype")
        device = kwargs.get("device")
        for a in args:
            if isinstance(a, str):
                if a.split(":")[0].lower() in ("cpu", "tpu", "gpu", "cuda", "xpu"):
                    device = a
                else:
                    dtype = a
            elif hasattr(a, "platform") or type(a).__name__ == "Place":
                device = a
            else:
                dtype = a
        out = self
        if dtype is not None:
            out = out.astype(dtype)  # tape-recorded cast
        if device is not None:
            from . import ops
            from .framework import device as _device_mod

            dev = _device_mod._resolve(device)
            out = ops.registry.apply("to_device", lambda x: jax.device_put(x, dev), out)
        if out is self:
            out = Tensor._wrap(self._array, self.stop_gradient)
            out._grad_node = self._grad_node
        return out

    def astype(self, dtype):
        from . import ops

        return ops.math.cast(self, dtype)

    cast = astype

    def cpu(self):
        return self.to("cpu")

    def pin_memory(self):
        return self

    def cuda(self, *a, **k):  # API-compat alias: accelerator place
        return self

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True

    # in-place value assignment (optimizer updates, init)
    def set_value(self, value):
        if isinstance(value, Tensor):
            arr = value._array
        else:
            arr = jnp.asarray(np.asarray(value))
        if tuple(arr.shape) != tuple(self._array.shape):
            raise ValueError(
                f"set_value shape mismatch: {tuple(arr.shape)} vs {tuple(self._array.shape)}"
            )
        self._array = arr.astype(self._array.dtype)  # pdlint: disable=thread-shared-state -- Tensors are step/request-local values: device state is touched only by the engine thread (single-engine-thread design), so instances never cross threads even though the METHODS are reachable from many
        return self

    def copy_(self, other):
        return self.set_value(other)

    def zero_(self):
        self._array = jnp.zeros_like(self._array)
        return self

    def fill_(self, value):
        self._array = jnp.full_like(self._array, value)
        return self

    # ---- indexing -------------------------------------------------------------
    def __getitem__(self, idx):
        from . import ops

        return ops.indexing.getitem(self, idx)

    def __setitem__(self, idx, value):
        from . import ops

        ops.indexing.setitem_(self, idx, value)

    # dim helpers
    def dim(self):
        return self.ndim

    def numel(self):
        return self.size

    def element_size(self):
        return self._array.dtype.itemsize

    def value(self):
        return self


class Parameter(Tensor):
    """Trainable tensor: stop_gradient defaults to False, persistable True.

    Parity: paddle.base.framework.EagerParamBase."""

    def __init__(self, data=None, dtype=None, trainable=True, name=None):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name)
        self.persistable = True

    @classmethod
    def from_tensor(cls, t: Tensor, trainable=True, name=None):
        p = cls.__new__(cls)
        p._array = t._array if isinstance(t, Tensor) else jnp.asarray(t)
        p.stop_gradient = not trainable
        p._grad = None
        p._grad_node = None
        p.name = name
        p.persistable = True
        p._backward_hooks = []
        p._dist_attr = None
        return p

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v):
        self.stop_gradient = not v

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def _tensor_flatten(t: Tensor):
    return (t._array,), (t.stop_gradient, t.name)


def _tensor_unflatten(aux, children):
    t = Tensor._wrap(children[0], stop_gradient=aux[0])
    t.name = aux[1]
    return t


def _param_flatten(p: Parameter):
    return (p._array,), (p.stop_gradient, p.name)


def _param_unflatten(aux, children):
    p = Parameter.__new__(Parameter)
    p._array = children[0]
    p.stop_gradient = aux[0]
    p._grad = None
    p._grad_node = None
    p.name = aux[1]
    p.persistable = True
    p._backward_hooks = []
    return p


jax.tree_util.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)
jax.tree_util.register_pytree_node(Parameter, _param_flatten, _param_unflatten)


def is_tensor(x) -> bool:
    return isinstance(x, Tensor)


def unwrap(x):
    """Tensor → jax.Array (identity on non-tensors)."""
    return x._array if isinstance(x, Tensor) else x


def wrap(arr, stop_gradient=True):
    return Tensor._wrap(arr, stop_gradient)
