"""paddle.signal parity (python/paddle/signal.py): stft / istft frame ops."""
from __future__ import annotations

import jax.numpy as jnp

from .ops.registry import apply
from .tensor_class import unwrap


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice overlapping frames along ``axis`` (signal.py frame parity)."""

    def fn(a):
        n = a.shape[axis]
        num = 1 + (n - frame_length) // hop_length
        starts = jnp.arange(num) * hop_length
        idx = starts[:, None] + jnp.arange(frame_length)[None, :]
        moved = jnp.moveaxis(a, axis, -1)
        framed = moved[..., idx]  # [..., num, frame_length]
        if axis in (-1, a.ndim - 1):
            return jnp.swapaxes(framed, -1, -2)  # [..., frame_length, num]
        return jnp.moveaxis(jnp.swapaxes(framed, -1, -2), -1, axis)

    return apply("frame", fn, x)


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame (signal.py overlap_add parity); x[..., fl, frames]."""

    def fn(a):
        moved = jnp.moveaxis(a, axis, -1) if axis not in (-1, a.ndim - 1) else a
        fl, num = moved.shape[-2], moved.shape[-1]
        out_len = fl + hop_length * (num - 1)
        out = jnp.zeros(moved.shape[:-2] + (out_len,), moved.dtype)
        for i in range(num):  # static python loop — num is trace-static
            out = out.at[..., i * hop_length:i * hop_length + fl].add(
                moved[..., i])
        return out

    return apply("overlap_add", fn, x)


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """signal.py stft parity: [B, N] (or [N]) → complex spectrogram
    [B, n_fft//2+1, frames] (onesided)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    def fn(a, *w):
        sig = a[None] if a.ndim == 1 else a
        if center:
            pad = n_fft // 2
            sig = jnp.pad(sig, [(0, 0), (pad, pad)], mode=pad_mode)
        win = w[0] if w else jnp.ones(win_length, sig.dtype)
        if win_length < n_fft:
            lpad = (n_fft - win_length) // 2
            win = jnp.pad(win, (lpad, n_fft - win_length - lpad))
        n = sig.shape[-1]
        num = 1 + (n - n_fft) // hop_length
        starts = jnp.arange(num) * hop_length
        frames = sig[:, starts[:, None] + jnp.arange(n_fft)[None, :]]  # [B,F,n_fft]
        frames = frames * win[None, None, :]
        spec = (jnp.fft.rfft(frames, axis=-1) if onesided
                else jnp.fft.fft(frames, axis=-1))
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        out = jnp.swapaxes(spec, -1, -2)  # [B, freq, frames]
        return out[0] if a.ndim == 1 else out

    args = (x,) if window is None else (x, window)
    return apply("stft", fn, *args)


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """signal.py istft parity (inverse via overlap-add with window-square
    normalization)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    def fn(a, *w):
        spec = a[None] if a.ndim == 2 else a  # [B, freq, frames]
        spec = jnp.swapaxes(spec, -1, -2)     # [B, frames, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        frames = (jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided
                  else jnp.fft.ifft(spec, axis=-1).real)
        win = w[0] if w else jnp.ones(win_length, frames.dtype)
        if win_length < n_fft:
            lpad = (n_fft - win_length) // 2
            win = jnp.pad(win, (lpad, n_fft - win_length - lpad))
        frames = frames * win[None, None, :]
        num = frames.shape[1]
        out_len = n_fft + hop_length * (num - 1)
        out = jnp.zeros(frames.shape[:1] + (out_len,), frames.dtype)
        wsum = jnp.zeros(out_len, frames.dtype)
        for i in range(num):
            sl = slice(i * hop_length, i * hop_length + n_fft)
            out = out.at[:, sl].add(frames[:, i])
            wsum = wsum.at[sl].add(win * win)
        out = out / jnp.maximum(wsum, 1e-10)[None]
        if center:
            pad = n_fft // 2
            out = out[:, pad:out_len - pad]
        if length is not None:
            out = out[:, :length]
        return out[0] if a.ndim == 2 else out

    args = (x,) if window is None else (x, window)
    return apply("istft", fn, *args)
