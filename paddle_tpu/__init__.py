"""paddle_tpu — a TPU-native deep-learning framework with the capability
surface of the reference (GerHobbelt/Paddle, PaddlePaddle ~3.0-dev), built
from scratch on JAX/XLA/Pallas/pjit.

See /root/repo/SURVEY.md for the reference structural analysis and the
architecture mapping this package implements.
"""
from __future__ import annotations

# dtypes first (no jax-heavy imports)
from .framework.dtype import (
    bool_ as bool,  # noqa: A001 - paddle exports `paddle.bool`
    uint8,
    int8,
    int16,
    int32,
    int64,
    float16,
    bfloat16,
    float32,
    float64,
    complex64,
    complex128,
    float8_e4m3fn,
    float8_e5m2,
    set_default_dtype,
    get_default_dtype,
)

from .tensor_class import Tensor, Parameter, is_tensor
from .autograd import no_grad, enable_grad, set_grad_enabled, grad
from .autograd.pylayer import PyLayer, PyLayerContext
from .framework.random import seed, get_rng_state, set_rng_state
from . import device
from .framework.device import (
    set_device,
    get_device,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    CPUPlace,
    TPUPlace,
    CUDAPlace,
    CUDAPinnedPlace,
)

from . import ops
from .ops import registry as _registry

# ---- re-export the functional surface at top level (paddle.* parity) --------
from .ops.creation import (
    to_tensor, zeros, ones, full, empty, zeros_like, ones_like, full_like,
    empty_like, arange, linspace, logspace, eye, diag, diagflat, tril, triu,
    tril_indices, triu_indices, meshgrid, clone, assign, rand, randn, randint,
    randint_like, uniform, normal, standard_normal, randperm, bernoulli,
    poisson, multinomial, complex, polar, create_parameter, create_tensor,
)
from .ops.math import (
    abs, acos, acosh, asin, asinh, atan, atanh, ceil, cos, cosh, digamma, erf,
    erfinv, exp, expm1, floor, lgamma, log, log10, log1p, log2, neg,
    reciprocal, round, rsqrt, sigmoid, sign, sin, sinh, sqrt, square, tan,
    tanh, trunc, frac, angle, conj, real, imag, deg2rad, rad2deg, isnan,
    isinf, isfinite, logical_not, bitwise_not, add, subtract, multiply,
    divide, floor_divide, remainder, mod, floor_mod, pow, maximum, minimum,
    fmax, fmin, atan2, hypot, logaddexp, nextafter, copysign, heaviside, gcd,
    lcm, ldexp, bitwise_and, bitwise_or, bitwise_xor, bitwise_left_shift,
    bitwise_right_shift, i0, i1, divide_no_nan, scale,
    cast, clip, lerp, stanh, multiplex, addmm, inner, outer, logit,
    polygamma, nan_to_num, trapezoid, diff, sum, mean, prod, max, min, amax,
    amin, any, all, nansum, nanmean, median, nanmedian, std, var, logsumexp,
    logcumsumexp, cumsum, cumprod, cummax, cummin, count_nonzero, argmax,
    argmin, argsort, sort, topk, kthvalue, mode, equal, not_equal,
    greater_than, greater_equal, less_than, less_equal, logical_and,
    logical_or, logical_xor, allclose, isclose, equal_all, where,
    masked_fill, isneginf, isposinf, isreal,
)
from .ops.manipulation import (
    reshape, flatten, squeeze, unsqueeze, transpose, moveaxis, concat, stack,
    split, chunk, unbind, unstack, tile, repeat_interleave, expand, expand_as,
    broadcast_to, broadcast_tensors, flip, rot90, roll, slice, strided_slice,
    crop, gather, gather_nd, take_along_axis, put_along_axis, scatter,
    scatter_nd_add, scatter_nd, index_select, index_sample, index_add,
    index_put, masked_select, take, unique, unique_consecutive, nonzero,
    searchsorted, bucketize, as_complex, as_real, atleast_1d, atleast_2d,
    atleast_3d, tensordot, tolist, numel, shard_index, swapaxes, pad,
    tensor_split, hsplit, vsplit, dsplit, view,
)
from .ops.linalg import (
    matmul, mm, dot, bmm, mv, t, cross, dist, norm, trace, diagonal, kron,
    einsum, histogram, bincount,
)
from . import linalg
from .autograd import backward as _backward_fn

__version__ = "0.1.0"


def flops(*args, **kwargs):  # paddle.flops parity — model profiler hook
    from .hapi.summary import flops as _flops

    return _flops(*args, **kwargs)


def in_dynamic_mode() -> bool:
    """Eager-vs-traced probe (paddle.in_dynamic_mode parity). Returns False
    inside jit-traced code."""
    import jax

    try:
        return jax.core.trace_state_clean()
    except Exception:  # pragma: no cover - jax internal API drift  # pdlint: disable=silent-exception -- probe of a jax-internal API: outside a trace the True (eager) answer is correct, and there is nothing to log per-call on this hot predicate
        return True


def get_flags(name=None):
    from .utils import flags as _flags

    return _flags.get_flags(name)


def set_flags(d):
    from .utils import flags as _flags

    return _flags.set_flags(d)


def save(obj, path, **kwargs):
    from .framework_io import save as _save

    return _save(obj, path, **kwargs)


def load(path, **kwargs):
    from .framework_io import load as _load

    return _load(path, **kwargs)


def summary(net, input_size=None, dtypes=None, input=None):
    from .hapi.summary import summary as _summary

    return _summary(net, input_size, dtypes, input)


def iinfo(dtype):
    import numpy as np

    from .framework.dtype import convert_dtype

    return np.iinfo(convert_dtype(dtype))


def finfo(dtype):
    import jax.numpy as jnp

    from .framework.dtype import convert_dtype

    return jnp.finfo(convert_dtype(dtype))


def is_grad_enabled():
    from .autograd.tape import grad_enabled

    return grad_enabled()


# subpackages (imported lazily in __getattr__ to keep import light and avoid
# cycles: nn imports paddle_tpu at module load)
_LAZY_SUBMODULES = (
    "nn",
    "observability",
    "optimizer",
    "amp",
    "io",
    "jit",
    "distributed",
    "vision",
    "metric",
    "hapi",
    "profiler",
    "incubate",
    "sparse",
    "static",
    "utils",
    "text",
    "audio",
    "onnx",
    "quantization",
    "autograd",
    "distribution",
    "generation",
    "inference",
    "linalg",
    "fft",
    "signal",
    "geometric",
    "strings",
    "regularizer",
    "callbacks",
    "sysconfig",
    "hub",
    "version",
    "tensorrt",
    "peft",
)



# ---- schema-generated op tail + retrofit registration -------------------------
from .ops import schema as _schema

histogramdd = _schema.generated("histogramdd")
renorm = _schema.generated("renorm")
reverse = _schema.generated("reverse")
increment = _schema.generated("increment")
as_strided = _schema.generated("as_strided")
view_as = _schema.generated("view_as")
vander = _schema.generated("vander")
quantile = _schema.generated("quantile")
nanquantile = _schema.generated("nanquantile")
index_fill = _schema.generated("index_fill")
fill_diagonal = _schema.generated("fill_diagonal")

from .tensor_array import (  # noqa: E402
    TensorArray, create_array, array_length, array_read, array_write)
gammaln = _schema.generated("gammaln")
gammainc = _schema.generated("gammainc")
gammaincc = _schema.generated("gammaincc")
i0e = _schema.generated("i0e")
i1e = _schema.generated("i1e")

# round-3 tensor-surface tail (tensor_method_func parity)
sinc = _schema.generated("sinc")
multigammaln = _schema.generated("multigammaln")
isin = _schema.generated("isin")
sgn = _schema.generated("sgn")
frexp = _schema.generated("frexp")
signbit = _schema.generated("signbit")
cumulative_trapezoid = _schema.generated("cumulative_trapezoid")
reduce_as = _schema.generated("reduce_as")
add_n = _schema.generated("add_n")
histogram_bin_edges = _schema.generated("histogram_bin_edges")
block_diag = _schema.generated("block_diag")
slice_scatter = _schema.generated("slice_scatter")
select_scatter = _schema.generated("select_scatter")
diagonal_scatter = _schema.generated("diagonal_scatter")
masked_scatter = _schema.generated("masked_scatter")
unflatten = _schema.generated("unflatten")
cdist = _schema.generated("cdist")
cholesky_inverse = _schema.generated("cholesky_inverse")
top_p_sampling = _schema.generated("top_p_sampling")
bitwise_invert = ops.math.bitwise_not
less = ops.math.less_than


def broadcast_shape(x_shape, y_shape):
    """paddle.broadcast_shape — pure shape computation (InferMeta analog)."""
    import numpy as _np

    return list(_np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def is_empty(x):
    """paddle.is_empty: True iff the tensor has zero elements."""
    import jax.numpy as _jnp

    from .tensor_class import unwrap as _unwrap, wrap as _wrap

    return _wrap(_jnp.asarray(_unwrap(x).size == 0))


def rank(x):
    """paddle.rank: 0-D int tensor holding the rank (ndim) of x."""
    import jax.numpy as _jnp

    from .tensor_class import unwrap as _unwrap, wrap as _wrap

    return _wrap(_jnp.asarray(_unwrap(x).ndim))


def is_complex(x):
    from .framework.dtype import is_complex_dtype
    from .tensor_class import unwrap as _unwrap

    return is_complex_dtype(_unwrap(x).dtype)


def is_floating_point(x):
    from .framework.dtype import is_floating_point_dtype
    from .tensor_class import unwrap as _unwrap

    return is_floating_point_dtype(_unwrap(x).dtype)


def is_integer(x):
    from .framework.dtype import is_integer_dtype
    from .tensor_class import unwrap as _unwrap

    return is_integer_dtype(_unwrap(x).dtype)


# ---- top-level __all__ tail (reference python/paddle/__init__.py parity) -----
def enable_static():
    from . import static as _static

    return _static.enable_static()


def disable_static():
    from . import static as _static

    return _static.disable_static()


from .ops.manipulation import (  # noqa: E402
    hstack, vstack, dstack, column_stack, row_stack, cartesian_prod,
    combinations, shape)
from .ops.creation import binomial, standard_gamma, log_normal  # noqa: E402
from .nn.initializer_core import ParamAttr  # noqa: E402
from .linalg import matrix_transpose  # noqa: E402

pdist = _schema.generated("pdist")
positive = _schema.generated("positive")
unfold = _schema.generated("unfold_window")
diag_embed = linalg.diag_embed

import numpy as _np  # noqa: E402

inf = float("inf")
newaxis = None
dtype = _np.dtype          # paddle.dtype: Tensor.dtype instances are np dtypes


class _SpecialDType:
    """Non-numeric VarType sentinel (paddle.pstring / paddle.raw parity —
    XLA has no such dtypes; these exist for isinstance/label use only)."""

    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name

    def __repr__(self):
        return f"paddle.{self.name}"


pstring = _SpecialDType("pstring")
raw = _SpecialDType("raw")


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """paddle.set_printoptions → numpy printoptions (our repr prints via
    numpy)."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def batch(reader, batch_size, drop_last=False):
    """paddle.batch (python/paddle/batch.py): batch a sample generator."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched


def check_shape(shape, op_name="", expected_shape_type=(list, tuple),
                expected_element_type=(int,), expected_tensor_dtype=("int32", "int64")):
    """paddle.check_shape (base/data_feeder.py): eager mode returns
    immediately in the reference too — shape errors surface from jnp."""
    return None


def disable_signal_handler():
    """paddle.disable_signal_handler: the reference uninstalls its C++
    fatal-signal dumpers; this runtime installs none, so there is nothing
    to disable (documented no-op)."""
    return None


class LazyGuard:
    """paddle.LazyGuard parity. Under JAX, parameter arrays are committed
    lazily by async dispatch and cost no device memory until first use, so
    eager initialization is already 'lazy' in the sense this guard provides
    in the reference (delayed allocation); the context manager is kept for
    API compatibility."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def get_cuda_rng_state():
    """CUDA-API-name parity: maps to the single framework RNG state."""
    return get_rng_state()


def set_cuda_rng_state(state):
    return set_rng_state(state)


def to_dlpack(x):
    """paddle.utils.dlpack surface: the device array as a dlpack-capable
    object (modern __dlpack__ protocol — consumers call __dlpack__
    themselves; the legacy one-shot capsule is deprecated in jax)."""
    from .tensor_class import unwrap as _unwrap

    return _unwrap(x)


def from_dlpack(ext):
    import jax.numpy as _jnp2

    from .tensor_class import wrap as _wrap

    return _wrap(_jnp2.from_dlpack(ext))


def log_normal_(x, mean=1.0, std=2.0, name=None):
    return x.log_normal_(mean, std)


def _install_inplace_functions():
    """Module-level in-place forms (paddle.log_(x) etc. — the reference
    exports every Tensor inplace method as a function too)."""
    g = globals()
    names = [
        "abs", "acos", "addmm", "asin", "atan", "bernoulli", "bitwise_and",
        "bitwise_invert", "bitwise_left_shift", "bitwise_not", "bitwise_or",
        "bitwise_right_shift", "bitwise_xor", "cast", "cauchy", "ceil",
        "clip", "copysign", "cos", "cosh", "cumprod", "cumsum", "digamma",
        "divide", "equal", "erf", "erfinv", "exp", "expm1", "flatten",
        "floor", "floor_divide", "floor_mod", "frac", "gammainc",
        "gammaincc", "gammaln", "gcd", "geometric", "greater_equal",
        "greater_than", "hypot", "i0", "index_add", "index_fill",
        "index_put", "lcm",
        "ldexp", "lerp", "less", "less_equal", "less_than", "lgamma", "log",
        "log10", "log1p", "log2", "logical_and", "logical_not", "logical_or",
        "logical_xor", "logit", "masked_fill", "masked_scatter", "mod",
        "multigammaln", "multiply", "nan_to_num", "neg", "normal",
        "not_equal", "polygamma", "pow", "put_along_axis", "reciprocal",
        "remainder", "renorm", "reshape", "round", "rsqrt", "scale",
        "scatter", "sigmoid", "sign", "sin", "sinc", "sinh", "sqrt",
        "square", "squeeze", "subtract", "t", "tan", "tanh", "transpose",
        "tril", "triu", "trunc", "uniform", "unsqueeze", "where", "add",
        "exponential",
    ]
    for name in names:
        meth = name + "_"
        if not hasattr(Tensor, meth):
            continue

        def fn(x, *a, _m=meth, **k):
            return getattr(x, _m)(*a, **k)

        fn.__name__ = meth
        fn.__doc__ = (f"In-place function form of Tensor.{meth} "
                      "(reference exports both)")
        g.setdefault(meth, fn)


_install_inplace_functions()


def _finalize_schema():
    """Register every public-op retrofit in the registry (ops.yaml parity:
    the registry enumerates the full kernel surface). Resolution of each
    public path is lazy, so nn/linalg/fft/signal stay lazily imported."""
    _schema.register_retrofits()


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name == "Model":
        from .hapi.model import Model

        return Model
    if name == "DataParallel":
        from .distributed.parallel import DataParallel

        return DataParallel
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


def __dir__():
    # lazy names must be introspectable (dir()/doc tooling/surface diffs),
    # not just gettable
    return sorted(set(globals()) | set(_LAZY_SUBMODULES)
                  | {"Model", "DataParallel"})


_finalize_schema()
