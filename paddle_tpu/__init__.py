"""paddle_tpu — a TPU-native deep-learning framework with the capability
surface of the reference (GerHobbelt/Paddle, PaddlePaddle ~3.0-dev), built
from scratch on JAX/XLA/Pallas/pjit.

See /root/repo/SURVEY.md for the reference structural analysis and the
architecture mapping this package implements.
"""
from __future__ import annotations

# dtypes first (no jax-heavy imports)
from .framework.dtype import (
    bool_ as bool,  # noqa: A001 - paddle exports `paddle.bool`
    uint8,
    int8,
    int16,
    int32,
    int64,
    float16,
    bfloat16,
    float32,
    float64,
    complex64,
    complex128,
    float8_e4m3fn,
    float8_e5m2,
    set_default_dtype,
    get_default_dtype,
)

from .tensor_class import Tensor, Parameter, is_tensor
from .autograd import no_grad, enable_grad, set_grad_enabled, grad
from .autograd.pylayer import PyLayer, PyLayerContext
from .framework.random import seed, get_rng_state, set_rng_state
from .framework import device
from .framework.device import (
    set_device,
    get_device,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    CPUPlace,
    TPUPlace,
    CUDAPlace,
)

from . import ops
from .ops import registry as _registry

# ---- re-export the functional surface at top level (paddle.* parity) --------
from .ops.creation import (
    to_tensor, zeros, ones, full, empty, zeros_like, ones_like, full_like,
    empty_like, arange, linspace, logspace, eye, diag, diagflat, tril, triu,
    tril_indices, triu_indices, meshgrid, clone, assign, rand, randn, randint,
    randint_like, uniform, normal, standard_normal, randperm, bernoulli,
    poisson, multinomial, complex, polar,
)
from .ops.math import (
    abs, acos, acosh, asin, asinh, atan, atanh, ceil, cos, cosh, digamma, erf,
    erfinv, exp, expm1, floor, lgamma, log, log10, log1p, log2, neg,
    reciprocal, round, rsqrt, sigmoid, sign, sin, sinh, sqrt, square, tan,
    tanh, trunc, frac, angle, conj, real, imag, deg2rad, rad2deg, isnan,
    isinf, isfinite, logical_not, bitwise_not, add, subtract, multiply,
    divide, floor_divide, remainder, mod, floor_mod, pow, maximum, minimum,
    fmax, fmin, atan2, hypot, logaddexp, nextafter, copysign, heaviside, gcd,
    lcm, ldexp, bitwise_and, bitwise_or, bitwise_xor, bitwise_left_shift,
    bitwise_right_shift, i0, i1, divide_no_nan, scale,
    cast, clip, lerp, stanh, multiplex, addmm, inner, outer, logit,
    polygamma, nan_to_num, trapezoid, diff, sum, mean, prod, max, min, amax,
    amin, any, all, nansum, nanmean, median, nanmedian, std, var, logsumexp,
    logcumsumexp, cumsum, cumprod, cummax, cummin, count_nonzero, argmax,
    argmin, argsort, sort, topk, kthvalue, mode, equal, not_equal,
    greater_than, greater_equal, less_than, less_equal, logical_and,
    logical_or, logical_xor, allclose, isclose, equal_all, where,
    masked_fill, isneginf, isposinf, isreal,
)
from .ops.manipulation import (
    reshape, flatten, squeeze, unsqueeze, transpose, moveaxis, concat, stack,
    split, chunk, unbind, unstack, tile, repeat_interleave, expand, expand_as,
    broadcast_to, broadcast_tensors, flip, rot90, roll, slice, strided_slice,
    crop, gather, gather_nd, take_along_axis, put_along_axis, scatter,
    scatter_nd_add, scatter_nd, index_select, index_sample, index_add,
    index_put, masked_select, take, unique, unique_consecutive, nonzero,
    searchsorted, bucketize, as_complex, as_real, atleast_1d, atleast_2d,
    atleast_3d, tensordot, tolist, numel, shard_index, swapaxes, pad,
)
from .ops.linalg import (
    matmul, mm, dot, bmm, mv, t, cross, dist, norm, trace, diagonal, kron,
    einsum, histogram, bincount,
)
from .ops import linalg
from .autograd import backward as _backward_fn

__version__ = "0.1.0"


def flops(*args, **kwargs):  # paddle.flops parity — model profiler hook
    from .hapi.summary import flops as _flops

    return _flops(*args, **kwargs)


def in_dynamic_mode() -> bool:
    """Eager-vs-traced probe (paddle.in_dynamic_mode parity). Returns False
    inside jit-traced code."""
    import jax

    try:
        return jax.core.trace_state_clean()
    except Exception:  # pragma: no cover - jax internal API drift
        return True


def get_flags(name=None):
    from .utils import flags as _flags

    return _flags.get_flags(name)


def set_flags(d):
    from .utils import flags as _flags

    return _flags.set_flags(d)


def save(obj, path, **kwargs):
    from .framework_io import save as _save

    return _save(obj, path, **kwargs)


def load(path, **kwargs):
    from .framework_io import load as _load

    return _load(path, **kwargs)


def summary(net, input_size=None, dtypes=None, input=None):
    from .hapi.summary import summary as _summary

    return _summary(net, input_size, dtypes, input)


def iinfo(dtype):
    import numpy as np

    from .framework.dtype import convert_dtype

    return np.iinfo(convert_dtype(dtype))


def finfo(dtype):
    import jax.numpy as jnp

    from .framework.dtype import convert_dtype

    return jnp.finfo(convert_dtype(dtype))


def is_grad_enabled():
    from .autograd.tape import grad_enabled

    return grad_enabled()


# subpackages (imported lazily in __getattr__ to keep import light and avoid
# cycles: nn imports paddle_tpu at module load)
_LAZY_SUBMODULES = (
    "nn",
    "optimizer",
    "amp",
    "io",
    "jit",
    "distributed",
    "vision",
    "metric",
    "hapi",
    "profiler",
    "incubate",
    "sparse",
    "static",
    "utils",
    "text",
    "audio",
    "onnx",
    "quantization",
    "autograd",
    "distribution",
    "generation",
    "inference",
    "linalg",
    "fft",
    "signal",
    "geometric",
)



# ---- schema-generated op tail + retrofit registration -------------------------
from .ops import schema as _schema

histogramdd = _schema.generated("histogramdd")
renorm = _schema.generated("renorm")
reverse = _schema.generated("reverse")
increment = _schema.generated("increment")
as_strided = _schema.generated("as_strided")
view_as = _schema.generated("view_as")
vander = _schema.generated("vander")
quantile = _schema.generated("quantile")
nanquantile = _schema.generated("nanquantile")
index_fill = _schema.generated("index_fill")
fill_diagonal = _schema.generated("fill_diagonal")

from .tensor_array import (  # noqa: E402
    TensorArray, create_array, array_length, array_read, array_write)
gammaln = _schema.generated("gammaln")
gammainc = _schema.generated("gammainc")
gammaincc = _schema.generated("gammaincc")
i0e = _schema.generated("i0e")
i1e = _schema.generated("i1e")


def _finalize_schema():
    """Register every public-op retrofit in the registry (ops.yaml parity:
    the registry enumerates the full kernel surface). Resolution of each
    public path is lazy, so nn/linalg/fft/signal stay lazily imported."""
    _schema.register_retrofits()


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name == "Model":
        from .hapi.model import Model

        return Model
    if name == "DataParallel":
        from .distributed.parallel import DataParallel

        return DataParallel
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


_finalize_schema()
