"""Lifecycle analysis: CFG-based must-release checking (``leak-path``).

The serving stack balances acquire/release pairs by convention — a pool
lease claimed per placement and released per attempt, a tracer span
ended on every outcome, a KV bundle exported from one engine and
admitted into another, a file or socket closed after use. Every ceiling
in the roadmap's next arc (multi-tenant adapter handles, quantized KV
pages, the cluster cache tier, autoscaler-driven drain) multiplies
those pairs, and a single exception-path miss is permanent capacity
loss on a fleet that sizes itself. The reference C++ made this class
structurally impossible with scope guards; RAII-less Python needs a
checker instead.

Three pieces:

- ``analysis/cfg.py`` (one level up, reusable): statement-granular
  control-flow graphs with branch/loop/try/finally/with/raise edges;
- ``resources.py``: the catalog — which calls acquire which resource,
  which calls/methods release it, and which hand ownership elsewhere
  (transfer is NOT a leak: returning a bundle, sealing it into a
  channel, parking a lease on ``self``);
- ``dataflow.py``: the intraprocedural must-release walk over the CFG
  (with one-level summaries for same-module helpers), producing
  ``leak-path`` findings that name the resource, the acquire site, and
  the concrete escape edge.

Registered as the ``leak-path`` rule (``lifecycle/rules.py``), gated
behind ``pdlint --lifecycle`` exactly like ``--graph``/``--threads``,
and held green by tests/test_lifecycle_analysis.py. The catalog rows
live in docs/ANALYSIS.md ("Lifecycle analysis").
"""
from .resources import CATALOG, ResourceSpec  # noqa: F401
from .dataflow import check_module  # noqa: F401
