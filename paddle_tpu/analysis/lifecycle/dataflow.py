"""The must-release dataflow: walk the CFG from each acquire site.

Intraprocedural, per function, with one-level summaries for same-module
helpers (the engines route slot/lease teardown through helpers, and a
``self._teardown(lease)`` that releases its parameter must count).

The walk is deliberately binary: from the acquire node, explore every
CFG path while the resource is HELD; a statement that releases,
transfers, aliases, or rebinds the resource ENDS its path. Reaching the
function's ``exit`` or ``raise`` boundary while still HELD is a leak,
reported with the concrete escape edge (the statement whose raise edge
left the function, or the return that skipped the release). Reaching
the acquire node again while HELD is the loop re-acquire leak.

Cheap None-narrowing keeps the common guard clean: on a branch testing
``v is None`` / ``not v`` the resource is vacuously absent down the
None edge, so ``if lease is None: return`` never reports. The same
narrowing covers the -1 index-sentinel convention (``if slot < 0:
return`` after ``_alloc_slot``). Everything
fancier (aliases, tuple unpacking, cross-function flows) conservatively
ends tracking — for a gate, silence beats a false leak.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from .. import cfg as _cfg
from .resources import CATALOG, CONTAINER_STORES, NORAISE, ResourceSpec, match

__all__ = ["check_module", "LeakReport"]

_MAX_TEXT = 64


class LeakReport:
    """One leak: everything the rule needs to render a Finding."""

    __slots__ = ("line", "resource", "var", "acquire_text", "escape")

    def __init__(self, line, resource, var, acquire_text, escape):
        self.line = line
        self.resource = resource
        self.var = var
        self.acquire_text = acquire_text
        self.escape = escape

    @property
    def message(self) -> str:
        who = f"{self.resource} '{self.var}'" if self.var else self.resource
        return (f"{who} acquired via `{self.acquire_text}` "
                f"{self.escape}")


def _short(node: ast.AST) -> str:
    try:
        text = ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on stdlib ast
        text = type(node).__name__
    text = " ".join(text.split())
    return text if len(text) <= _MAX_TEXT else text[:_MAX_TEXT - 1] + "…"


def _names(node: ast.AST) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _is_name(node, v: str) -> bool:
    return isinstance(node, ast.Name) and node.id == v


def _arg_names(call: ast.Call) -> Iterable[str]:
    for a in call.args:
        if isinstance(a, ast.Starred):
            a = a.value
        if isinstance(a, ast.Name):
            yield a.id
    for kw in call.keywords:
        if isinstance(kw.value, ast.Name):
            yield kw.value.id


# ---- one-level helper summaries --------------------------------------------

def module_summaries(ctx) -> Dict[str, Tuple[ast.AST, Dict]]:
    """``{helper_name: (func_def, {(spec_name, param): effect})}`` —
    which parameters each module-local function releases or transfers,
    judged ONLY by direct catalog matches in its body (one level: a
    helper of a helper does not count)."""
    out: Dict[str, Tuple[ast.AST, Dict]] = {}
    for _qual, func in _cfg.function_nodes(ctx.tree):
        params = {a.arg for a in func.args.args} - {"self", "cls"}
        if not params:
            continue
        effects: Dict[Tuple[str, str], str] = {}
        for node in ast.walk(func):
            if isinstance(node, ast.Call):
                resolved = ctx.resolve_call(node.func)
                args = [a for a in _arg_names(node) if a in params]
                recv = (node.func.value.id
                        if isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id in params else None)
                for spec in CATALOG:
                    if args and any(match(resolved, p)
                                    for p in spec.release_arg):
                        for a in args:
                            effects[(spec.name, a)] = "release"
                    if recv and isinstance(node.func, ast.Attribute) \
                            and node.func.attr in spec.release_methods:
                        effects.setdefault((spec.name, recv), "release")
                    if args and any(match(resolved, p)
                                    for p in spec.transfer_arg):
                        for a in args:
                            effects.setdefault((spec.name, a), "transfer")
            elif isinstance(node, ast.Assign):
                stored = _names(node.value)
                if any(isinstance(t, (ast.Attribute, ast.Subscript))
                       for t in node.targets):
                    for p in params & stored:
                        for spec in CATALOG:
                            effects.setdefault((spec.name, p), "transfer")
        if effects:
            out[func.name] = (func, effects)
    return out


def _summary_effect(call: ast.Call, resolved: str, v: str, spec,
                    summaries) -> Optional[str]:
    helper = resolved.rsplit(".", 1)[-1]
    entry = summaries.get(helper)
    if entry is None:
        return None
    func, effects = entry
    params = [a.arg for a in func.args.args]
    offset = 1 if (params[:1] in (["self"], ["cls"])
                   and isinstance(call.func, ast.Attribute)) else 0
    param = None
    for i, a in enumerate(call.args):
        if _is_name(a, v) and i + offset < len(params):
            param = params[i + offset]
            break
    if param is None:
        for kw in call.keywords:
            if _is_name(kw.value, v) and kw.arg:
                param = kw.arg
                break
    if param is None:
        return None
    return effects.get((spec.name, param))


# ---- per-statement effect on one held resource -----------------------------

def _call_effect(exprs: List[ast.AST], v: str, spec: ResourceSpec, ctx,
                 summaries) -> Optional[str]:
    for root in exprs:
        for node in _cfg._eager_nodes(root):
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.resolve_call(node.func)
            has_v = any(a == v for a in _arg_names(node))
            if has_v and any(match(resolved, p)
                             for p in spec.release_arg):
                return "release"
            if isinstance(node.func, ast.Attribute) \
                    and _is_name(node.func.value, v) \
                    and node.func.attr in spec.release_methods:
                return "release"
            if has_v and any(match(resolved, p)
                             for p in spec.transfer_arg):
                return "transfer"
            if has_v and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in CONTAINER_STORES:
                return "transfer"
            if has_v:
                eff = _summary_effect(node, resolved, v, spec, summaries)
                if eff:
                    return eff
    return None


def _effect(node: _cfg.CFGNode, v: str, spec: ResourceSpec, ctx,
            summaries) -> Optional[str]:
    """What this CFG node does to held resource ``v``: ``release`` /
    ``transfer`` / ``stop`` (alias, rebind, del — tracking ends
    conservatively) / None (no effect)."""
    stmt = node.stmt
    kind = node.kind
    if kind == "branch":
        return _call_effect([stmt.test], v, spec, ctx, summaries)
    if kind == "loop":
        it = stmt.iter if isinstance(stmt, (ast.For, ast.AsyncFor)) \
            else stmt.test
        if isinstance(stmt, (ast.For, ast.AsyncFor)) \
                and v in _names(stmt.target):
            return "stop"
        return _call_effect([it], v, spec, ctx, summaries)
    if kind == "with":
        exprs = [item.context_expr for item in stmt.items]
        eff = _call_effect(exprs, v, spec, ctx, summaries)
        if eff:
            return eff
        if any(v in _names(item.context_expr) for item in stmt.items):
            # ``with closing(v):`` / ``with v:`` — managed from here
            return "transfer"
        if any(item.optional_vars is not None
               and v in _names(item.optional_vars)
               for item in stmt.items):
            return "stop"
        return None
    if kind == "handler":
        return "stop" if stmt.name == v else None
    if kind != "stmt":
        return None
    # ---- plain statements ------------------------------------------------
    if isinstance(stmt, ast.Return):
        if stmt.value is not None and v in _names(stmt.value):
            return "transfer"
        return _call_effect([stmt.value], v, spec, ctx, summaries) \
            if stmt.value is not None else None
    if isinstance(stmt, ast.Delete):
        if any(v in _names(t) for t in stmt.targets):
            return "stop"
        return None
    if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        value = stmt.value
        eff = _call_effect([value], v, spec, ctx, summaries) \
            if value is not None else None
        if eff:
            return eff
        if value is not None and v in _names(value):
            if any(isinstance(t, (ast.Attribute, ast.Subscript,
                                  ast.Tuple, ast.List))
                   for t in targets):
                return "transfer"     # parked on an object / container
            if any(isinstance(t, ast.Name) for t in targets):
                return "stop"         # alias: w = v
        if any(_is_name(t, v) for t in targets):
            return "stop"             # rebind: v = <something else>
        return None
    if isinstance(stmt, ast.Expr):
        val = stmt.value
        if isinstance(val, (ast.Yield, ast.YieldFrom, ast.Await)):
            inner = val.value
            if inner is not None and v in _names(inner):
                return "transfer"
            return _call_effect([inner], v, spec, ctx, summaries) \
                if inner is not None else None
        return _call_effect([val], v, spec, ctx, summaries)
    if isinstance(stmt, ast.Raise):
        exprs = [e for e in (stmt.exc, stmt.cause) if e is not None]
        if any(v in _names(e) for e in exprs):
            return "transfer"         # the exception now carries it
        return _call_effect(exprs, v, spec, ctx, summaries)
    return _call_effect([stmt], v, spec, ctx, summaries)


def _narrowed_edges(node: _cfg.CFGNode, v: str) -> Dict[str, bool]:
    """Edge kinds on which ``v`` is provably None/absent after this
    branch: ``{'true': True}`` means the true edge cannot hold the
    resource."""
    if node.kind not in ("branch", "loop") \
            or not isinstance(node.stmt, (ast.If, ast.While)):
        return {}
    test = node.stmt.test
    if _is_name(test, v):
        return {"false": True}
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
            and _is_name(test.operand, v):
        return {"true": True}
    if isinstance(test, ast.Compare) and len(test.ops) == 1 \
            and _is_name(test.left, v) \
            and isinstance(test.comparators[0], ast.Constant):
        const = test.comparators[0].value
        op = test.ops[0]
        if const is None:
            if isinstance(op, ast.Is):
                return {"true": True}
            if isinstance(op, ast.IsNot):
                return {"false": True}
        # the index-sentinel convention: acquires that return -1 for
        # "nothing available" (engine _alloc_slot) guard with < 0
        if const == 0:
            if isinstance(op, ast.Lt):
                return {"true": True}
            if isinstance(op, ast.GtE):
                return {"false": True}
        if const == -1:
            if isinstance(op, ast.Eq):
                return {"true": True}
            if isinstance(op, ast.NotEq):
                return {"false": True}
    return {}


# ---- acquire-site discovery ------------------------------------------------

def _acquire_sites(g: _cfg.ControlFlowGraph, ctx):
    """Yield ``(node, var, spec, text, discarded)`` for every catalog
    acquire in this function's CFG. Finally-copy duplicates are deduped
    by (ast stmt, spec)."""
    seen = set()
    for node in g.nodes.values():
        stmt = node.stmt
        if stmt is None or node.kind != "stmt":
            continue
        value = None
        var = None
        discarded = False
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            var, value = stmt.targets[0].id, stmt.value
        elif isinstance(stmt, ast.AnnAssign) \
                and isinstance(stmt.target, ast.Name):
            var, value = stmt.target.id, stmt.value
        elif isinstance(stmt, ast.Expr):
            value, discarded = stmt.value, True
        if not isinstance(value, ast.Call):
            continue
        resolved = ctx.resolve_call(value.func)
        if not resolved:
            continue
        for spec in CATALOG:
            if any(match(resolved, p) for p in spec.acquire):
                key = (id(stmt), spec.name)
                if key not in seen:
                    seen.add(key)
                    yield node, var, spec, _short(value), discarded
            elif discarded and spec.acquire_arg \
                    and any(match(resolved, p) for p in spec.acquire_arg):
                args = list(_arg_names(value))
                if args:
                    key = (id(stmt), spec.name)
                    if key not in seen:
                        seen.add(key)
                        yield node, args[0], spec, _short(value), False


# ---- the walk --------------------------------------------------------------

def _walk(g: _cfg.ControlFlowGraph, start: int, v: str,
          spec: ResourceSpec, ctx, summaries) -> Optional[str]:
    """First escape description while HELD, or None when every path
    releases/transfers."""
    from collections import deque

    q = deque()
    for (dst, kind) in g.succ(start):
        if kind == "raise":
            continue      # the acquire call itself failed: nothing held
        q.append((dst, kind, start))
    seen = set()
    while q:
        nid, kind, src = q.popleft()
        if nid == g.exit:
            s = g.nodes[src]
            if s.stmt is not None and isinstance(s.stmt, ast.Return):
                return f"leaks at `{_short(s.stmt)}` (line {s.line})"
            return "leaks at function exit"
        if nid == g.raise_exit:
            s = g.nodes[src]
            what = _short(s.stmt) if s.stmt is not None else "a statement"
            return f"leaks when `{what}` raises"
        if nid == start:
            return ("is re-acquired while a previous acquisition is "
                    "still held (loop path without release)")
        if nid in seen:
            continue
        seen.add(nid)
        node = g.nodes[nid]
        eff = _effect(node, v, spec, ctx, summaries) \
            if node.stmt is not None else None
        if eff in ("release", "transfer", "stop"):
            continue
        narrowed = _narrowed_edges(node, v)
        for (dst, k) in g.succ(nid):
            if narrowed.get(k):
                continue
            q.append((dst, k, nid))
    return None


def check_module(ctx) -> List[LeakReport]:
    """Every leak in one module — the ``leak-path`` rule's core."""
    reports: List[LeakReport] = []
    reported = set()
    summaries = module_summaries(ctx)
    for _qual, func in _cfg.function_nodes(ctx.tree):
        try:
            g = _cfg.build_cfg(func, resolver=ctx.resolve_call,
                               noraise=NORAISE)
        except RecursionError:      # pathological nesting: skip, don't die
            continue
        for (node, var, spec, text, discarded) in _acquire_sites(g, ctx):
            key = (node.line, spec.name, var)
            if key in reported:
                continue
            if discarded:
                reported.add(key)
                reports.append(LeakReport(
                    node.line, spec.name, var, text,
                    "is discarded immediately — bind it so it can be "
                    "released, or transfer it"))
                continue
            escape = _walk(g, node.id, var, spec, ctx, summaries)
            if escape is not None:
                reported.add(key)
                reports.append(LeakReport(node.line, spec.name, var,
                                          text, escape))
    reports.sort(key=lambda r: (r.line, r.resource, r.var or ""))
    return reports
