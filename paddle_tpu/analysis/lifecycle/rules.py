"""The ``leak-path`` rule: must-release checking over per-function CFGs.

A thin adapter — the work is ``cfg.build_cfg`` + ``dataflow.check_module``
against the ``resources.CATALOG``. Gated behind ``pdlint --lifecycle``
(the walk visits every path of every function for every catalog
resource; the default lint must stay instant), or by naming it in
``--select``.

Scope inside paddle_tpu/ is the serving tier — the modules that actually
move slots, leases, bundles, and spans. Kernel/analysis internals churn
ASTs and locks in ways the catalog was never written for; widening scope
there would only manufacture suppression noise. Fixture snippets (any
path outside paddle_tpu/) are always checked, so tests exercise the
rule without a serving-path filename.
"""
from __future__ import annotations

from typing import Iterable

from ..core import Finding, ModuleContext, Rule, register_rule
from .dataflow import check_module

__all__ = ["LeakPathRule"]

_SERVING_PREFIXES = (
    "paddle_tpu/serving",          # serving.py, serving_http.py,
                                   # serving_cluster/*
    "paddle_tpu/observability/",
    "paddle_tpu/chaos",
    "paddle_tpu/loadgen",
    "paddle_tpu/speculative",
)


def _in_scope(path: str) -> bool:
    if not path.startswith("paddle_tpu/"):
        return True                # fixtures and snippets: always check
    return path.startswith(_SERVING_PREFIXES)


@register_rule
class LeakPathRule(Rule):
    id = "leak-path"
    rationale = ("a resource acquired on one path must be released, "
                 "transferred, or returned on EVERY path; an "
                 "exception-edge leak is permanent capacity loss "
                 "(docs/ANALYSIS.md 'Lifecycle analysis')")
    lifecycle = True

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        if not _in_scope(ctx.path):
            return
        for r in check_module(ctx):
            f = self.finding(ctx, r.line, r.message)
            f.data = {"resource": r.resource, "var": r.var,
                      "acquire": r.acquire_text}
            yield f
