"""The resource catalog: acquire/release/transfer signatures.

Each ``ResourceSpec`` declares one resource family by the *shape of the
calls* that move it through its lifecycle. Patterns come in two forms:

- ``"open"`` — exact match on the alias-resolved call path;
- ``"*.select"`` / ``"*.pool.select"`` — suffix match (any receiver):
  ``self.pool.select`` matches both.

Ownership semantics the dataflow honors for every spec:

- binding the acquire call inside a ``with`` item is MANAGED — the
  context manager's ``__exit__`` is the release;
- returning/yielding the resource, storing it into an attribute,
  subscript, or container (``.append``/``.put``/…), or passing it to a
  declared ``transfer_arg`` call TRANSFERS ownership — not a leak;
- aliasing (``w = v``) conservatively ends tracking (the checker is a
  leak detector, not an escape analysis — silence beats a false leak);
- a spec's ``release_methods`` release via the resource itself
  (``v.close()``); ``release_arg`` patterns release via a call that
  takes the resource (``pool.release(v)``).

To declare a NEW resource (the PR-19 adapter registry will): add a spec
here, a catalog row to docs/ANALYSIS.md, and a positive/negative fixture
pair to tests/test_lifecycle_analysis.py. Nothing else — the dataflow
is table-driven.
"""
from __future__ import annotations

import dataclasses
from typing import Tuple

__all__ = ["ResourceSpec", "CATALOG", "NORAISE", "CONTAINER_STORES"]


@dataclasses.dataclass(frozen=True)
class ResourceSpec:
    """One resource family.

    ``acquire``       calls whose RESULT is the resource
    ``acquire_arg``   calls that turn their first argument into a held
                      resource (``pool.claim(w)``)
    ``release_methods`` method names released via the resource
                      (``v.close()``)
    ``release_arg``   calls that release the resource passed as any
                      argument (``pool.release(v)``)
    ``transfer_arg``  calls that take ownership of the resource passed
                      as an argument (``engine.admit_migrated(bundle)``)
    ``with_ok``       acquiring inside a ``with`` item is managed
    """

    name: str
    rationale: str
    acquire: Tuple[str, ...] = ()
    acquire_arg: Tuple[str, ...] = ()
    release_methods: Tuple[str, ...] = ()
    release_arg: Tuple[str, ...] = ()
    transfer_arg: Tuple[str, ...] = ()
    with_ok: bool = True


def match(resolved: str, pattern: str) -> bool:
    """``"*.x.y"`` is a dotted-suffix pattern; anything else is exact
    (after import-alias resolution)."""
    if pattern.startswith("*."):
        suffix = pattern[1:]                   # keep the leading dot
        return resolved.endswith(suffix) or resolved == pattern[2:]
    return resolved == pattern


# Container/method calls that count as ownership transfer for EVERY
# spec: the resource now lives in a structure someone else drains.
CONTAINER_STORES = frozenset({
    "append", "add", "put", "put_nowait", "push", "insert", "extend",
    "setdefault", "register", "appendleft", "send", "submit",
})

# Calls trusted not to raise: without this list every logger line
# between an acquire and its release would be a reported leak path.
# Deliberately small — only no-fail bookkeeping primitives.
NORAISE = frozenset({
    # clocks and ids
    "time.monotonic", "time.perf_counter", "time.perf_counter_ns",
    "time.time", "time.time_ns", "uuid.uuid4",
    # the rank-aware logger and stdlib logging surface
    "get_logger", "debug", "info", "warning", "error", "exception",
    # metric families (observability.metrics): counters/gauges never
    # raise on the hot path by contract
    "inc", "dec", "set", "observe", "labels",
    # flight recorder: record() is the measured-<1%-overhead hot path
    # and swallows internally by contract
    "record",
    # the pool lease teardown is a lock-guarded decrement — no-raise by
    # contract, so a finally can release one lease before another
    # without manufacturing a leak path between them
    "self.pool.release", "pool.release",
    # builtins that cannot fail on the values these paths feed them
    "len", "isinstance", "id", "repr", "str", "int", "float", "bool",
    "min", "max", "abs", "round", "sorted", "list", "dict", "tuple",
    "frozenset", "getattr", "hasattr", "format", "join", "split",
    "strip", "startswith", "endswith", "items", "keys", "values",
    "copy", "get", "pop", "discard", "clear", "update", "remove",
})


CATALOG: Tuple[ResourceSpec, ...] = (
    ResourceSpec(
        name="file-handle",
        rationale=("an unclosed file keeps an fd until GC feels like "
                   "it; under fd pressure the next open() fails"),
        acquire=("open", "io.open", "os.fdopen", "gzip.open",
                 "codecs.open"),
        release_methods=("close",),
    ),
    ResourceSpec(
        name="socket",
        rationale=("a leaked socket holds a port and a peer; routers "
                   "and probes open thousands over a process lifetime"),
        acquire=("socket.socket", "socket.create_connection",
                 "socket.socketpair"),
        release_methods=("close", "detach"),
    ),
    ResourceSpec(
        name="http-conn",
        rationale=("an HTTPConnection left open after an error path "
                   "pins its socket; the pool probe and relay open one "
                   "per poll/placement"),
        acquire=("http.client.HTTPConnection",
                 "httplib.HTTPConnection"),
        release_methods=("close",),
    ),
    ResourceSpec(
        name="pool-lease",
        rationale=("select()/claim() count a pending placement onto a "
                   "worker; a path that skips release() makes the "
                   "router see phantom load forever and starves the "
                   "replica"),
        acquire=("*.pool.select",),
        acquire_arg=("*.pool.claim",),
        release_arg=("*.pool.release",),
    ),
    ResourceSpec(
        name="tracer-span",
        rationale=("a start_span() without end() on some path never "
                   "reaches the buffer — the trace shows a hole "
                   "exactly where the failure was"),
        acquire=("*.start_span",),
        release_methods=("end",),
    ),
    ResourceSpec(
        name="kv-bundle",
        rationale=("an exported KV bundle owns a live request's "
                   "progress; dropping it on an exception path loses "
                   "the stream's tokens irrecoverably"),
        acquire=("*.export_slot", "*.export_prefill"),
        transfer_arg=("*.admit_migrated", "*.admit_prefilled",
                      "*.offer", "*.seal"),
    ),
    ResourceSpec(
        name="engine-slot",
        rationale=("a KV slot freed on no path is permanent capacity "
                   "loss — the engine's max_batch shrinks by one until "
                   "restart"),
        acquire=("*._alloc_slot",),
        release_arg=("*._release_slot",),
    ),
    ResourceSpec(
        name="lock-handle",
        rationale=("a bare .acquire() whose .release() is skippable "
                   "deadlocks the next waiter; with-blocks make it "
                   "structural"),
        acquire_arg=("*._lock.acquire",),
        release_arg=("*._lock.release",),
    ),
    ResourceSpec(
        name="process-handle",
        rationale=("a spawned worker process neither waited, "
                   "terminated, nor parked on the supervisor is a "
                   "zombie holding its TPU chips"),
        acquire=("subprocess.Popen",),
        release_methods=("wait", "terminate", "kill", "communicate"),
    ),
)
