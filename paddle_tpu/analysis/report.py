"""pdlint reporters: text (``file:line rule-id message``) and JSON.

The JSON schema is a stability contract (tests/test_static_analysis.py
pins it): CI consumers parse ``findings``/``counts``/``total`` and must
not break when rules are added. Bump ``SCHEMA_VERSION`` on any
shape-incompatible change.
"""
from __future__ import annotations

import json
from typing import Iterable, List, Optional

from .core import Finding

__all__ = ["render_text", "render_json", "SCHEMA_VERSION"]

SCHEMA_VERSION = 1


def render_text(findings: Iterable[Finding],
                baselined: int = 0) -> str:
    findings = list(findings)
    lines = [f.render() for f in findings]
    tail = f"pdlint: {len(findings)} finding(s)"
    if baselined:
        tail += f" ({baselined} baselined, not shown)"
    lines.append(tail)
    return "\n".join(lines)


def render_json(findings: Iterable[Finding], baselined: int = 0,
                rule_ids: Optional[List[str]] = None) -> str:
    findings = list(findings)
    counts = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    doc = {
        "schema_version": SCHEMA_VERSION,
        "tool": "pdlint",
        # ``data`` (a rule-attached JSON payload, e.g. the shard-solver
        # ledger) appears ONLY on findings that carry it — additive, so
        # the pinned 5-key shape holds for every other finding
        "findings": [
            dict({"file": f.file, "line": f.line, "rule": f.rule,
                  "symbol": f.symbol, "message": f.message},
                 **({"data": f.data} if f.data is not None else {}))
            for f in findings
        ],
        "counts": counts,
        "total": len(findings),
        "baselined": baselined,
    }
    if rule_ids is not None:
        doc["rules"] = sorted(rule_ids)
    return json.dumps(doc, indent=1, sort_keys=True) + "\n"
