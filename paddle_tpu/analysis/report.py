"""pdlint reporters: text (``file:line rule-id message``), JSON, SARIF.

The JSON schema is a stability contract (tests/test_static_analysis.py
pins it): CI consumers parse ``findings``/``counts``/``total`` and must
not break when rules are added. Bump ``SCHEMA_VERSION`` on any
shape-incompatible change. SARIF (``--format sarif``) is 2.1.0 — the
shape CI annotators ingest; fingerprints reuse the baseline key (file,
rule, symbol, message) so annotations survive unrelated edits exactly
like the baseline does.
"""
from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional

from .core import Finding

__all__ = ["render_text", "render_json", "render_sarif",
           "SCHEMA_VERSION"]

SCHEMA_VERSION = 1

_SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                 "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def render_text(findings: Iterable[Finding],
                baselined: int = 0) -> str:
    findings = list(findings)
    lines = [f.render() for f in findings]
    tail = f"pdlint: {len(findings)} finding(s)"
    if baselined:
        tail += f" ({baselined} baselined, not shown)"
    lines.append(tail)
    return "\n".join(lines)


def render_json(findings: Iterable[Finding], baselined: int = 0,
                rule_ids: Optional[List[str]] = None) -> str:
    findings = list(findings)
    counts = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    doc = {
        "schema_version": SCHEMA_VERSION,
        "tool": "pdlint",
        # ``data`` (a rule-attached JSON payload, e.g. the shard-solver
        # ledger) appears ONLY on findings that carry it — additive, so
        # the pinned 5-key shape holds for every other finding
        "findings": [
            dict({"file": f.file, "line": f.line, "rule": f.rule,
                  "symbol": f.symbol, "message": f.message},
                 **({"data": f.data} if f.data is not None else {}))
            for f in findings
        ],
        "counts": counts,
        "total": len(findings),
        "baselined": baselined,
    }
    if rule_ids is not None:
        doc["rules"] = sorted(rule_ids)
    return json.dumps(doc, indent=1, sort_keys=True) + "\n"


def render_sarif(findings: Iterable[Finding],
                 rules: Optional[Dict[str, object]] = None) -> str:
    """SARIF 2.1.0. ``rules`` is the registry (id -> Rule) so the tool
    component carries each rule's rationale; results fingerprint on the
    baseline key, not line numbers."""
    findings = list(findings)
    rule_meta = []
    for rid in sorted(rules or {}):
        rule_meta.append({
            "id": rid,
            "shortDescription": {"text": getattr(rules[rid], "rationale",
                                                 "") or rid},
        })
    results = []
    for f in findings:
        results.append({
            "ruleId": f.rule,
            "level": "warning",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.file,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(1, f.line)},
                },
            }],
            "partialFingerprints": {
                "pdlintKey/v1": "|".join(f.key()),
            },
        })
    doc = {
        "$schema": _SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "pdlint",
                "informationUri": "docs/ANALYSIS.md",
                "rules": rule_meta,
            }},
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=1, sort_keys=True) + "\n"
