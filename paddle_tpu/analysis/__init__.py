"""paddle_tpu.analysis — pdlint, the framework-native static analyzer.

Machine-checks the conventions the TPU-native collapse traded the
reference's generators for: trace purity, hot-path host-sync hygiene,
lock discipline, silent-exception hygiene, op-schema consistency, and
the metrics/span catalog contracts. The ``graph`` subpackage adds the
second layer — jaxpr-level preflight rules (sharding, dtype promotion,
retrace hazards, cost) that read the TRACED program instead of the
source, run under ``pdlint --graph`` and ``Engine.preflight()``. The
``threads`` subpackage is the third — whole-program concurrency
analysis (thread model, lock-order graph with deadlock-cycle witness
chains, blocking-under-lock, cross-thread unguarded state) under
``pdlint --threads``, paired with the runtime lock-order witness
(``FLAGS_lock_witness``). The ``lifecycle`` subpackage is the fourth —
CFG-based must-release analysis (``cfg.py`` control-flow graphs, the
resource catalog, the ``leak-path`` dataflow) under
``pdlint --lifecycle``; see docs/ANALYSIS.md "Lifecycle analysis". The
full rule catalog is in docs/ANALYSIS.md and ``scripts/pdlint.py`` is
the CLI; the tier-1 gates live in tests/test_static_analysis.py,
tests/test_graph_analysis.py, tests/test_thread_analysis.py and
tests/test_lifecycle_analysis.py.
"""
from . import baseline, report  # noqa: F401
from .core import (  # noqa: F401
    Finding, ModuleContext, ProjectRule, Rule, RULES, analyze_file,
    analyze_source, ast_rules, iter_py_files, module_context,
    project_rules, register_rule, run,
)

__all__ = [
    "Finding", "ModuleContext", "ProjectRule", "Rule", "RULES",
    "analyze_file", "analyze_source", "ast_rules", "iter_py_files",
    "module_context", "project_rules", "register_rule", "run",
    "baseline", "report",
]
