"""paddle_tpu.analysis.graph — jaxpr-level preflight analysis.

The second static-analysis layer: where pdlint's AST rules read Python
source, these rules read the TRACED program (``jax.make_jaxpr`` over
the model zoo's build functions) — sharding validity and propagation
(graph-shard-spec), bf16→f32 upcasts (graph-dtype-promotion), jit-cache
hazards (graph-retrace-hazard), byte/FLOP admission estimates
(graph-preflight-cost), and OpDecl dtype honesty (graph-op-dtypes).
:mod:`.solver` inverts the shard-spec pass into a planner — the
auto-sharding search behind ``param_specs="auto"``, the
``graph-shard-solver`` audit rule, and ``scripts/pdlint.py --solve``.

Three surfaces: ``scripts/pdlint.py --graph``, ``Engine.preflight()``
(serving.py, via :mod:`.preflight`), and the tier-1 zoo sweep
(tests/test_graph_analysis.py). See docs/ANALYSIS.md "Graph rules".
"""
from . import (  # noqa: F401
    cost, dtype_flow, op_dtypes, retrace, shard_spec, solver, zoo,
)
from .preflight import (  # noqa: F401
    PreflightError, PreflightReport, preflight_model,
)
from .solver import ShardingPlan  # noqa: F401
from .trace import (  # noqa: F401
    TracedGraph, iter_eqns, spec, trace_fn, trace_layer,
)

__all__ = [
    "TracedGraph", "trace_fn", "trace_layer", "iter_eqns", "spec",
    "PreflightError", "PreflightReport", "preflight_model",
    "ShardingPlan",
    "cost", "dtype_flow", "op_dtypes", "retrace", "shard_spec",
    "solver", "zoo",
]
