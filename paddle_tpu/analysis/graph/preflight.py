"""Serving preflight: shard-spec + dtype + cost at model-load.

``Engine.preflight()`` (serving.py) calls this before any buffer is
allocated or step compiled: trace the model abstractly, validate the
sharding annotations it carries, scan for dtype upcasts, and bound the
memory footprint — then refuse with a STRUCTURED findings report
instead of letting XLA crash minutes into compilation. The report
reuses pdlint's ``Finding`` type so the same text/JSON reporters render
it.

Severity model: shard-spec violations, untraceable models, and budget
overruns are ``fatal`` (the engine would crash or OOM); dtype upcasts
are advisory (wrong-but-running). ``PreflightError`` carries the report.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from ..core import Finding
from . import cost as _cost
from . import dtype_flow, retrace, shard_spec
from .trace import TracedGraph, spec, trace_layer

FATAL_RULES = ("graph-shard-spec", "graph-retrace-hazard",
               "graph-preflight-cost")


@dataclasses.dataclass
class PreflightReport:
    model: str
    findings: List[Finding] = dataclasses.field(default_factory=list)
    cost: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: the auto-sharding solver's ShardingPlan.as_dict() when
    #: param_specs="auto" ran (specs, byte/reshard accounting, ledger)
    plan: Optional[Dict[str, Any]] = None

    @property
    def fatal(self) -> List[Finding]:
        return [f for f in self.findings if f.rule in FATAL_RULES]

    @property
    def ok(self) -> bool:
        return not self.fatal

    def render(self) -> str:
        lines = [f.render() for f in self.findings]
        lines.append(f"preflight {self.model}: "
                     f"{len(self.fatal)} fatal / "
                     f"{len(self.findings)} finding(s), "
                     f"cost={self.cost}")
        return "\n".join(lines)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "model": self.model,
            "ok": self.ok,
            "cost": dict(self.cost),
            "plan": self.plan,
            "findings": [
                {"rule": f.rule, "symbol": f.symbol, "message": f.message,
                 "fatal": f.rule in FATAL_RULES}
                for f in self.findings
            ],
        }


class PreflightError(RuntimeError):
    """Raised by Engine.preflight on fatal findings; ``.report`` holds
    the structured PreflightReport."""

    def __init__(self, report: PreflightReport):
        super().__init__(
            f"preflight rejected {report.model}:\n{report.render()}")
        self.report = report


def _collect_param_placements(model) -> Dict[str, tuple]:
    """Placements already attached to parameters via dist.shard_tensor
    (``_dist_attr``) -> {param_name: (mesh, placements)}."""
    out = {}
    for name, p in getattr(model, "named_parameters", lambda: [])():
        attr = getattr(p, "_dist_attr", None)
        if attr is not None:
            out[name] = (attr.mesh, attr.placements)
    return out


def preflight_model(model, *, batch: int = 1, seq_len: int = 16,
                    mesh=None, param_specs: Optional[Dict] = None,
                    budget_bytes: Optional[int] = None,
                    kv_cache_bytes: int = 0,
                    allow_upcast=(),) -> PreflightReport:
    """Run the three preflight layers over a live model.

    ``mesh`` + ``param_specs`` ({name-substring: PartitionSpec tuple})
    validate an EXPLICIT layout; ``param_specs="auto"`` instead runs the
    auto-sharding solver over the trace and adopts the cheapest feasible
    plan (attached as ``report.plan``), so an arbitrary checkpoint +
    mesh serves with a machine-chosen layout. Independently, placements
    already attached to parameters (``dist.shard_tensor``) are validated
    against their own meshes. ``budget_bytes`` (device HBM available to
    this model) turns the cost estimate into an admission decision;
    ``kv_cache_bytes`` is added by the serving engine for its pool.
    """
    name = type(model).__name__
    report = PreflightReport(model=name)
    file = f"<preflight:{name}>"

    import jax.numpy as jnp

    ids = spec((batch, seq_len), jnp.int32)
    traced = trace_layer(model, ids, name=name)
    if traced.error is not None:
        for key, msg in retrace.find_hazards(traced):
            report.findings.append(Finding(
                file=file, line=1, rule="graph-retrace-hazard",
                message=msg, symbol=key))
        return report

    # ---- auto-sharding solver -----------------------------------------------
    plan = None
    if isinstance(param_specs, str):
        if param_specs != "auto":
            report.findings.append(Finding(
                file=file, line=1, rule="graph-shard-spec",
                message=f"param_specs={param_specs!r} is not a layout — "
                        "pass a spec mapping or 'auto'", symbol="auto"))
            param_specs = None
        elif mesh is None:
            report.findings.append(Finding(
                file=file, line=1, rule="graph-shard-spec",
                message="param_specs='auto' needs a mesh to plan over",
                symbol="auto"))
            param_specs = None
        else:
            from . import solver as _solver

            axis_sizes = dict(zip(mesh.dim_names, mesh.shape))
            plan = _solver.solve(traced, axis_sizes,
                                 budget_bytes=budget_bytes,
                                 extra_bytes=int(kv_cache_bytes))
            report.plan = plan.as_dict()
            param_specs = dict(plan.specs)
            if not plan.feasible:
                report.findings.append(Finding(
                    file=file, line=1, rule="graph-preflight-cost",
                    message=(f"no sharding plan fits: the cheapest "
                             f"({plan.assignment}) still needs "
                             f"~{plan.resident_bytes()} resident bytes "
                             f"per device (params "
                             f"{plan.per_device_param_bytes} + peak "
                             f"activations {plan.activation_bytes} + kv "
                             f"cache {int(kv_cache_bytes)}) against a "
                             f"budget of {int(budget_bytes)} — refuse "
                             "before compile"),
                    symbol="resident-bytes"))

    # ---- shard-spec ---------------------------------------------------------
    if mesh is not None and param_specs:
        axis_sizes = dict(zip(mesh.dim_names, mesh.shape))
        for pname in traced.param_names:
            aval = traced.param_avals[pname]
            for pat, sp in param_specs.items():
                if pat in pname:
                    for msg in shard_spec.check_partition_spec(
                            sp, axis_sizes, aval.shape,
                            what=f"param {pname}"):
                        report.findings.append(Finding(
                            file=file, line=1, rule="graph-shard-spec",
                            message=msg, symbol=pname))
                    break
    for pname, (pmesh, placements) in _collect_param_placements(
            model).items():
        arr_shape = traced.param_avals.get(pname)
        if arr_shape is None:
            continue
        for msg in shard_spec.check_placements(
                placements, pmesh, arr_shape.shape, what=f"param {pname}"):
            report.findings.append(Finding(
                file=file, line=1, rule="graph-shard-spec",
                message=msg, symbol=pname))

    # ---- dtype --------------------------------------------------------------
    for up in dtype_flow.find_upcasts(traced, allow=allow_upcast):
        report.findings.append(Finding(
            file=file, line=1, rule="graph-dtype-promotion",
            message=up.message(), symbol=f"{up.primitive}@{up.eqn_path}"))

    # ---- retrace hazards (baked consts) -------------------------------------
    for key, msg in retrace.find_hazards(traced):
        report.findings.append(Finding(
            file=file, line=1, rule="graph-retrace-hazard",
            message=msg, symbol=key))

    # ---- cost ---------------------------------------------------------------
    rep = _cost.estimate(traced)
    report.cost = rep.as_dict()
    report.cost["kv_cache_bytes"] = int(kv_cache_bytes)
    resident = rep.total_resident_bytes() + int(kv_cache_bytes)
    if plan is not None:
        # under the solver's plan, params are sharded: the admission
        # number is the per-device resident (kv already in extra_bytes),
        # and the feasibility finding above owns the budget decision
        resident = plan.resident_bytes()
    report.cost["resident_bytes"] = resident
    if plan is None and budget_bytes is not None and \
            resident > budget_bytes:
        report.findings.append(Finding(
            file=file, line=1, rule="graph-preflight-cost",
            message=(f"model needs ~{resident} resident bytes "
                     f"(params {rep.param_bytes} + peak activations "
                     f"{rep.peak_activation_bytes} + kv cache "
                     f"{int(kv_cache_bytes)}) but the budget is "
                     f"{int(budget_bytes)} — refuse before compile"),
            symbol="resident-bytes"))
    return report
