"""Graph-rule model enumeration: which families get preflighted, how.

Each entry builds a tiny-config model in bfloat16 (the serving dtype —
the dtype rule exists to protect exactly that build), declares its
abstract inputs, and optionally a sharding layout to validate/propagate.
The FAST set (llama, mixtral/MoE, whisper enc-dec, llama-sharded) is
what tier-1 sweeps on every pdlint --graph run; ``entries(full=True)``
extends over the wider zoo for the slow sweep.

Traces are memoized per entry name — the four graph rules share one
trace per model per process instead of re-tracing per rule.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Dict, FrozenSet, List, Optional, Sequence, Tuple

from .trace import TracedGraph, spec, trace_layer


@dataclasses.dataclass
class ShardLayout:
    """A mesh (axis name -> size; no devices needed) plus per-parameter
    PartitionSpecs from pattern rules — the annotation set shard-spec
    validates and feeds the propagation walk."""

    axis_sizes: Dict[str, int]
    # (substring-pattern, spec) — first match wins; unmatched params
    # stay replicated
    rules: Sequence[Tuple[str, Tuple]]

    def spec_for(self, param_name: str, ndim: int) -> Optional[Tuple]:
        for pat, sp in self.rules:
            if pat in param_name:
                return sp if len(sp) <= ndim else None
        return None

    def specs_for(self, traced) -> Dict[str, Tuple]:
        """The full {param name: normalized spec} mapping this layout
        assigns to a traced model — the hand-written plan the solver
        rule and the quality tests score against."""
        from . import shard_spec

        out: Dict[str, Tuple] = {}
        for name in traced.param_names:
            aval = traced.param_avals[name]
            sp = self.spec_for(name, len(aval.shape))
            if sp is not None:
                out[name] = shard_spec.normalize_spec(sp, len(aval.shape))
        return out


@dataclasses.dataclass
class ZooEntry:
    name: str
    build: Callable[[], object]               # -> Layer (bf16 tiny config)
    inputs: Callable[[object], tuple]         # model -> ShapeDtypeStructs
    allow_upcast: FrozenSet[str] = frozenset()
    shard: Optional[ShardLayout] = None


def _llama():
    from ...models.llama import LlamaConfig, LlamaForCausalLM

    return LlamaForCausalLM(LlamaConfig.tiny(dtype="bfloat16"))


def _mixtral():
    from ...models.mixtral import MixtralConfig, MixtralForCausalLM

    return MixtralForCausalLM(MixtralConfig.tiny(dtype="bfloat16"))


def _whisper():
    from ...models.whisper import (WhisperConfig,
                                   WhisperForConditionalGeneration)

    return WhisperForConditionalGeneration(
        WhisperConfig.tiny(dtype="bfloat16"))


def _ids_inputs(model):
    import jax.numpy as jnp

    return (spec((2, 16), jnp.int32),)


def _whisper_inputs(model):
    import jax.numpy as jnp

    cfg = model.config
    # features arrive in the model dtype (the serving front-end casts) —
    # the conv stem requires operand dtypes to match its weights
    return (spec((1, cfg.num_mel_bins, 2 * cfg.max_source_positions),
                 cfg.dtype),
            spec((1, 8), jnp.int32))


# Megatron layout over a (dp=2, mp=2) mesh: column-parallel projections
# shard the OUT dim (weights are [in, out]), row-parallel shard IN;
# embeddings/lm_head shard the vocab dim. mp=2 because the tiny config
# has 2 kv heads — mp must divide them or the attention reshape
# resharding the propagation walk flags is REAL (the known-bad fixture
# pins exactly that case at mp=4).
_LLAMA_SHARD = ShardLayout(
    axis_sizes={"dp": 2, "mp": 2},
    rules=(
        ("q_proj.weight", (None, "mp")),
        ("k_proj.weight", (None, "mp")),
        ("v_proj.weight", (None, "mp")),
        ("gate_proj.weight", (None, "mp")),
        ("up_proj.weight", (None, "mp")),
        ("o_proj.weight", ("mp", None)),
        ("down_proj.weight", ("mp", None)),
        ("embed_tokens.weight", ("mp", None)),
        ("lm_head.weight", (None, "mp")),
    ),
)


# the rope island: q/k convert to f32 and multiply the f32 cos/sin
# tables by design (precision) — the one deliberate tensor-mix every
# rope family carries. Allowing "mul" keeps dot_general/add/div/exp
# mixes hot for these models.
_ROPE = frozenset({"mul"})


def entries(full: bool = False) -> List[ZooEntry]:
    fast = [
        ZooEntry("llama", _llama, _ids_inputs, allow_upcast=_ROPE),
        ZooEntry("mixtral", _mixtral, _ids_inputs, allow_upcast=_ROPE),
        ZooEntry("whisper", _whisper, _whisper_inputs),
        ZooEntry("llama-sharded", _llama, _ids_inputs,
                 shard=_LLAMA_SHARD),
    ]
    if not full:
        return fast
    return fast + [
        ZooEntry("gpt2", _family("gpt2", "GPT2Config", "GPT2LMHeadModel"),
                 _ids_inputs),
        ZooEntry("qwen2", _family("qwen2", "Qwen2Config",
                                  "Qwen2ForCausalLM"), _ids_inputs,
                 allow_upcast=_ROPE),
        ZooEntry("qwen3", _family("qwen3", "Qwen3Config",
                                  "Qwen3ForCausalLM"), _ids_inputs,
                 allow_upcast=_ROPE),
        ZooEntry("mistral", _family("mistral", "MistralConfig",
                                    "MistralForCausalLM"), _ids_inputs,
                 allow_upcast=_ROPE),
        ZooEntry("gemma", _family("gemma", "GemmaConfig",
                                  "GemmaForCausalLM"), _ids_inputs,
                 allow_upcast=_ROPE),
        ZooEntry("gemma2", _family("gemma2", "Gemma2Config",
                                   "Gemma2ForCausalLM"), _ids_inputs,
                 allow_upcast=_ROPE),
        ZooEntry("phi3", _family("phi3", "Phi3Config", "Phi3ForCausalLM"),
                 _ids_inputs, allow_upcast=_ROPE),
        ZooEntry("olmo2", _family("olmo2", "Olmo2Config",
                                  "Olmo2ForCausalLM"), _ids_inputs,
                 allow_upcast=_ROPE),
        ZooEntry("glm", _family("glm", "GlmConfig", "GlmForCausalLM"),
                 _ids_inputs, allow_upcast=_ROPE),
        ZooEntry("qwen2-moe", _family("qwen2_moe", "Qwen2MoeConfig",
                                      "Qwen2MoeForCausalLM"), _ids_inputs,
                 allow_upcast=_ROPE),
        ZooEntry("deepseek-mla", _family("deepseek", "DeepseekV2Config",
                                         "DeepseekV2ForCausalLM",
                                         tiny="tiny_mla"), _ids_inputs,
                 allow_upcast=_ROPE),
    ]


def _family(mod: str, cfg_cls: str, model_cls: str, tiny: str = "tiny"):
    def build():
        import importlib

        m = importlib.import_module(f"paddle_tpu.models.{mod}")
        cfg = getattr(getattr(m, cfg_cls), tiny)(dtype="bfloat16")
        return getattr(m, model_cls)(cfg)

    build.__name__ = f"build_{mod}"
    return build


@functools.lru_cache(maxsize=32)
def traced(name: str, full: bool = False) -> TracedGraph:
    """Trace one zoo entry by name (memoized — rules share the trace)."""
    for e in entries(full=full):
        if e.name == name:
            model = e.build()
            return trace_layer(model, *e.inputs(model), name=e.name)
    raise KeyError(f"no zoo entry {name!r}")


def entry(name: str, full: bool = False) -> ZooEntry:
    for e in entries(full=full):
        if e.name == name:
            return e
    raise KeyError(f"no zoo entry {name!r}")
