"""retrace-hazard analysis: what defeats the jit cache or the trace.

Three hazard classes, all invisible until a production step mysteriously
recompiles (or never compiles):

1. **Data-dependent output shape** — ``jnp.nonzero``/``unique``/boolean
   masking make the output shape a function of VALUES; jax cannot trace
   them abstractly and raises mid-compile. The harness captures that
   exception (``TracedGraph.error``) and this module classifies it into
   a finding instead of a crash.
2. **Baked closure constants** — an array captured from the enclosing
   scope is burned into EVERY specialization as a const: a weak-typed
   scalar means a Python number got closed over (change it and the trace
   silently keeps the stale value — or, via static args, retraces); a
   large captured array multiplies its bytes by the number of compiled
   shape buckets.
3. **Live specialization blow-up** — the runtime half: StaticFunction
   (paddle_tpu/jit) counts compiled specializations per callable, and
   ``live_specialization_findings`` turns any count above threshold into
   a finding. Wired to the same hook ``jit.set_verbosity`` uses.
"""
from __future__ import annotations

from typing import List, Tuple

from .trace import TracedGraph

# substrings that identify jax's value-dependence failure modes across
# versions (ConcretizationTypeError and friends render differently)
_DATA_DEP_MARKERS = (
    "Abstract tracer value encountered",
    "must be statically specified",
    "data-dependent",
    "NonConcreteBooleanIndex",
    "Shapes must be 1D sequences of concrete values",
    "TracerBoolConversionError",
    "truth value of an array",
    "concrete value",
)

LARGE_CONST_BYTES = 1 << 20  # 1 MiB baked per specialization


def classify_trace_error(err: BaseException) -> str:
    """'data-dependent' | 'other' — the retrace rule reports the first,
    re-renders the second as a generic trace failure."""
    text = f"{type(err).__name__}: {err}"
    if any(m in text for m in _DATA_DEP_MARKERS):
        return "data-dependent"
    return "other"


def find_hazards(traced: TracedGraph,
                 large_const_bytes: int = LARGE_CONST_BYTES
                 ) -> List[Tuple[str, str]]:
    """Returns (key, message) pairs. ``key`` is stable for baselining:
    'trace-error', or 'const<N>' for the N-th hazardous constant."""
    out: List[Tuple[str, str]] = []
    if traced.error is not None:
        kind = classify_trace_error(traced.error)
        if kind == "data-dependent":
            out.append(("trace-error",
                        "data-dependent output shape: the program cannot "
                        "be traced abstractly and every distinct input "
                        "VALUE would recompile — use a static size= / "
                        "mask instead "
                        f"({type(traced.error).__name__})"))
        else:
            out.append(("trace-error",
                        f"model does not trace: "
                        f"{type(traced.error).__name__}: "
                        f"{str(traced.error).splitlines()[0][:160]}"))
        return out
    cj = traced.closed_jaxpr
    for i, (var, val) in enumerate(zip(cj.jaxpr.constvars, cj.consts)):
        aval = var.aval
        if getattr(aval, "weak_type", False) and aval.shape == ():
            out.append((f"const{i}",
                        "weak-typed scalar constant baked at trace time "
                        "— a Python number closed over the traced "
                        "function; pass it as an argument or it freezes "
                        "at its trace-time value"))
            continue
        nbytes = getattr(val, "nbytes", 0)
        if nbytes >= large_const_bytes:
            out.append((f"const{i}",
                        f"captured constant ({int(nbytes)} bytes, shape "
                        f"{tuple(aval.shape)}) is baked into every "
                        "specialization — thread it through as an input "
                        "so shape buckets share one copy"))
    return out


def live_specialization_findings(threshold: int = 8
                                 ) -> List[Tuple[str, int]]:
    """Consult the jit compile-cache statistics: StaticFunctions whose
    specialization count crossed ``threshold`` (the shape-bucketing
    contract says a serving step compiles a handful of buckets, not one
    per request). Returns (name, count) pairs."""
    from ...jit import specialization_stats

    return [(name, n) for name, n in sorted(specialization_stats().items())
            if n >= threshold]
