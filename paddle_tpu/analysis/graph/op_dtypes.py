"""OpDecl.dtypes honesty: claimed dtype lists vs eval_shape reality.

``OpDecl.dtypes`` is the ops.yaml dtype table analog, but nothing ever
executed it — a decl could claim bfloat16 while its impl upcasts every
bf16 input to float32 (jsp.special routines do), or claim float16 while
the impl outright rejects it. The check is the same mechanism
``infer_meta`` uses (ops/schema.py: jax.eval_shape of the registered
impl): abstractly evaluate the impl at each claimed dtype and compare
the output dtype.

Signature discovery: the impl is probed at float32 (always claimed,
always expected to work) over a small signature grid — 1..3 array
operands, square-matrix then vector shapes, then a tensor-list operand
(the add_n family). If nothing evaluates, the decl is skipped —
unverifiable-cheaply is not a finding. With a working signature, each
claimed dtype either evaluates (and its output dtype is compared) or
raises (a rejected claim).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

# square first (keeps matmul-shaped binaries evaluable), vector second
# (1-D-only signal ops); "list" probes a tensor-list operand
_PROBE_SHAPES = ((4, 4), (8,))

# float widths for upcast detection; int/bool outputs are never upcasts
# (comparisons, argmax and friends legitimately change kind)
_FLOAT_ORDER = {"bfloat16": 1, "float16": 1, "float32": 2, "float64": 3}


def _eval(impl, dtype: str, sig):
    import jax
    import jax.numpy as jnp

    from ...framework import random as _random

    arity, shape, as_list = sig
    specs = [jax.ShapeDtypeStruct(shape, jnp.dtype(dtype))
             for _ in range(arity)]
    # stateful-RNG impls (top_p_sampling) call next_key(); probe under a
    # concrete context key and restore the global state — otherwise the
    # abstract eval leaks a tracer into the process RNG
    prev = _random.get_rng_state()
    try:
        with _random.rng_context(jax.random.key(0)):
            if as_list:
                return jax.eval_shape(impl, specs)
            return jax.eval_shape(impl, *specs)
    finally:
        _random.set_rng_state(prev)


def _working_signature(impl) -> Optional[tuple]:
    for shape in _PROBE_SHAPES:
        for arity in (1, 2, 3):
            for as_list in (False, True) if arity == 2 else (False,):
                sig = (arity, shape, as_list)
                try:
                    _eval(impl, "float32", sig)
                    return sig
                except Exception:  # pdlint: disable=silent-exception -- probe grid: a non-matching signature is the expected miss
                    continue
    return None


def check_decl_dtypes(decls) -> List[Tuple[str, str]]:
    """Returns (op-name, message) pairs for dtype-list lies."""
    import jax

    problems: List[Tuple[str, str]] = []
    for d in decls:
        impl = getattr(d, "impl", None)
        if impl is None:
            continue
        sig = _working_signature(impl)
        if sig is None:
            continue
        for dt in d.dtypes:
            try:
                out = _eval(impl, dt, sig)
            except Exception as e:
                problems.append((d.name,
                                 f"op {d.name!r} claims dtype {dt!r} but "
                                 f"its impl rejects it "
                                 f"({type(e).__name__})"))
                continue
            leaves = jax.tree_util.tree_leaves(out)
            if not leaves or dt not in _FLOAT_ORDER:
                continue
            out_dt = str(leaves[0].dtype)
            if out_dt in _FLOAT_ORDER and \
                    _FLOAT_ORDER[out_dt] > _FLOAT_ORDER[dt]:
                problems.append((d.name,
                                 f"op {d.name!r} claims dtype {dt!r} but "
                                 f"its impl upcasts to {out_dt} — the "
                                 "decl advertises support the kernel "
                                 "doesn't keep"))
    return problems
