"""dtype-promotion analysis: find silent bf16 -> f32 upcasts in a jaxpr.

A model built in bfloat16 should compute in bfloat16; activations that
silently land in float32 double their bytes and every downstream eqn's
until something casts back. The classic sources are invisible in Python
source — a ``np.float32`` scalar constant promoting a mul, an f32
buffer added to bf16 activations, a matmul with
``preferred_element_type`` — and at jaxpr level jnp's promotion
machinery renders most of them as an inserted ``convert_element_type``,
the SAME eqn a deliberate ``.astype`` produces. So a per-eqn dtype
check cannot tell the norm's deliberate f32 island from the accident.

What can: **origin tracking**. Walk the jaxpr marking every f32 value
that descends from a bfloat16 ancestor ("derived"). A deliberate island
computes entirely among derived values (cast x up, do the math, cast
back). The accident is the MIX — an arithmetic eqn combining a derived
f32 operand with an f32 value of independent origin (a non-weak f32
literal or const, an f32 buffer, a table computed in f32): that is
precisely where jnp's promotion, not the author, chose float32.

Two finding classes:

- ``direct``: a non-convert eqn with a bf16 input and an f32 output
  (``preferred_element_type`` matmuls and friends).
- ``mix``: an arithmetic eqn mixing derived f32 with an independent
  non-weak f32 TENSOR (an f32 buffer or table whose bytes could have
  been bf16). Scalars never flag — weak ones (Python floats) because
  jax keeps bf16 for those, non-weak ones (an ``np.float32`` scale,
  ``-inf`` mask fill, eps) because with a derived operand present the
  island is already f32: a scalar contributes no bytes and cannot be
  the reason promotion chose float32.

Per-model allowlists (zoo entries / preflight callers) name allowed
PRIMITIVES for deliberate mixes (e.g. rope tables kept in f32 multiply
into converted q/k by design).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, Iterable, List, Set

from .trace import TracedGraph

LOW = ("bfloat16", "float16")
HIGH = ("float32", "float64")

# arithmetic that propagates magnitude — where an f32 operand forces an
# f32 result (comparisons/bool ops don't upcast anything)
_ARITH = {"add", "sub", "mul", "div", "max", "min", "pow", "atan2",
          "rem", "nextafter", "dot_general"}

# call-like eqns whose single sub-jaxpr maps invars/outvars 1:1
_TRANSPARENT_CALLS = {"pjit", "custom_vjp_call_jaxpr", "custom_jvp_call",
                      "custom_vjp_call", "remat", "checkpoint"}


@dataclasses.dataclass
class Upcast:
    eqn_path: str
    primitive: str
    kind: str                 # "direct" | "mix"
    detail: str

    def message(self) -> str:
        if self.kind == "direct":
            return (f"eqn {self.eqn_path} {self.primitive}: bf16 input "
                    f"produces {self.detail} output directly "
                    "(preferred_element_type or accumulation dtype) — "
                    "deliberate? cast explicitly so the island is "
                    "visible in source")
        return (f"eqn {self.eqn_path} {self.primitive}: mixes "
                f"bf16-derived f32 with {self.detail} — jnp promotion "
                "chose float32 here, not the author; cast the constant/"
                "buffer to the model dtype or allowlist the primitive "
                "as a deliberate f32 island")


def _is_low(aval) -> bool:
    return hasattr(aval, "dtype") and str(aval.dtype) in LOW


def _is_high(aval) -> bool:
    return hasattr(aval, "dtype") and str(aval.dtype) in HIGH


def find_upcasts(traced: TracedGraph,
                 allow: Iterable[str] = ()) -> List[Upcast]:
    if not traced.ok:
        return []
    allowed: FrozenSet[str] = frozenset(allow)
    out: List[Upcast] = []
    jaxpr = traced.closed_jaxpr.jaxpr
    _walk(jaxpr, derived=set(), prefix="", allowed=allowed, out=out)
    return out


def _walk(jaxpr, derived: Set, prefix: str, allowed: FrozenSet[str],
          out: List[Upcast]) -> None:
    """``derived``: vars (of this jaxpr) holding f32 values with a bf16
    ancestor. Mutated as eqns are walked; sub-jaxprs get their own set
    seeded through the call boundary."""

    def is_derived(v):
        return (not hasattr(v, "val")) and v in derived

    def var_high_independent(v):
        # an f32 TENSOR operand with no bf16 lineage; scalars never
        # count (see module docstring — they carry no bytes and the
        # island is already f32 once a derived operand is present)
        aval = v.aval
        if not _is_high(aval) or aval.shape == () or \
                getattr(aval, "weak_type", False):
            return False
        if hasattr(v, "val"):  # Literal
            return True
        return v not in derived

    for i, eqn in enumerate(jaxpr.eqns):
        path = f"{prefix}{i}"
        prim = eqn.primitive.name
        in_avals = [v.aval for v in eqn.invars if hasattr(v, "aval")]
        any_low_in = any(_is_low(a) for a in in_avals)
        any_derived_in = any(is_derived(v) for v in eqn.invars
                             if not hasattr(v, "val"))

        sub = _sub_jaxpr(eqn)
        if sub is not None and prim in _TRANSPARENT_CALLS and \
                len(sub.invars) == len(eqn.invars):
            inner_derived = {iv for iv, ov in zip(sub.invars, eqn.invars)
                             if not hasattr(ov, "val") and ov in derived}
            _walk(sub, inner_derived, f"{path}.{prim}.", allowed, out)
            for ov, iv in zip(eqn.outvars, sub.outvars):
                if (not hasattr(iv, "val") and iv in inner_derived) or \
                        (hasattr(iv, "aval") and _is_low(iv.aval)):
                    if _is_high(ov.aval):
                        derived.add(ov)
            # low-dtype lineage continues through low outputs implicitly
            continue

        if prim == "convert_element_type":
            # a convert to f32 joins the island lineage unless its input
            # is an independent high float: bf16 sources are the island
            # itself, and int/bool sources (masks, one_hot) picked f32
            # only to FOLLOW the island's dtype — neither is independent
            # f32 bytes that could have been bf16
            ov = eqn.outvars[0]
            src_indep_high = any(
                _is_high(a) and not getattr(a, "weak_type", False)
                for a in in_avals) and not (any_low_in or any_derived_in)
            if _is_high(ov.aval) and not src_indep_high:
                derived.add(ov)
            continue

        # direct upcast: bf16 in, f32 out, not a convert
        if any_low_in and prim not in allowed:
            hi = [str(v.aval.dtype) for v in eqn.outvars
                  if _is_high(v.aval)]
            if hi:
                out.append(Upcast(path, prim, "direct", hi[0]))

        # the mix: derived f32 meets independent f32 in arithmetic
        if prim in _ARITH and prim not in allowed and any_derived_in:
            indep = [v for v in eqn.invars if var_high_independent(v)]
            if indep:
                what = ("an f32 literal/const"
                        if any(hasattr(v, "val") for v in indep)
                        else "an independent f32 value")
                out.append(Upcast(path, prim, "mix", what))

        # lineage propagation: any eqn with a low or derived input whose
        # output is f32 keeps the lineage
        if any_low_in or any_derived_in:
            for ov in eqn.outvars:
                if hasattr(ov, "aval") and _is_high(ov.aval):
                    derived.add(ov)


def _sub_jaxpr(eqn):
    for v in eqn.params.values():
        inner = getattr(v, "jaxpr", None)
        if inner is not None and hasattr(inner, "eqns"):
            return inner
        if hasattr(v, "eqns"):
            return v
    return None
