"""The tracer harness: lift a Layer (or bare function) into a jaxpr.

pdlint's AST rules see Python source; the bugs that actually burn TPU
time live in the *traced program* — that is where dtype promotion
happens, where a sharded dim meets a reshape, where a closure constant
gets baked into every specialization. GSPMD (PAPERS.md) decides sharding
entirely from the annotated program before execution, and the XLA fusion
analysis paper reasons at the same granularity; ``TracedGraph`` is the
carrier both use here: the closed jaxpr plus everything the graph rules
need to key findings stably (parameter-name order, const avals, byte
sizes).

Tracing is ABSTRACT (``jax.make_jaxpr`` over ShapeDtypeStructs): no
FLOP executes, no buffer allocates, so a 70B-config model preflights in
the time it takes to trace — exactly the InferMeta-style gate the
TPU-native collapse dropped when ops/schema.py moved shape inference
into evaluation.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class TracedGraph:
    """One traced program + the metadata graph rules key findings on.

    ``param_names`` aligns 1:1 with the leading jaxpr invars (the
    flattened functional state), then one rng-key invar, then the data
    inputs — ``invar_spec_slots()`` exposes that layout so shard specs
    given per parameter NAME map onto invars without guessing.
    ``error`` is set (and ``closed_jaxpr`` None) when tracing raised —
    the retrace-hazard rule classifies those instead of crashing the
    lint run.
    """

    name: str
    closed_jaxpr: Optional[Any] = None
    param_names: List[str] = dataclasses.field(default_factory=list)
    param_avals: Dict[str, Any] = dataclasses.field(default_factory=dict)
    n_data_inputs: int = 0
    error: Optional[BaseException] = None

    @property
    def ok(self) -> bool:
        return self.closed_jaxpr is not None

    def param_bytes(self) -> int:
        return sum(int(jnp.dtype(a.dtype).itemsize) * _size(a.shape)
                   for a in self.param_avals.values())

    def invar_index_of_param(self, name: str) -> int:
        """Index into ``closed_jaxpr.jaxpr.invars`` for a parameter name
        (state leaves flatten in sorted-key order — dict pytrees)."""
        return self.param_names.index(name)

    def data_invars(self):
        """The invars carrying the data inputs (after state + rng key)."""
        return self.closed_jaxpr.jaxpr.invars[len(self.param_names) + 1:]


def _size(shape) -> int:
    n = 1
    for s in shape:
        n *= int(s)
    return n


def spec(shape, dtype) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))


def trace_fn(fn: Callable, *arg_specs, name: str = "") -> TracedGraph:
    """Trace a bare function over abstract inputs (the fixture entry
    point). A trace-time exception is captured, not raised."""
    name = name or getattr(fn, "__name__", "<fn>")
    try:
        cj = jax.make_jaxpr(fn)(*arg_specs)
    except Exception as e:  # classified by the retrace-hazard rule
        return TracedGraph(name=name, error=e,
                           n_data_inputs=len(arg_specs))
    return TracedGraph(name=name, closed_jaxpr=cj,
                       n_data_inputs=len(arg_specs))


def trace_layer(layer, *arg_specs, name: str = "",
                method: Optional[str] = None) -> TracedGraph:
    """Trace a Layer's forward (or ``method``) into a jaxpr.

    Mirrors the StaticFunction pure wrapper (jit/__init__.py): the
    functional state rides as the first traced input (so params are
    invars, not baked consts), the rng key as the second, and the layer
    is put in eval mode for the duration — dropout branches must not
    differ between the preflighted program and the served one.
    """
    from ...autograd import tape as _tape
    from ...framework import random as _random
    from ...nn.layer import functional_weights
    from ...tensor_class import Tensor, wrap

    name = name or type(layer).__name__
    state = layer.functional_state()
    fn = getattr(layer, method) if method else layer.forward

    def pure(state_arrs, rng_key, *xs):
        subs = layer.sublayers(include_self=True)
        prev_modes = [l.training for l in subs]
        for l in subs:
            l.training = False
        try:
            with functional_weights(layer, state_arrs), \
                    _random.rng_context(rng_key):
                out = fn(*[wrap(x) for x in xs])
            return jax.tree_util.tree_map(
                lambda x: x._array if isinstance(x, Tensor) else x, out,
                is_leaf=lambda x: isinstance(x, Tensor))
        finally:
            for l, m in zip(subs, prev_modes):
                l.training = m

    state_specs = {k: spec(v.shape, v.dtype) for k, v in state.items()}
    # dict pytrees flatten in sorted-key order — the invar <-> name map
    param_names = sorted(state_specs)
    key_spec = spec((2,), jnp.uint32)
    prev = _tape.set_grad_enabled(False)
    try:
        cj = jax.make_jaxpr(pure)(state_specs, key_spec, *arg_specs)
    except Exception as e:
        return TracedGraph(name=name, error=e, param_names=param_names,
                           param_avals=state_specs,
                           n_data_inputs=len(arg_specs))
    finally:
        _tape.set_grad_enabled(prev)
    return TracedGraph(name=name, closed_jaxpr=cj,
                       param_names=param_names, param_avals=state_specs,
                       n_data_inputs=len(arg_specs))


def iter_eqns(jaxpr, _prefix: str = "") -> Iterator[Tuple[str, Any]]:
    """Walk eqns depth-first, descending into sub-jaxprs (pjit bodies,
    custom_vjp calls, scan/while carries). Yields ``(path, eqn)`` where
    ``path`` is a stable dotted index ("14.custom_vjp_call_jaxpr.2") —
    the eqn half of the model+eqn finding key."""
    for i, eqn in enumerate(jaxpr.eqns):
        yield f"{_prefix}{i}", eqn
        for v in eqn.params.values():
            for sub in (v if isinstance(v, (list, tuple)) else [v]):
                inner = getattr(sub, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    yield from iter_eqns(
                        inner, f"{_prefix}{i}.{eqn.primitive.name}.")
                elif hasattr(sub, "eqns"):
                    yield from iter_eqns(
                        sub, f"{_prefix}{i}.{eqn.primitive.name}.")


def avals_in(eqn) -> List[Any]:
    return [v.aval for v in eqn.invars if hasattr(v, "aval")]


def avals_out(eqn) -> List[Any]:
    return [v.aval for v in eqn.outvars if hasattr(v, "aval")]
