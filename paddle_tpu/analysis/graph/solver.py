"""Auto-sharding solver: graphcheck grown from lint to planner.

PR 4's shard-spec layer *checks* hand-written PartitionSpecs — validate
the annotations, propagate them through the jaxpr, flag the implicit
reshards. This module inverts the pass, following GSPMD's design
(PAPERS.md: sharding is decidable from annotations + propagation, so
*proposing* annotations is a search over the same decision procedure)
and the search-over-parallel-plans framing of the auto-parallelization
line: given a traced model, mesh axis sizes, and an HBM budget,

1. classify the functional-state params into shardable **weight
   classes** (input embeddings, lm head, attention qkv/o, mlp up/down,
   norm/scalar) from their names and avals;
2. enumerate candidate PartitionSpec assignments per class —
   ``replicated``, ``row`` (second-to-last dim over the model axis),
   ``column`` (last dim over the model axis), ``fsdp`` (dim 0 over the
   data axis);
3. reuse :func:`shard_spec.propagate_events` to infer activation specs
   and collect every reshard/collective event each plan implies;
4. score each feasible plan with the existing cost model — per-device
   resident bytes (``cost.py``'s param/activation/kv terms, params
   divided by their shard product) plus a reshard-bytes term charged at
   every propagation event (implicit reshards at ``RESHARD_WEIGHT``×
   their tensor bytes — an unplanned all-to-all rides the interconnect,
   an order slower than HBM; planned collectives at 1×);
5. return the cheapest plan under budget as a structured
   :class:`ShardingPlan` carrying the specs, the byte/reshard accounting,
   and a rejected-plan ledger.

The search is exact over its enumeration: plans are evaluated in
ascending per-device-byte order and pruning is branch-and-bound on the
``cost >= bytes`` lower bound, so the returned plan is the true argmin.
Everything is pure over a :class:`~.trace.TracedGraph` — no devices, no
``jax.Mesh``; the same solve runs identically in preflight, the
``graph-shard-solver`` lint, and ``scripts/pdlint.py --solve``.
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Dict, List, Mapping, Optional, Tuple

from . import cost as _cost
from . import shard_spec
from .trace import TracedGraph

__all__ = [
    "ShardingPlan", "solve", "score_specs", "classify_params",
    "apply_plan", "RESHARD_WEIGHT", "COLLECTIVE_WEIGHT",
]

# An implicit reshard is an *unplanned* all-to-all on the step path:
# charged at 8x the tensor's bytes (ICI/interconnect bandwidth sits
# roughly an order of magnitude below HBM on every TPU generation the
# repo targets). Planned collectives (row-parallel all-reduce,
# vocab-parallel lookup) are the known Megatron tax: charged at 1x.
RESHARD_WEIGHT = 8
COLLECTIVE_WEIGHT = 1

# ---- weight classification --------------------------------------------------

# (class, name substrings) — first match wins; checked against ndim>=2
# before a non-replicated candidate applies. Patterns cover the families
# the zoo enumerates (llama-likes, MoE experts, whisper enc-dec, gpt2).
_CLASS_PATTERNS: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("lm_head", ("lm_head.weight", "output_projection.weight")),
    ("embed_in", ("embed_tokens.weight", "wte.weight", "wpe.weight",
                  "embed_positions.weight", "encoder_pos.weight",
                  "decoder_pos.weight", "shared.weight")),
    ("attn_qkv", ("q_proj.weight", "k_proj.weight", "v_proj.weight",
                  "qkv_proj.weight", "q_a_proj", "q_b_proj",
                  "kv_a_proj", "kv_b_proj", "c_attn.weight")),
    ("attn_o", ("o_proj.weight", "out_proj.weight", "wo.weight")),
    ("mlp_up", ("gate_proj.weight", "up_proj.weight",
                "gate_up_proj.weight", "fc1.weight", "c_fc.weight",
                ".w1.", ".w3.", "experts.w1", "experts.w3")),
    ("mlp_down", ("down_proj.weight", "fc2.weight", ".w2.",
                  "experts.w2")),
)

#: classes the candidate enumeration iterates, in deterministic order
CLASSES = ("embed_in", "lm_head", "attn_qkv", "attn_o", "mlp_up",
           "mlp_down")

#: candidate names per class, in deterministic order
CANDIDATES = ("replicated", "column", "row", "fsdp")


def classify_params(traced: TracedGraph) -> Dict[str, str]:
    """param name -> weight class (``norm_scalar`` for everything the
    patterns don't claim or that is sub-2D: biases, norms, scalars)."""
    out: Dict[str, str] = {}
    for name in traced.param_names:
        aval = traced.param_avals[name]
        klass = "norm_scalar"
        if len(aval.shape) >= 2:
            for k, pats in _CLASS_PATTERNS:
                if any(p in name for p in pats):
                    klass = k
                    break
        out[name] = klass
    return out


def _candidate_spec(choice: str, ndim: int, model_axis: Optional[str],
                    data_axis: Optional[str]) -> Optional[Tuple]:
    """The spec a candidate assigns to one ndim-rank weight (None =
    replicated). ``row`` shards the second-to-last dim (the contraction
    input for [in, out] weights; the vocab dim for [vocab, hidden]
    embeddings), ``column`` the last, ``fsdp`` dim 0 over the data axis
    (ZeRO-3-style)."""
    if ndim < 2:
        return None
    if choice == "column" and model_axis:
        return tuple([None] * (ndim - 1) + [model_axis])
    if choice == "row" and model_axis:
        return tuple([None] * (ndim - 2) + [model_axis, None])
    if choice == "fsdp" and data_axis:
        return tuple([data_axis] + [None] * (ndim - 1))
    return None


def _pick_axes(axis_sizes: Mapping[str, int]
               ) -> Tuple[Optional[str], Optional[str]]:
    """(model_axis, data_axis): ``mp``/``tp``/``model`` vs ``dp``/
    ``data`` by convention, else the largest/remaining axis. Axes of
    size 1 are useless for sharding and ignored."""
    live = {a: s for a, s in axis_sizes.items() if int(s) > 1}
    model = next((a for a in ("mp", "tp", "model") if a in live), None)
    data = next((a for a in ("dp", "data", "fsdp", "sharding")
                 if a in live), None)
    rest = [a for a in sorted(live, key=lambda a: (-live[a], a))
            if a not in (model, data)]
    if model is None and rest:
        model = rest.pop(0)
    if data is None and rest:
        data = rest.pop(0)
    return model, data


# ---- the plan ---------------------------------------------------------------

@dataclasses.dataclass
class ShardingPlan:
    """The solver's answer: the chosen specs plus the full accounting
    that justified them (and the ledger of plans that lost)."""

    model: str
    axis_sizes: Dict[str, int]
    assignment: Dict[str, str]            # class -> candidate name
    specs: Dict[str, Tuple]               # param name -> spec (sharded only)
    classes: Dict[str, str]               # param name -> class
    per_device_param_bytes: int = 0
    activation_bytes: int = 0
    extra_bytes: int = 0                  # kv cache etc. (caller-supplied)
    reshard_bytes: int = 0                # weighted charge, both classes
    n_reshard_events: int = 0             # implicit (unexpected) reshards
    n_collective_events: int = 0          # planned collectives
    cost: int = 0
    budget_bytes: Optional[int] = None
    feasible: bool = True
    plans_considered: int = 0
    ledger: List[Dict[str, Any]] = dataclasses.field(default_factory=list)

    def resident_bytes(self) -> int:
        """Per-device bytes that must fit at once under this plan."""
        return (self.per_device_param_bytes + self.activation_bytes
                + self.extra_bytes)

    def as_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["specs"] = {k: list(v) for k, v in self.specs.items()}
        d["resident_bytes"] = self.resident_bytes()
        return d

    def placements_for(self, mesh) -> Dict[str, list]:
        """param name -> placements over a ProcessMesh (Shard/Replicate
        per mesh dim) — what ``dist.shard_tensor`` consumes."""
        from ...distributed.placements import Replicate, Shard

        out: Dict[str, list] = {}
        for name, sp in self.specs.items():
            pls = []
            for ax in mesh.dim_names:
                dim = next((d for d, e in enumerate(sp)
                            if ax in shard_spec._axes_of(e)), None)
                pls.append(Replicate() if dim is None else Shard(dim))
            out[name] = pls
        return out


# ---- scoring ----------------------------------------------------------------

def _shard_product(sp, axis_sizes: Mapping[str, int]) -> int:
    total = 1
    for entry in sp:
        for ax in shard_spec._axes_of(entry):
            total *= int(axis_sizes.get(ax, 1))
    return total


def _nbytes(aval) -> int:
    import jax.numpy as jnp

    n = int(jnp.dtype(aval.dtype).itemsize)
    for s in aval.shape:
        n *= int(s)
    return n


def score_specs(traced: TracedGraph, specs: Mapping[str, Tuple],
                axis_sizes: Mapping[str, int], *,
                extra_bytes: int = 0,
                activation_bytes: Optional[int] = None,
                validate: bool = True) -> Dict[str, Any]:
    """Score an arbitrary {param name: spec} layout with the solver's
    metric — the shared yardstick the ``graph-shard-solver`` lint uses
    to audit hand-written ``param_specs`` against the planner's.

    Returns ``{cost, per_device_param_bytes, activation_bytes,
    reshard_bytes, n_reshard_events, n_collective_events, problems}``;
    ``problems`` non-empty means the layout is invalid (cost is still
    computed from the valid entries).
    """
    if activation_bytes is None:
        activation_bytes = _cost.estimate(traced).peak_activation_bytes
    problems: List[str] = []
    in_specs: Dict[int, Tuple] = {}
    per_dev = 0
    for name in traced.param_names:
        aval = traced.param_avals[name]
        sp = specs.get(name)
        if sp is None:
            per_dev += _nbytes(aval)
            continue
        sp = shard_spec.normalize_spec(sp, len(aval.shape))
        if validate:
            problems += shard_spec.check_partition_spec(
                sp, axis_sizes, aval.shape, what=f"param {name}")
        per_dev += _nbytes(aval) // max(1, _shard_product(sp, axis_sizes))
        in_specs[traced.invar_index_of_param(name)] = sp
    events = shard_spec.propagate_events(traced, in_specs, axis_sizes)
    n_reshard = sum(1 for e in events if not e.expected)
    n_coll = len(events) - n_reshard
    reshard_bytes = sum(
        e.bytes * (COLLECTIVE_WEIGHT if e.expected else RESHARD_WEIGHT)
        for e in events)
    resident = per_dev + int(activation_bytes) + int(extra_bytes)
    return {
        "cost": resident + reshard_bytes,
        "per_device_param_bytes": per_dev,
        "activation_bytes": int(activation_bytes),
        "extra_bytes": int(extra_bytes),
        "reshard_bytes": reshard_bytes,
        "n_reshard_events": n_reshard,
        "n_collective_events": n_coll,
        "problems": problems,
    }


# ---- the search -------------------------------------------------------------

def solve(traced: TracedGraph, axis_sizes: Mapping[str, int], *,
          budget_bytes: Optional[int] = None, extra_bytes: int = 0,
          ledger_limit: int = 32) -> ShardingPlan:
    """Search the per-class assignment space for the cheapest feasible
    plan. Deterministic: candidates enumerate in fixed order, plans are
    scored in ascending byte order, ties break on the assignment key.

    When no plan fits ``budget_bytes`` the cheapest plan overall is
    returned with ``feasible=False`` — the caller (preflight) turns that
    into the fatal admission finding, with the numbers attached.
    """
    if not traced.ok:
        raise ValueError(f"cannot solve an untraced model "
                         f"({traced.name}: {traced.error!r})")
    axis_sizes = {str(a): int(s) for a, s in axis_sizes.items()}
    model_axis, data_axis = _pick_axes(axis_sizes)
    classes = classify_params(traced)
    activation = _cost.estimate(traced).peak_activation_bytes

    # per-class candidate choices, deduped once axes collapse (a mesh
    # without a live data axis makes "fsdp" an alias of "replicated")
    per_class: Dict[str, Tuple[str, ...]] = {}
    for k in CLASSES:
        names = [n for n, c in classes.items() if c == k]
        if not names:
            continue
        seen: Dict[Optional[Tuple], str] = {}
        for choice in CANDIDATES:
            ndim = len(traced.param_avals[names[0]].shape)
            key = _candidate_spec(choice, ndim, model_axis, data_axis)
            if key not in seen:
                seen[key] = choice
        per_class[k] = tuple(seen.values())

    # enumerate assignments; compute the cheap byte term first and sort
    # ascending so the cost >= bytes bound prunes propagation exactly
    replicated_bytes = traced.param_bytes()
    base_resident = int(activation) + int(extra_bytes)
    plans: List[Tuple[int, Tuple[Tuple[str, str], ...],
                      Dict[str, Tuple], Optional[str]]] = []
    for combo in itertools.product(
            *(per_class[k] for k in sorted(per_class))):
        assignment = tuple(zip(sorted(per_class), combo))
        specs: Dict[str, Tuple] = {}
        invalid: Optional[str] = None
        per_dev = replicated_bytes
        for name in traced.param_names:
            choice = dict(assignment).get(classes[name], "replicated")
            aval = traced.param_avals[name]
            sp = _candidate_spec(choice, len(aval.shape), model_axis,
                                 data_axis)
            if sp is None:
                continue
            bad = shard_spec.check_partition_spec(
                sp, axis_sizes, aval.shape, what=f"param {name}")
            if bad:
                invalid = bad[0]
                break
            specs[name] = sp
            nb = _nbytes(aval)
            per_dev += nb // _shard_product(sp, axis_sizes) - nb
        plans.append((per_dev, assignment, specs, invalid))
    plans.sort(key=lambda p: (p[0], p[1]))

    best: Optional[Dict[str, Any]] = None
    best_key: Optional[Tuple] = None
    ledger: List[Dict[str, Any]] = []

    def log_plan(assignment, status, *, cost=None, per_dev=None,
                 reason=""):
        ledger.append({"assignment": dict(assignment), "status": status,
                       "cost": cost, "per_device_param_bytes": per_dev,
                       "reason": reason})

    for per_dev, assignment, specs, invalid in plans:
        if invalid is not None:
            log_plan(assignment, "invalid-spec", per_dev=per_dev,
                     reason=invalid)
            continue
        lower_bound = per_dev + base_resident
        if best is not None and lower_bound >= best["cost"]:
            # cost >= resident bytes: nothing below here can win
            log_plan(assignment, "pruned", per_dev=per_dev,
                     reason=f"byte lower bound {lower_bound} >= best "
                            f"cost {best['cost']}")
            continue
        score = score_specs(traced, specs, axis_sizes,
                            extra_bytes=extra_bytes,
                            activation_bytes=activation, validate=False)
        resident = (score["per_device_param_bytes"]
                    + score["activation_bytes"] + score["extra_bytes"])
        if budget_bytes is not None and resident > budget_bytes:
            log_plan(assignment, "over-budget", cost=score["cost"],
                     per_dev=score["per_device_param_bytes"],
                     reason=f"resident {resident} > budget "
                            f"{int(budget_bytes)}")
            continue
        key = (score["cost"], assignment)
        if best is None or key < (best["cost"], best_key[1]):
            if best is not None:
                log_plan(best_key[1], "costlier", cost=best["cost"],
                         per_dev=best["per_device_param_bytes"],
                         reason="beaten by a cheaper plan")
            best = dict(score, specs=specs)
            best_key = key
        else:
            log_plan(assignment, "costlier", cost=score["cost"],
                     per_dev=score["per_device_param_bytes"],
                     reason=f"cost {score['cost']} >= best "
                            f"{best['cost']}")

    feasible = best is not None
    if best is None:
        # nothing under budget: re-run unconstrained so the refusal
        # carries the cheapest plan's numbers
        return dataclasses.replace(
            solve(traced, axis_sizes, budget_bytes=None,
                  extra_bytes=extra_bytes, ledger_limit=ledger_limit),
            budget_bytes=int(budget_bytes), feasible=False)

    ledger.sort(key=lambda e: (e["cost"] is None, e["cost"] or 0))
    chosen = dict(best_key[1])
    return ShardingPlan(
        model=traced.name,
        axis_sizes=dict(axis_sizes),
        assignment={k: chosen.get(k, "replicated") for k in CLASSES
                    if k in per_class},
        specs=dict(best["specs"]),
        classes=classes,
        per_device_param_bytes=best["per_device_param_bytes"],
        activation_bytes=best["activation_bytes"],
        extra_bytes=int(extra_bytes),
        reshard_bytes=best["reshard_bytes"],
        n_reshard_events=best["n_reshard_events"],
        n_collective_events=best["n_collective_events"],
        cost=best["cost"],
        budget_bytes=None if budget_bytes is None else int(budget_bytes),
        feasible=feasible,
        plans_considered=len(plans),
        ledger=ledger[:ledger_limit],
    )


# ---- wiring helpers ---------------------------------------------------------

def apply_plan(model, specs: Mapping[str, Any], mesh) -> int:
    """Lay a live model's parameters out per a plan's spec mapping
    (``report.plan["specs"]`` or ``ShardingPlan.specs``) over a
    ProcessMesh via ``dist.shard_tensor`` — the serve-with-a-machine-
    chosen-plan step. Returns the number of parameters sharded."""
    from ...distributed.api import shard_tensor
    from ...distributed.placements import Replicate, Shard

    by_owner: Dict[str, Any] = {}
    for lname, sub in model.named_sublayers(include_self=True):
        by_owner[lname] = sub
    n = 0
    for pname, param in model.named_parameters():
        sp = specs.get(pname)
        if sp is None:
            continue
        owner_name, _, leaf = pname.rpartition(".")
        owner = by_owner.get(owner_name)
        if owner is None or leaf not in owner._parameters:
            continue
        pls = []
        for ax in mesh.dim_names:
            dim = next((d for d, e in enumerate(sp)
                        if ax in shard_spec._axes_of(e)), None)
            pls.append(Replicate() if dim is None else Shard(dim))
        owner._parameters[leaf] = shard_tensor(param, mesh, pls)
        n += 1
    return n
