"""The graph rules: jaxpr-level preflight checks in the pdlint registry.

These are ``ProjectRule``s with ``graph = True`` — they trace models
(hundreds of ms each, memoized per run), so they run only under
``scripts/pdlint.py --graph`` (or when selected explicitly), keeping the
default AST lint instant. Findings key on model+eqn
(``file="<graph:llama>"``, ``symbol="dot_general@14"``) so the baseline
machinery works unchanged for graph findings.
"""
from __future__ import annotations

import os
from typing import Iterable, List

from ..core import Finding, ProjectRule, register_rule
from . import cost as _cost
from . import dtype_flow, op_dtypes, retrace, shard_spec, solver, zoo

_SCHEMA_FILE = "paddle_tpu/ops/schema.py"


def _graph_file(model_name: str) -> str:
    return f"<graph:{model_name}>"


def _full_sweep() -> bool:
    """Zoo scope: the fast 4-family set by default; PDLINT_GRAPH_SCOPE=
    full widens to the whole zoo (the slow-marked sweep)."""
    return os.environ.get("PDLINT_GRAPH_SCOPE", "") == "full"


class GraphRule(ProjectRule):
    """A project rule that traces programs; opt-in via --graph."""

    graph = True


@register_rule
class ShardSpecRule(GraphRule):
    id = "graph-shard-spec"
    rationale = ("an invalid PartitionSpec (unknown axis, indivisible "
                 "dim, double-sharded axis) or an implicit reshard on "
                 "the step path surfaces as an opaque XLA crash or a "
                 "silent all-to-all tax — both decidable before compile "
                 "(GSPMD)")

    def check_project(self, root: str) -> Iterable[Finding]:
        full = _full_sweep()
        for e in zoo.entries(full=full):
            if e.shard is None:
                continue
            t = zoo.traced(e.name, full=full)
            file = _graph_file(e.name)
            if not t.ok:
                continue  # the retrace rule owns trace failures
            in_specs = {}
            for name, sp in e.shard.specs_for(t).items():
                aval = t.param_avals[name]
                for msg in shard_spec.check_partition_spec(
                        sp, e.shard.axis_sizes, aval.shape,
                        what=f"param {name}"):
                    yield Finding(file=file, line=1, rule=self.id,
                                  message=msg, symbol=name)
                in_specs[t.invar_index_of_param(name)] = sp
            for path, prim, msg in shard_spec.propagate(
                    t, in_specs, e.shard.axis_sizes):
                yield Finding(file=file, line=1, rule=self.id,
                              message=f"implicit reshard: {msg}",
                              symbol=f"{prim}@{path}")
        # OpDecl.spmd notes vs observed eval_shape behavior — the
        # propagation walk trusts those notes, so lies here mis-shard
        from paddle_tpu.ops import schema as _schema

        for name, msg in shard_spec.check_spmd_notes(_schema.DECLS):
            yield Finding(file=_SCHEMA_FILE, line=1, rule=self.id,
                          message=msg, symbol=name)


@register_rule
class ShardSolverRule(GraphRule):
    id = "graph-shard-solver"
    rationale = ("hand-written param_specs the auto-sharding solver "
                 "beats by >=20% on the static cost metric (per-device "
                 "resident bytes + weighted reshard bytes) are leaving "
                 "HBM or interconnect on the table — the planner audits "
                 "the humans")

    #: the hand layout survives while it is within 20% of the planner
    MARGIN = 0.8

    def check_project(self, root: str) -> Iterable[Finding]:
        full = _full_sweep()
        for e in zoo.entries(full=full):
            if e.shard is None:
                continue
            t = zoo.traced(e.name, full=full)
            if not t.ok:
                continue
            hand_specs = e.shard.specs_for(t)
            if not hand_specs:
                continue
            hand = solver.score_specs(t, hand_specs, e.shard.axis_sizes)
            plan = solver.solve(t, e.shard.axis_sizes)
            if hand["cost"] <= 0 or \
                    plan.cost >= self.MARGIN * hand["cost"]:
                continue
            pct = 100 * (1 - plan.cost / hand["cost"])
            yield Finding(
                file=_graph_file(e.name), line=1, rule=self.id,
                symbol="solver",
                message=(f"hand-written specs cost {hand['cost']} but "
                         f"the solver's plan costs {plan.cost} "
                         f"({pct:.0f}% cheaper) — assignment "
                         f"{plan.assignment}"),
                data={"hand": hand, "plan": {
                    "assignment": plan.assignment,
                    "cost": plan.cost,
                    "per_device_param_bytes": plan.per_device_param_bytes,
                    "reshard_bytes": plan.reshard_bytes,
                    "specs": {k: list(v) for k, v in plan.specs.items()},
                }, "ledger": plan.ledger})


@register_rule
class DtypePromotionRule(GraphRule):
    id = "graph-dtype-promotion"
    rationale = ("a bf16-built model silently computing islands in f32 "
                 "(weak-typed constants, dtype= reductions) doubles "
                 "activation bytes with no accuracy contract — visible "
                 "only at jaxpr level")

    def check_project(self, root: str) -> Iterable[Finding]:
        full = _full_sweep()
        for e in zoo.entries(full=full):
            if e.shard is not None:
                continue  # sharded twin re-traces the same program
            t = zoo.traced(e.name, full=full)
            if not t.ok:
                continue
            for up in dtype_flow.find_upcasts(t, allow=e.allow_upcast):
                yield Finding(file=_graph_file(e.name), line=1,
                              rule=self.id, message=up.message(),
                              symbol=f"{up.primitive}@{up.eqn_path}")


@register_rule
class RetraceHazardRule(GraphRule):
    id = "graph-retrace-hazard"
    rationale = ("data-dependent shapes and baked closure constants "
                 "defeat the jit cache — every production step "
                 "recompiles (or never compiles) where the trace could "
                 "have said so upfront")

    def check_project(self, root: str) -> Iterable[Finding]:
        full = _full_sweep()
        for e in zoo.entries(full=full):
            if e.shard is not None:
                continue
            t = zoo.traced(e.name, full=full)
            for key, msg in retrace.find_hazards(t):
                yield Finding(file=_graph_file(e.name), line=1,
                              rule=self.id, message=msg, symbol=key)


@register_rule
class PreflightCostRule(GraphRule):
    id = "graph-preflight-cost"
    rationale = ("serving admission must know param/activation bytes "
                 "and FLOPs before touching the device — a family whose "
                 "cost cannot be estimated cannot be preflighted")

    def check_project(self, root: str) -> Iterable[Finding]:
        full = _full_sweep()
        for e in zoo.entries(full=full):
            if e.shard is not None:
                continue
            t = zoo.traced(e.name, full=full)
            if not t.ok:
                continue
            rep = _cost.estimate(t)
            file = _graph_file(e.name)
            if rep.param_bytes <= 0:
                yield Finding(file=file, line=1, rule=self.id,
                              message="param byte estimate is zero — "
                              "the functional state carries no avals",
                              symbol="param-bytes")
            if rep.flops <= 0:
                yield Finding(file=file, line=1, rule=self.id,
                              message="FLOP estimate is zero — the "
                              "traced program has no costed eqns",
                              symbol="flops")


@register_rule
class AutotuneCostTableRule(GraphRule):
    id = "graph-cost-table"
    rationale = ("a persisted autotune cost-table entry whose recorded "
                 "bytes/FLOPs no longer match the kernel's analytical "
                 "cost model was measured against a different kernel "
                 "than the one shipping — its winner (and its roofline "
                 "pruning evidence) is stale")

    def check_project(self, root: str) -> Iterable[Finding]:
        import json

        from ...ops.pallas import autotune
        # importing the kernel modules registers their cost models
        from ...ops.pallas import decode_tail, fused_norm  # noqa: F401
        # the step profiler persists serving_decode_step observations
        # into the same table; its model must be live for the replay
        from ...observability import perf  # noqa: F401

        path = autotune.cache_path()
        if not os.path.isfile(path):
            return
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            yield Finding(file=rel, line=1, rule=self.id,
                          symbol="cache-file",
                          message=f"autotune cache unreadable "
                                  f"({type(e).__name__}: {e})")
            return
        for kernel, sigs in data.items():
            if not isinstance(sigs, dict):
                continue
            for key, ent in sigs.items():
                if not isinstance(ent, dict):
                    continue
                est = ent.get("est")
                params = ent.get("params")
                choice = ent.get("choice")
                if not est or not params or not choice:
                    continue  # pre-search-era entry: nothing to check
                symbol = f"{kernel}:{key}"
                try:
                    cur = autotune.analytical_cost(kernel, params, choice)
                except (KeyError, TypeError, ValueError) as e:
                    yield Finding(
                        file=rel, line=1, rule=self.id, symbol=symbol,
                        message=f"cost model replay failed on recorded "
                                f"params ({type(e).__name__}: {e})")
                    continue
                if cur is None:
                    yield Finding(
                        file=rel, line=1, rule=self.id, symbol=symbol,
                        message="entry carries analytical estimates but "
                                "no cost model is registered for this "
                                "kernel anymore — stale evidence")
                    continue
                for field in ("bytes", "flops"):
                    want = cur.get(field)
                    got = est.get(field)
                    if want is None or got is None:
                        continue
                    if abs(int(want) - int(got)) > max(1, int(want) // 100):
                        yield Finding(
                            file=rel, line=1, rule=self.id, symbol=symbol,
                            message=f"recorded {field}={got} disagrees "
                                    f"with the analytical estimate "
                                    f"{want} — re-run the sweep (or fix "
                                    f"the cost model drift)")


@register_rule
class OpDtypesRule(GraphRule):
    id = "graph-op-dtypes"
    rationale = ("an OpDecl claiming a dtype its impl upcasts or "
                 "rejects advertises support the kernel doesn't keep — "
                 "checkable by the same eval_shape path infer_meta uses")

    def check_project(self, root: str) -> Iterable[Finding]:
        import sys

        if root not in sys.path:
            sys.path.insert(0, root)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from paddle_tpu.ops import schema as _schema

        for name, msg in op_dtypes.check_decl_dtypes(_schema.DECLS):
            yield Finding(file=_SCHEMA_FILE, line=1, rule=self.id,
                          message=msg, symbol=name)
