"""shard-spec analysis: PartitionSpec validity + GSPMD-lite propagation.

GSPMD's core result (PAPERS.md): sharding is fully decidable from the
annotations plus a propagation pass over the traced program — nothing
about it requires touching a device. This module is that decision
procedure, reduced to the two failure classes that actually burn TPU
time here:

1. **Invalid annotation** — a PartitionSpec naming a mesh axis that
   doesn't exist, double-assigning one mesh axis, or sharding a dim the
   axis size doesn't divide. XLA reports these as opaque compile-time
   crashes *after* minutes of tracing; ``check_partition_spec`` reports
   them from the annotation alone.
2. **Implicit reshard** — a propagation walk over the jaxpr flags eqns
   where a sharded dim cannot survive (a reshape that splits a dim with
   the sharded factor in the minor position, a dot_general whose
   contracting dims carry mismatched axes). GSPMD silently inserts
   all-to-alls there; on the decode step path that is a per-token tax
   nobody asked for.

Everything is pure (mesh = axis-name -> size mapping), so rules and
fixtures run without devices or ``jax.Mesh`` construction.

The walk reports two event classes (``ReshardEvent.expected``):
*unexpected* implicit reshards (the lint findings ``propagate`` has
always returned) and *expected* collectives — the planned Megatron
communication GSPMD inserts by design (matched-contraction all-reduce,
vocab-parallel embedding gather). Expected events are never findings,
but they carry byte charges the auto-sharding solver (``solver.py``)
sums into its cost metric, so a plan that leans on collectives pays for
them in the search.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

# a spec here is a tuple, one entry per tensor dim: None | axis-name |
# tuple of axis-names (the PartitionSpec shape, minus the class)
Spec = Tuple


def normalize_spec(spec, ndim: int) -> Spec:
    """PartitionSpec / tuple / list -> a full-rank tuple of entries."""
    entries = list(tuple(spec))
    if len(entries) > ndim:
        return tuple(entries)  # over-rank: left to the validator to flag
    entries += [None] * (ndim - len(entries))
    return tuple(entries)


def _axes_of(entry) -> Tuple[str, ...]:
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(entry)
    return (entry,)


def check_partition_spec(spec, axis_sizes: Mapping[str, int],
                         shape: Sequence[int], *,
                         what: str = "value") -> List[str]:
    """Validate one spec against a mesh (axis-name -> size) and a shape.

    Returns messages for: rank overflow, unknown axis, one mesh axis
    used on two dims (double-sharding), and a dim size the sharding
    product doesn't divide.
    """
    problems: List[str] = []
    entries = tuple(tuple(spec))
    if len(entries) > len(shape):
        problems.append(
            f"{what}: spec {entries!r} has {len(entries)} entries for "
            f"rank-{len(shape)} shape {tuple(shape)}")
        return problems
    used: Dict[str, int] = {}
    for dim, entry in enumerate(normalize_spec(spec, len(shape))):
        axes = _axes_of(entry)
        total = 1
        for ax in axes:
            if ax not in axis_sizes:
                problems.append(
                    f"{what}: dim {dim} sharded over unknown mesh axis "
                    f"{ax!r} (mesh axes: {sorted(axis_sizes)})")
                continue
            if ax in used:
                problems.append(
                    f"{what}: mesh axis {ax!r} assigned to both dim "
                    f"{used[ax]} and dim {dim} (an axis shards at most "
                    "one dim)")
            used[ax] = dim
            total *= axis_sizes[ax]
        if total > 1 and shape[dim] % total != 0:
            problems.append(
                f"{what}: dim {dim} of size {shape[dim]} not divisible "
                f"by sharding {axes!r} (prod={total})")
    return problems


def check_placements(placements, mesh, shape, *,
                     what: str = "value") -> List[str]:
    """Validate a placements list (Shard/Replicate/Partial) against a
    ProcessMesh + shape WITHOUT raising — the preflight form of
    ``placements_to_partition_spec``."""
    from ...distributed.placements import Shard

    problems: List[str] = []
    axis_sizes = dict(zip(mesh.dim_names, mesh.shape))
    if len(placements) > mesh.ndim:
        problems.append(
            f"{what}: {len(placements)} placements for mesh of rank "
            f"{mesh.ndim}")
        return problems
    per_dim: Dict[int, List[str]] = {}
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            if p.dim >= len(shape):
                problems.append(
                    f"{what}: Shard(dim={p.dim}) invalid for rank-"
                    f"{len(shape)} shape {tuple(shape)}")
                continue
            per_dim.setdefault(p.dim, []).append(mesh.dim_names[mesh_dim])
    spec = tuple(tuple(per_dim[d]) if d in per_dim else None
                 for d in range(len(shape)))
    problems += check_partition_spec(spec, axis_sizes, shape, what=what)
    return problems


# ---- GSPMD-lite propagation -------------------------------------------------

_ELEMENTWISE_SAFE = {
    # unary + binary elementwise, casts, and ops that keep layout
    "add", "sub", "mul", "div", "max", "min", "pow", "exp", "log",
    "tanh", "logistic", "rsqrt", "sqrt", "neg", "abs", "sign", "cos",
    "sin", "erf", "floor", "ceil", "round", "rem", "and", "or", "xor",
    "not", "eq", "ne", "lt", "le", "gt", "ge", "select_n",
    "convert_element_type", "stop_gradient", "integer_pow", "clamp",
    "is_finite", "nextafter", "atan2", "square", "cbrt", "tan", "copy",
}

_REDUCERS = {"reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
             "reduce_and", "reduce_or", "argmax", "argmin"}


def _merge_specs(specs: List[Optional[Spec]], shape) -> Tuple[Spec, bool]:
    """Elementwise merge of operand specs (broadcasting-aware on the
    right-aligned dims). Returns (merged, conflict) — conflict when two
    operands shard one dim over different axes (GSPMD must reshard one)."""
    ndim = len(shape)
    out: List = [None] * ndim
    conflict = False
    for sp in specs:
        if sp is None:
            continue
        # right-align (numpy broadcasting) a lower-rank operand spec
        pad = ndim - len(sp)
        for i, entry in enumerate(sp):
            d = i + pad
            if entry is None:
                continue
            if out[d] is None:
                out[d] = entry
            elif _axes_of(out[d]) != _axes_of(entry):
                conflict = True
    # one mesh axis landing on two output dims is equally impossible —
    # GSPMD must strip it from one of them (a reshard)
    seen: Dict[str, int] = {}
    for d, entry in enumerate(out):
        for ax in _axes_of(entry):
            if ax in seen and seen[ax] != d:
                conflict = True
            seen[ax] = d
    return tuple(out), conflict


def _reshape_groups(in_shape, out_shape):
    """Pair contiguous dim groups with equal products (the classic
    reshape factor matching). Yields (in_dims, out_dims) index tuples;
    returns None when no clean grouping exists."""
    groups = []
    i = j = 0
    ni, nj = len(in_shape), len(out_shape)
    while i < ni or j < nj:
        gi, gj = [i], [j]
        if i >= ni or j >= nj:
            return None
        pi, pj = int(in_shape[i]), int(out_shape[j])
        while pi != pj:
            if pi < pj:
                i += 1
                if i >= ni:
                    return None
                gi.append(i)
                pi *= int(in_shape[i])
            else:
                j += 1
                if j >= nj:
                    return None
                gj.append(j)
                pj *= int(out_shape[j])
        groups.append((tuple(gi), tuple(gj)))
        i += 1
        j += 1
    return groups


@dataclasses.dataclass
class ReshardEvent:
    """One propagation event: an eqn where sharding forces communication.

    ``expected=False`` — an *implicit* reshard (the lint finding: GSPMD
    silently re-tiles). ``expected=True`` — a planned collective the
    layout implies by design (matched-contraction all-reduce,
    vocab-parallel embedding gather); never a finding, but ``bytes``
    (the eqn's output bytes, the tensor that moves) feeds the solver's
    cost metric.
    """

    path: str
    primitive: str
    message: str
    bytes: int = 0
    expected: bool = False

    def as_tuple(self) -> Tuple[str, str, str]:
        return (self.path, self.primitive, self.message)


def _out_bytes(eqn) -> int:
    import jax.numpy as jnp

    total = 0
    for v in eqn.outvars:
        aval = getattr(v, "aval", None)
        if aval is None or not hasattr(aval, "shape"):
            continue
        n = int(jnp.dtype(aval.dtype).itemsize)
        for s in aval.shape:
            n *= int(s)
        total += n
    return total


def propagate(traced, in_specs: Dict[int, Spec],
              axis_sizes: Mapping[str, int]) -> List[Tuple[str, str, str]]:
    """Walk the top-level jaxpr propagating shardings forward.

    ``in_specs``: invar index -> spec. Returns findings as
    ``(eqn_path, primitive, message)`` for eqns that force an implicit
    reshard. Unknown primitives drop the sharding silently (GSPMD knows
    more rules than we model; silence beats noise) — the walk exists to
    catch the *decidable* hazards, not to re-implement GSPMD. Expected
    collectives (see :class:`ReshardEvent`) are not returned here; use
    ``propagate_events`` for the full event stream the solver scores.
    """
    return [e.as_tuple() for e in propagate_events(traced, in_specs,
                                                   axis_sizes)
            if not e.expected]


def propagate_events(traced, in_specs: Dict[int, Spec],
                     axis_sizes: Mapping[str, int]) -> List[ReshardEvent]:
    """The event-stream form of :func:`propagate`: every implicit
    reshard AND every expected collective, each with the byte charge
    the solver's cost metric sums."""
    jaxpr = traced.closed_jaxpr.jaxpr
    env: Dict[Any, Spec] = {}
    for idx, sp in in_specs.items():
        var = jaxpr.invars[idx]
        env[var] = normalize_spec(sp, len(var.aval.shape))
    events: List[ReshardEvent] = []

    def lookup(v):
        # Literals (inline constants) are unhashable and never sharded
        if hasattr(v, "val") or not hasattr(v, "aval"):
            return None
        return env.get(v)

    for path, eqn in enumerate(jaxpr.eqns):
        prim = eqn.primitive.name
        ins = [lookup(v) for v in eqn.invars if hasattr(v, "aval")]
        if not any(sp is not None for sp in ins):
            continue

        def emit(msg, *, expected=False, prim=prim, path=path, eqn=eqn):
            events.append(ReshardEvent(
                path=str(path), primitive=prim, message=msg,
                bytes=_out_bytes(eqn), expected=expected))

        out_spec: Optional[Spec] = None
        if prim in _ELEMENTWISE_SAFE and eqn.outvars:
            shape = eqn.outvars[0].aval.shape
            out_spec, conflict = _merge_specs(ins, shape)
            if conflict:
                emit("operands shard one dim over different mesh axes — "
                     "GSPMD inserts a reshard to reconcile them")
        elif prim == "transpose":
            (sp,) = [s for s in ins if s is not None][:1] or [None]
            if sp is not None:
                perm = eqn.params["permutation"]
                out_spec = tuple(sp[p] for p in perm)
        elif prim == "broadcast_in_dim":
            sp = ins[0]
            if sp is not None:
                shape = eqn.params["shape"]
                bdims = eqn.params["broadcast_dimensions"]
                out: List = [None] * len(shape)
                for src, dst in enumerate(bdims):
                    out[dst] = sp[src]
                out_spec = tuple(out)
        elif prim == "reshape":
            sp = ins[0]
            in_shape = eqn.invars[0].aval.shape
            out_shape = eqn.outvars[0].aval.shape
            out_spec, msg = _propagate_reshape(sp, in_shape, out_shape,
                                               axis_sizes)
            if msg:
                emit(msg)
        elif prim == "dot_general":
            out_spec, msgs = _propagate_dot(eqn, ins)
            for msg, expected in msgs:
                emit(msg, expected=expected)
        elif prim == "gather":
            out_spec, msgs = _propagate_gather(eqn, ins)
            for msg, expected in msgs:
                emit(msg, expected=expected)
        elif prim.startswith("scatter"):
            out_spec, msgs = _propagate_scatter(eqn, ins)
            for msg, expected in msgs:
                emit(msg, expected=expected)
        elif prim in _REDUCERS:
            sp = ins[0]
            if sp is not None:
                axes = set(eqn.params.get("axes", ()))
                out_spec = tuple(e for d, e in enumerate(sp)
                                 if d not in axes)
        # unknown primitive: out_spec stays None (sharding dropped)
        if out_spec is not None and any(e is not None for e in out_spec):
            for ov in eqn.outvars:
                if hasattr(ov, "aval") and \
                        len(ov.aval.shape) == len(out_spec):
                    env[ov] = out_spec
    return events


def _propagate_reshape(sp, in_shape, out_shape, axis_sizes):
    if sp is None or not any(e is not None for e in sp):
        return None, None
    groups = _reshape_groups(in_shape, out_shape)
    if groups is None:
        return None, (f"reshape {tuple(in_shape)} -> {tuple(out_shape)} "
                      "has no clean dim grouping; sharded operand forces "
                      "an implicit reshard")
    out: List = [None] * len(out_shape)
    for in_dims, out_dims in groups:
        sharded = [(d, sp[d]) for d in in_dims if sp[d] is not None]
        if not sharded:
            continue
        d, entry = sharded[0]
        if len(sharded) > 1:
            return None, ("reshape merges two sharded dims "
                          f"{[x[0] for x in sharded]} into one — implicit "
                          "reshard")
        total = 1
        for ax in _axes_of(entry):
            total *= int(axis_sizes.get(ax, 1))
        if d == in_dims[0] and int(out_shape[out_dims[0]]) % total == 0:
            # sharded dim is the MAJOR factor of its group and the shard
            # count divides the major output dim: layout survives
            out[out_dims[0]] = entry
        else:
            return None, (f"reshape splits dim {d} with sharding "
                          f"{_axes_of(entry)!r} in the minor position "
                          f"({tuple(in_shape)} -> {tuple(out_shape)}) — "
                          "GSPMD must all-to-all to re-tile")
    return tuple(out), None


def _propagate_dot(eqn, ins):
    """Returns ``(out_spec, [(message, expected), ...])``."""
    ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
    lsp, rsp = (ins + [None, None])[:2]
    msgs: List[Tuple[str, bool]] = []
    # contracting dims sharded over mismatched axes -> reshard before the
    # matmul; matched axes -> partial output (GSPMD all-reduces: the
    # planned Megatron row-parallel collective — expected, but charged)
    for ld, rd in zip(lc, rc):
        la = _axes_of(lsp[ld]) if lsp is not None else ()
        ra = _axes_of(rsp[rd]) if rsp is not None else ()
        if la and ra and la != ra:
            msgs.append((f"contracting dims sharded over different axes "
                         f"({la!r} vs {ra!r}) — implicit reshard before "
                         "the matmul", False))
            return None, msgs
        if la or ra:
            # matched axes, or one side sharded with the other replicated
            # (GSPMD slices the replicated operand locally — free): both
            # produce a partial output that must be all-reduced
            msgs.append((f"contracting dims sharded over "
                         f"{(la or ra)!r} — partial output, GSPMD "
                         "all-reduces (planned row-parallel collective)",
                         True))
    # batch dims sharded over mismatched axes -> one operand re-tiles
    # before the batched matmul (the hazard _merge_specs used to miss)
    batch_out: List = []
    for ld, rd in zip(lb, rb):
        la = _axes_of(lsp[ld]) if lsp is not None else ()
        ra = _axes_of(rsp[rd]) if rsp is not None else ()
        if la and ra and la != ra:
            msgs.append((f"batch dims sharded over different axes "
                         f"({la!r} vs {ra!r}) — implicit reshard before "
                         "the batched matmul", False))
            return None, msgs
        if la:
            batch_out.append(lsp[ld])
        elif ra:
            batch_out.append(rsp[rd])
        else:
            batch_out.append(None)
    # output layout: batch dims, then lhs free dims, then rhs free dims
    out: List = list(batch_out)
    for d in range(len(eqn.invars[0].aval.shape)):
        if d not in lc and d not in lb:
            out.append(lsp[d] if lsp is not None else None)
    for d in range(len(eqn.invars[1].aval.shape)):
        if d not in rc and d not in rb:
            out.append(rsp[d] if rsp is not None else None)
    return tuple(out), msgs


def _propagate_gather(eqn, ins):
    """Gather (embedding lookups, the paged-KV page reads).

    An indexed/collapsed dim that is sharded is the *vocab-parallel*
    pattern — GSPMD lowers it to a masked local lookup + all-reduce (or
    an all-gather of the table): planned, so an *expected* event. A
    window dim whose slice is partial while sharded forces a genuine
    re-tile (unexpected). Full-slice window dims keep their layout and
    propagate into the matching output offset dims.
    """
    sp = ins[0] if ins else None
    if sp is None or not any(e is not None for e in sp):
        return None, []
    dnums = eqn.params["dimension_numbers"]
    slice_sizes = eqn.params["slice_sizes"]
    op_shape = eqn.invars[0].aval.shape
    out_shape = eqn.outvars[0].aval.shape
    batching = tuple(getattr(dnums, "operand_batching_dims", ()) or ())
    collapsed = set(dnums.collapsed_slice_dims) | set(batching)
    indexed = set(dnums.start_index_map)
    msgs: List[Tuple[str, bool]] = []
    out: List = [None] * len(out_shape)
    window_dims = [d for d in range(len(op_shape)) if d not in collapsed]
    for out_d, op_d in zip(sorted(dnums.offset_dims), window_dims):
        entry = sp[op_d]
        if entry is None:
            continue
        if op_d in indexed or int(slice_sizes[op_d]) != int(op_shape[op_d]):
            msgs.append((f"gather slices through dim {op_d} sharded over "
                         f"{_axes_of(entry)!r} — implicit reshard to "
                         "re-tile the window", False))
        elif 0 <= out_d < len(out_shape):
            out[out_d] = entry
    for op_d in sorted(collapsed):
        entry = sp[op_d]
        if entry is not None:
            msgs.append((f"gather indexes dim {op_d} sharded over "
                         f"{_axes_of(entry)!r} — planned vocab/page-"
                         "parallel lookup (masked + all-reduce)", True))
    out_spec = tuple(out)
    if not any(e is not None for e in out_spec):
        out_spec = None
    return out_spec, msgs


def _propagate_scatter(eqn, ins):
    """Scatter (the paged-KV cache write path).

    Scatter preserves the operand's layout, so the output inherits its
    spec — UNLESS the scattered-into dims are themselves sharded (the
    updates land on other shards: GSPMD must all-to-all them), or the
    updates' window dims are sharded differently from the operand's.
    """
    osp = ins[0] if ins else None
    usp = ins[2] if len(ins) > 2 else None
    if osp is None and usp is None:
        return None, []
    dnums = eqn.params["dimension_numbers"]
    ndim = len(eqn.invars[0].aval.shape)
    inserted = set(dnums.inserted_window_dims) | \
        set(getattr(dnums, "operand_batching_dims", ()) or ())
    scattered = set(dnums.scatter_dims_to_operand_dims) | inserted
    msgs: List[Tuple[str, bool]] = []
    if osp is not None:
        for d in sorted(scattered):
            if d < len(osp) and osp[d] is not None:
                msgs.append((f"scatter writes into dim {d} sharded over "
                             f"{_axes_of(osp[d])!r} — GSPMD must "
                             "all-to-all the updates across shards",
                             False))
    # window dims: operand dims not inserted map onto update_window_dims
    # in order; a mismatch re-tiles the updates before the write
    if osp is not None and usp is not None:
        window = [d for d in range(ndim) if d not in inserted]
        for upd_d, op_d in zip(sorted(dnums.update_window_dims), window):
            oe = osp[op_d] if op_d < len(osp) else None
            ue = usp[upd_d] if upd_d < len(usp) else None
            if oe is not None and ue is not None and \
                    _axes_of(oe) != _axes_of(ue):
                msgs.append((f"scatter updates shard dim {upd_d} over "
                             f"{_axes_of(ue)!r} but the operand window "
                             f"dim {op_d} is over {_axes_of(oe)!r} — "
                             "implicit reshard of the updates", False))
    return osp, msgs


# ---- OpDecl.spmd cross-check ------------------------------------------------

def check_spmd_notes(decls) -> List[Tuple[str, str]]:
    """Cross-check each OpDecl's declared spmd note against observed
    eval_shape behavior: an op claiming ``elementwise`` must preserve the
    input shape; one claiming ``reduce`` must not. Impls needing extra
    required args are skipped (the note is unverifiable cheaply, not
    wrong). Returns (op-name, message) pairs.
    """
    import contextlib

    import jax as _jax
    import jax.numpy as _jnp

    from ...framework import random as _random

    @contextlib.contextmanager
    def _rng_guard():
        # stateful-RNG impls call next_key(); keep the abstract probe
        # from leaking a tracer into the process RNG state
        prev = _random.get_rng_state()
        try:
            with _random.rng_context(_jax.random.key(0)):
                yield
        finally:
            _random.set_rng_state(prev)

    problems: List[Tuple[str, str]] = []
    probe = _jax.ShapeDtypeStruct((4, 6), _jnp.float32)
    for d in decls:
        note = str(getattr(d, "spmd", "") or "")
        if note not in ("elementwise", "reduce"):
            continue
        try:
            with _rng_guard():
                out = _jax.eval_shape(d.impl, probe)
        except Exception:  # pdlint: disable=silent-exception -- unverifiable-cheaply (impl needs attrs) is a skip, not a fault
            continue
        leaves = _jax.tree_util.tree_leaves(out)
        if not leaves:
            continue
        shape = tuple(leaves[0].shape)
        if note == "elementwise" and shape != tuple(probe.shape):
            # tensor-LIST ops (add_n): elementwise over the list entries
            # — re-probe with a list before calling the note a lie
            try:
                with _rng_guard():
                    lo = _jax.eval_shape(d.impl, [probe, probe])
                lv = _jax.tree_util.tree_leaves(lo)
                if lv and tuple(lv[0].shape) == tuple(probe.shape):
                    continue
            except Exception:  # pdlint: disable=silent-exception -- list re-probe failing just confirms the single-array verdict below
                pass
            problems.append((d.name,
                             f"op {d.name!r} declares spmd='elementwise' "
                             f"but maps {tuple(probe.shape)} -> {shape} "
                             "(propagation would mis-shard it)"))
        elif note == "reduce" and shape == tuple(probe.shape):
            problems.append((d.name,
                             f"op {d.name!r} declares spmd='reduce' but "
                             "preserves the input shape"))
    return problems
