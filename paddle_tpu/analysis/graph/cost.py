"""preflight-cost: param/activation bytes and FLOPs from the jaxpr alone.

The XLA fusion-analysis result (PAPERS.md) is that the traced graph
carries enough structure for cost reasoning before any code is emitted;
here that buys the serving property the reference got from its
allocator dry-run: refuse a model that cannot fit BEFORE touching the
device, with numbers in the refusal message.

Estimates are deliberately coarse and deliberately *upper-bound-ish*:

- ``param_bytes`` — exact (from the functional-state avals).
- ``peak_activation_bytes`` — the widest single eqn's output bytes plus
  its input bytes (XLA fuses aggressively, so liveness-accurate numbers
  would require its buffer assignment; the widest-eqn bound is what the
  admission decision needs).
- ``flops`` — dot_general/conv as 2·M·N·K-style MACs, elementwise and
  reductions as one FLOP per element. Good to ~2x, which is enough to
  rank models and spot the accidental O(n²) at preflight.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict

from .trace import TracedGraph, iter_eqns


@dataclasses.dataclass
class CostReport:
    param_bytes: int = 0
    peak_activation_bytes: int = 0
    flops: int = 0
    output_bytes: int = 0
    eqns: int = 0

    def total_resident_bytes(self) -> int:
        """What must fit at once: weights + the widest live working set."""
        return self.param_bytes + self.peak_activation_bytes

    def as_dict(self) -> Dict[str, int]:
        return dataclasses.asdict(self)


def _nbytes(aval) -> int:
    import jax.numpy as jnp

    n = int(jnp.dtype(aval.dtype).itemsize)
    for s in aval.shape:
        n *= int(s)
    return n


def _numel(aval) -> int:
    n = 1
    for s in aval.shape:
        n *= int(s)
    return n


def _dot_flops(eqn) -> int:
    ((lc, _rc), (lb, _rb)) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval
    out = eqn.outvars[0].aval
    k = 1
    for d in lc:
        k *= int(lhs.shape[d])
    return 2 * _numel(out) * k


def _conv_flops(eqn) -> int:
    rhs = eqn.invars[1].aval  # kernel
    out = eqn.outvars[0].aval
    per_out = 1
    for s in rhs.shape[:-1] if len(rhs.shape) else ():
        per_out *= int(s)
    return 2 * _numel(out) * max(per_out, 1)


def estimate(traced: TracedGraph) -> CostReport:
    """Cost of one forward pass of the traced program."""
    rep = CostReport(param_bytes=traced.param_bytes())
    if not traced.ok:
        return rep
    cj = traced.closed_jaxpr
    for aval in cj.out_avals:
        if hasattr(aval, "shape"):
            rep.output_bytes += _nbytes(aval)
    for _path, eqn in iter_eqns(cj.jaxpr):
        rep.eqns += 1
        prim = eqn.primitive.name
        outs = [v.aval for v in eqn.outvars if hasattr(v, "aval")]
        ins = [v.aval for v in eqn.invars
               if hasattr(v, "aval") and hasattr(v.aval, "shape")]
        width = sum(_nbytes(a) for a in outs if hasattr(a, "shape")) + \
            sum(_nbytes(a) for a in ins)
        rep.peak_activation_bytes = max(rep.peak_activation_bytes, width)
        if prim == "dot_general":
            rep.flops += _dot_flops(eqn)
        elif prim.startswith("conv_general"):
            rep.flops += _conv_flops(eqn)
        elif prim in ("pjit", "custom_vjp_call_jaxpr", "custom_jvp_call",
                      "custom_vjp_call", "scan", "while", "cond"):
            continue  # inner eqns are walked by iter_eqns themselves
        else:
            rep.flops += sum(_numel(a) for a in outs if hasattr(a, "shape"))
    return rep


def kv_cache_bytes(config: Any, max_batch: int, max_len: int) -> int:
    """Decode-cache footprint for a served causal LM config (the paged
    pool serving.py allocates): layers · 2 (K+V) · heads_kv · max_batch ·
    max_len · head_dim · itemsize. Families without the fields return 0
    (their engines size caches differently)."""
    import jax.numpy as jnp

    try:
        layers = int(config.num_hidden_layers)
        hk = int(config.num_key_value_heads)
        from ...models.llama import head_dim_of

        d = int(head_dim_of(config))
        itemsize = int(jnp.dtype(config.dtype).itemsize)
    except (AttributeError, TypeError):
        return 0
    return layers * 2 * hk * max_batch * max_len * d * itemsize
