"""Runtime lock-order witness (``FLAGS_lock_witness``, default off).

The static lock-order graph (:mod:`lock_graph`) proves what *can* nest;
the witness observes what *does*. When the flag is on, locks created
through :func:`make_lock` / :func:`make_rlock` are thin instrumented
wrappers: each acquisition records the per-thread stack of witness locks
already held, every (held, acquired) pair becomes an observed order
edge, and two validations run on each NEW edge:

- **inversion** — the reverse edge was already observed at runtime: two
  threads have taken the same two locks in opposite orders, the textbook
  AB/BA deadlock, caught the first time it happens rather than the time
  it hangs;
- **static-order conflict** — the static graph contains a path from the
  acquired lock back to the held one (so the static analysis says this
  nesting direction is the *wrong way around* versus the code's own
  order) and no forward edge sanctioning it.

A violation appends to the report, emits a ``lock.order_violation``
flight-recorder event (with both acquisition chains), and rides incident
bundles (``bundle["lock_witness"]``) — the serving-cluster dryrun gate
runs with the witness on and asserts zero violations over the real
router+worker topology, validating the static graph against execution
the way ``graph-cost-table`` validates the autotuner.

Off is free: ``make_lock`` returns a plain ``threading.Lock``; the only
cost is one flag read at construction time.
"""
from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["make_lock", "make_rlock", "witness_enabled", "report",
           "reset", "violations", "load_static_edges", "WitnessLock"]

_STACK_LIMIT = 12       # frames kept per first-seen edge


def witness_enabled() -> bool:
    try:
        from ...utils.flags import flag

        return bool(flag("FLAGS_lock_witness"))
    except (ImportError, KeyError):
        return False    # stripped build without the flag registry


class _Witness:
    """Process-wide observed-order state. Internal synchronisation is a
    plain lock (never a WitnessLock — the witness must not observe
    itself)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._edges: Dict[Tuple[str, str], dict] = {}
        self._violations: List[dict] = []
        self._locks_seen: Set[str] = set()
        self._static: Optional[Set[Tuple[str, str]]] = None
        self._static_reach: Optional[Dict[str, Set[str]]] = None
        self._static_tried = False

    # ---- held-stack bookkeeping (thread-local, no lock needed) --------
    def _held(self) -> List[str]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def on_acquire(self, name: str):
        held = self._held()
        new_pairs = []
        with self._lock:
            self._locks_seen.add(name)
            for h in dict.fromkeys(held):       # dedupe, keep order
                if h == name:
                    continue
                edge = self._edges.get((h, name))
                if edge is None:
                    new_pairs.append(h)
                else:
                    edge["count"] += 1
        if new_pairs:
            stack = [f"{f.filename.rsplit(os.sep, 1)[-1]}:{f.lineno} "
                     f"{f.name}" for f in
                     traceback.extract_stack(limit=_STACK_LIMIT)[:-2]]
            for h in new_pairs:
                self._record_edge(h, name, stack)
        held.append(name)

    def on_release(self, name: str):
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    # ---- edges + validation -------------------------------------------
    def _record_edge(self, src: str, dst: str, stack: List[str]):
        with self._lock:
            if (src, dst) in self._edges:
                self._edges[(src, dst)]["count"] += 1
                return
            self._edges[(src, dst)] = {
                "count": 1,
                "thread": threading.current_thread().name,
                "stack": stack,
            }
            reverse = self._edges.get((dst, src))
        kind = None
        prior = None
        if reverse is not None:
            kind = "inversion"
            prior = reverse["stack"]
        else:
            self._ensure_static()
            with self._lock:
                reach = self._static_reach
            if (reach is not None and src in reach.get(dst, ())
                    and (src, dst) not in (self._static or ())):
                kind = "static_conflict"
        if kind is not None:
            self._violation(kind, src, dst, stack, prior)

    def _violation(self, kind, src, dst, stack, prior):
        entry = {
            "kind": kind,
            "edge": [src, dst],
            "thread": threading.current_thread().name,
            "stack": stack,
            "prior_stack": prior,
        }
        with self._lock:
            self._violations.append(entry)
        try:
            from ...observability import flightrecorder as _frec

            rec = _frec.RECORDER
            if rec.enabled:
                rec.record(_frec.EV_LOCK_ORDER, violation=kind, held=src,
                           acquired=dst,
                           thread=threading.current_thread().name)
        except Exception:  # pdlint: disable=silent-exception -- the witness must never take its process down; the violation is still in the report
            pass

    # ---- static graph --------------------------------------------------
    def _ensure_static(self):
        with self._lock:
            if self._static_tried:
                return
            self._static_tried = True
        try:
            import paddle_tpu

            root = os.path.dirname(os.path.dirname(
                os.path.abspath(paddle_tpu.__file__)))
            self.set_static(load_static_edges(root))
        except Exception:  # pdlint: disable=silent-exception -- no source tree at runtime (installed wheel): inversion detection still runs, static cross-check reports unavailable
            pass

    def set_static(self, edges: Set[Tuple[str, str]]):
        """Install the static edge set (also disables the lazy load —
        an explicit graph must not be clobbered by the repo scan)."""
        reach: Dict[str, Set[str]] = {}
        for a, b in edges:
            reach.setdefault(a, set()).add(b)
        changed = True
        while changed:
            changed = False
            for a in list(reach):
                new = set()
                for b in reach[a]:
                    new |= reach.get(b, set())
                if not new <= reach[a]:
                    reach[a] |= new
                    changed = True
        with self._lock:
            self._static = set(edges)
            self._static_reach = reach
            self._static_tried = True

    # ---- surfaces -------------------------------------------------------
    def report(self) -> dict:
        with self._lock:
            return {
                "enabled": witness_enabled(),
                "locks": sorted(self._locks_seen),
                "edges": [
                    {"from": a, "to": b, "count": e["count"],
                     "thread": e["thread"]}
                    for (a, b), e in sorted(self._edges.items())],
                "violations": list(self._violations),
                "static_edges": (len(self._static)
                                 if self._static is not None else None),
                "unmodeled_edges": sorted(
                    f"{a} -> {b}" for (a, b) in self._edges
                    if self._static is not None
                    and (a, b) not in self._static),
            }

    def reset(self):
        with self._lock:
            self._edges.clear()
            self._violations.clear()
            self._locks_seen.clear()


WITNESS = _Witness()


class WitnessLock:
    """A Lock/RLock wrapper reporting acquisition order to the witness.
    Context-manager compatible, and ``threading.Condition`` accepts it
    as its underlying lock (Condition's default ``_release_save`` /
    ``_acquire_restore`` / ``_is_owned`` fallbacks only need
    acquire/release — so even a Condition's wait/notify traffic is
    witnessed)."""

    __slots__ = ("name", "_inner")

    def __init__(self, name: str, rlock: bool = False):
        self.name = name
        self._inner = threading.RLock() if rlock else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            WITNESS.on_acquire(self.name)
        return ok

    def release(self):
        WITNESS.on_release(self.name)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


def make_lock(name: str):
    """A lock for cross-thread state: plain ``threading.Lock`` normally,
    a witnessed wrapper under ``FLAGS_lock_witness``. ``name`` must be
    the static identity ``ClassName.attr`` so runtime order validates
    against the static graph."""
    if witness_enabled():
        return WitnessLock(name)
    return threading.Lock()


def make_rlock(name: str):
    if witness_enabled():
        return WitnessLock(name, rlock=True)
    return threading.RLock()


def report() -> dict:
    return WITNESS.report()


def violations() -> List[dict]:
    return WITNESS.report()["violations"]


def reset():
    WITNESS.reset()


def load_static_edges(root: str) -> Set[Tuple[str, str]]:
    from .lock_graph import static_edge_pairs

    return static_edge_pairs(root)
