"""The concurrency rules: thread-naming (AST) + the three whole-program
rules over the thread model and lock-order graph (opt-in via ``pdlint
--threads``, mirroring how graph rules opt in via ``--graph``).

Findings point at real file:line sites, so the inline ``# pdlint:
disable=<id>`` pragma and the baseline machinery work unchanged; witness
chains (the file:line path proving an edge or a blocking reach) ride
``Finding.data`` like the shard-solver's ledger.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List

from ..core import Finding, ModuleContext, ProjectRule, Rule, register_rule
from .lock_graph import build_lock_graph
from .model import ProjectModel, get_model

__all__ = ["deadlock_findings", "blocking_findings",
           "shared_state_findings", "naming_findings"]

_CTOR_METHODS = {"__init__", "__new__"}


# ---- thread-naming (AST, always on) -----------------------------------------

@register_rule
class ThreadNamingRule(Rule):
    id = "thread-naming"
    rationale = ("an unnamed thread shows up as Thread-N in incident-"
                 "bundle all-thread stack dumps — unattributable at "
                 "3am; every spawn site passes name=")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if ctx.resolve_call(node.func) not in ("threading.Thread",
                                                   "Thread"):
                continue
            if any(kw.arg == "name" for kw in node.keywords):
                continue
            if len(node.args) >= 3:     # positional name
                continue
            yield self.finding(
                ctx, node.lineno,
                "threading.Thread(...) without name= — unnamed threads "
                "make incident-bundle stack dumps unattributable")


# ---- the whole-program rules ------------------------------------------------

def _suppressed(model: ProjectModel, file: str, line: int,
                rule_id: str) -> bool:
    mod = model.modules.get(file)
    return mod is not None and mod.ctx.suppressed(line, rule_id)


def _symbol(model: ProjectModel, file: str, line: int) -> str:
    mod = model.modules.get(file)
    return mod.ctx.symbol_for_line(line) if mod is not None else ""


def deadlock_findings(model: ProjectModel,
                      rule_id: str = "thread-deadlock") -> List[Finding]:
    graph = build_lock_graph(model)
    out = []
    for cycle in graph.cycles():
        edges = [graph.edges[pair] for pair in cycle]
        path = " -> ".join([cycle[0][0]] + [b for (_a, b) in cycle])
        file, line, _note = edges[0].witness[0]
        witness_txt = "; ".join(
            " | ".join(e.chain()) for e in edges)
        if _suppressed(model, file, line, rule_id):
            continue
        out.append(Finding(
            file=file, line=line, rule=rule_id,
            symbol=_symbol(model, file, line),
            message=(f"lock-order cycle {path} — two threads walking it "
                     f"from different ends deadlock; witness: "
                     f"{witness_txt}"),
            data={"cycle": [a for (a, _b) in cycle] + [cycle[0][0]],
                  "edges": [{"from": e.src, "to": e.dst,
                             "witness": e.chain()} for e in edges]}))
    return out


def blocking_findings(model: ProjectModel,
                      rule_id: str = "thread-blocking-under-lock"
                      ) -> List[Finding]:
    graph = build_lock_graph(model)
    out, seen = [], set()
    for site in graph.blocking:
        key = (site.file, site.line, site.lock, site.call)
        if key in seen:
            continue
        seen.add(key)
        if _suppressed(model, site.file, site.line, rule_id):
            continue
        out.append(Finding(
            file=site.file, line=site.line, rule=rule_id,
            symbol=_symbol(model, site.file, site.line),
            message=(f"blocking call ({site.call}) reachable while "
                     f"holding {site.lock} — every other thread needing "
                     "the lock stalls behind the wait; move the I/O "
                     "outside the critical section"),
            data={"lock": site.lock, "chain": site.chain}))
    return out


_THREADSAFE_TYPES = {"local"}   # threading.local attrs are per-thread


def shared_state_findings(model: ProjectModel,
                          rule_id: str = "thread-shared-state"
                          ) -> List[Finding]:
    graph = build_lock_graph(model)
    out = []
    for cls_key, attrs in sorted(graph.accesses.items()):
        file, cls_qual = cls_key
        cls = model.modules[file].classes[cls_qual]
        for attr, recs in sorted(attrs.items()):
            tok = cls.attr_types.get(attr, "")
            if tok.rsplit(".", 1)[-1] in _THREADSAFE_TYPES:
                continue
            recs = [r for r in recs
                    if model.functions[r[0]].name not in _CTOR_METHODS]
            writes = [r for r in recs if r[2].startswith("write")]
            if not writes:
                continue
            threads = set()
            for fkey, _line, _kind, _locked, _m in recs:
                threads |= model.threads.get(fkey, set())
            if len(threads) < 2:
                continue
            unguarded = [r for r in recs if not r[3]]
            if not unguarded:
                continue
            # lock-free publication: every write assigns a constant —
            # a GIL-atomic store readers may legally race (the guarded
            # fast-path flag idiom)
            if all(r[2] == "write-const" for r in writes):
                continue
            # anchor at the first unguarded WRITE when there is one —
            # that's the mutation a pragma would justify
            anchor = next((r for r in unguarded
                           if r[2].startswith("write")), unguarded[0])
            _fk, line, kind, _lk, mname = anchor
            if _suppressed(model, file, line, rule_id):
                continue
            verb = {"read": "read", "write": "written",
                    "write-const": "written",
                    "write-rmw": "read-modify-written"}.get(kind, kind)
            g = next((r for r in recs if r[3]), None)
            guarded_note = (f"; guarded in {g[4]}() line {g[1]}"
                            if g else "; no access holds a lock")
            out.append(Finding(
                file=file, line=line, rule=rule_id,
                symbol=_symbol(model, file, line),
                message=(f"attribute 'self.{attr}' of '{cls.name}' is "
                         f"shared across threads "
                         f"{{{', '.join(sorted(threads))}}} but {verb} "
                         f"without a lock in "
                         f"{mname}(){guarded_note} — guard every access "
                         "or confine the attribute to one thread"),
                data={"threads": sorted(threads),
                      "accesses": [
                          {"method": m, "line": ln, "kind": k,
                           "locked": lk,
                           "threads": sorted(model.threads.get(fk, ()))}
                          for fk, ln, k, lk, m in recs[:12]]}))
    return out


def naming_findings(model: ProjectModel) -> List[Finding]:
    """Spawn sites without a name (the model's view — the AST rule is
    the enforced twin; this powers the model fixture tests)."""
    return [Finding(file=sp.file, line=sp.line, rule="thread-naming",
                    message="unnamed thread", symbol="")
            for sp in model.spawn_sites if not sp.has_name]


class _ThreadRule(ProjectRule):
    """Base: whole-program rules opt in via ``--threads``."""

    threads = True

    def _findings(self, model: ProjectModel) -> List[Finding]:
        raise NotImplementedError

    def check_project(self, root: str) -> Iterable[Finding]:
        return self._findings(get_model(root))


@register_rule
class ThreadDeadlockRule(_ThreadRule):
    id = "thread-deadlock"
    rationale = ("a cycle in the lock-order graph is a deadlock waiting "
                 "for the right interleaving; the finding carries the "
                 "full file:line witness chain")

    def _findings(self, model):
        return deadlock_findings(model, self.id)


@register_rule
class BlockingUnderLockRule(_ThreadRule):
    id = "thread-blocking-under-lock"
    rationale = ("sleep/shm/socket/barrier/subprocess waits reachable "
                 "under a held lock convoy every thread that needs it — "
                 "I/O belongs outside critical sections")

    def _findings(self, model):
        return blocking_findings(model, self.id)


@register_rule
class ThreadSharedStateRule(_ThreadRule):
    id = "thread-shared-state"
    rationale = ("an attribute reachable from two threads with any "
                 "unguarded access is a lost-update/torn-read race — "
                 "the whole-program growth of lock-discipline")

    def _findings(self, model):
        return shared_state_findings(model, self.id)
