"""The thread model: which threads can execute each function.

Pure-AST whole-program analysis (no paddle_tpu import — fixture snippets
unit-test it in isolation, like every AST rule). Three steps:

1. **Index** every module: functions (nested defs included, qualnames
   like ``Cls.method.inner``), classes (bases, methods, the inferred
   type of every ``self.X = ClassName(...)`` attribute), import aliases.
2. **Resolve** a conservative call graph. Only confident edges exist:
   ``self.m()`` through the project MRO, bare names through nested-def /
   module / import scope, receivers whose type is known from a local
   ``x = ClassName(...)`` or a ctor-assigned attribute, ``super().m()``,
   and the serving handler's ``server_obj`` dispatch (resolved against
   every project class that defines ``_make_handler``). A method
   *reference* (``self.m`` passed as a callback, returned from
   ``_post_handler``, a nested def passed as an argument) is an edge
   from the referencing function — the callback runs on whatever thread
   the referencer hands it to, which the closure then propagates.
3. **Assign threads.** Roots: each ``threading.Thread(target=T)`` site
   starts thread *name* (its ``name=`` kwarg, else ``thread@file:line``)
   at ``T``; every method of a project ``BaseHTTPRequestHandler`` /
   ``ServingHandlerBase`` subclass runs on ``http-handler``; every
   public function/method that is neither a thread target nor a handler
   method is callable from ``main``. Private functions inherit threads
   purely from their callers — "a helper runs on whatever thread calls
   it" is the model.

The result (``ProjectModel.threads``) feeds the cross-thread shared
state rule and the lock-order graph; ``threads_of()`` is the query API
the fixture tests drive.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from ..core import ModuleContext, iter_py_files, module_context

__all__ = ["ProjectModel", "get_model", "FuncKey", "SpawnSite"]

FuncKey = Tuple[str, str]      # (rel_file, qualname)

MAIN_THREAD = "main"
HANDLER_THREAD = "http-handler"

_THREAD_CALLS = ("threading.Thread", "Thread")
_HANDLER_BASES = {"BaseHTTPRequestHandler", "ServingHandlerBase"}


class SpawnSite:
    """One ``threading.Thread(...)`` construction."""

    __slots__ = ("file", "line", "target", "thread_name", "has_name",
                 "daemon")

    def __init__(self, file, line, target, thread_name, has_name, daemon):
        self.file = file
        self.line = line
        self.target: Optional[FuncKey] = target
        self.thread_name = thread_name
        self.has_name = has_name
        self.daemon = daemon


class FuncInfo:
    __slots__ = ("file", "qualname", "name", "line", "node", "cls_qual")

    def __init__(self, file, qualname, name, line, node, cls_qual):
        self.file = file
        self.qualname = qualname
        self.name = name
        self.line = line
        self.node = node
        self.cls_qual = cls_qual      # enclosing class qualname or None

    @property
    def key(self) -> FuncKey:
        return (self.file, self.qualname)


class ClassInfo:
    __slots__ = ("file", "qualname", "name", "node", "bases", "methods",
                 "attr_types")

    def __init__(self, file, qualname, name, node, bases):
        self.file = file
        self.qualname = qualname
        self.name = name
        self.node = node
        self.bases: List[str] = bases          # resolved dotted strings
        self.methods: Dict[str, str] = {}      # name -> qualname
        self.attr_types: Dict[str, str] = {}   # self.X -> dotted type

    @property
    def key(self):
        return (self.file, self.qualname)


class ModuleInfo:
    __slots__ = ("file", "ctx", "functions", "classes")

    def __init__(self, file, ctx):
        self.file = file
        self.ctx: ModuleContext = ctx
        self.functions: Dict[str, FuncInfo] = {}   # qualname -> info
        self.classes: Dict[str, ClassInfo] = {}    # qualname -> info


def _resolve_dotted(ctx: ModuleContext, node) -> str:
    """Dotted path of an expression through the import alias map (same
    resolution rule as ``ModuleContext.resolve_call``)."""
    return ctx.resolve_call(node)


class ProjectModel:
    """The indexed project + call graph + thread assignment."""

    MODULE_BODY = "<module>"   # pseudo-function for top-level statements

    def __init__(self, sources: Dict[str, str],
                 contexts: Optional[Dict[str, ModuleContext]] = None):
        # ``contexts`` are pre-parsed ModuleContexts (the shared
        # ``core.module_context`` cache): one parse per file per run,
        # and pragma-usage marks land on the SAME context objects the
        # driver's unused-disable check reads. ``sources`` alone (the
        # fixture-test path) parses privately.
        self._contexts = contexts or {}
        self.modules: Dict[str, ModuleInfo] = {}
        self.functions: Dict[FuncKey, FuncInfo] = {}
        self.classes_by_name: Dict[str, List[ClassInfo]] = {}
        self.edges: Dict[FuncKey, List[Tuple[FuncKey, int]]] = {}
        self.spawn_sites: List[SpawnSite] = []
        self.server_classes: List[ClassInfo] = []
        # per-Call-node resolution caches the lock-graph walk reuses
        self.call_targets: Dict[int, List[FuncKey]] = {}
        self.call_dotted: Dict[int, str] = {}
        self.recv_types: Dict[int, str] = {}
        self._spawn_target_ids: Set[int] = set()
        self.threads: Dict[FuncKey, Set[str]] = {}
        self._parse(sources)
        self._resolve_all()
        self._assign_threads()

    # ---- step 1: index ---------------------------------------------------
    def _parse(self, sources: Dict[str, str]):
        for file, src in sorted(sources.items()):
            try:
                ctx = self._contexts.get(file) or ModuleContext(file, src)
            except SyntaxError:
                continue
            mod = ModuleInfo(file, ctx)
            self.modules[file] = mod
            self._index_scope(mod, ctx.tree, qual="", cls_qual=None)
        for mod in self.modules.values():
            for cls in mod.classes.values():
                self.classes_by_name.setdefault(cls.name, []).append(cls)
        for mod in self.modules.values():
            for cls in mod.classes.values():
                self._scan_attr_types(mod, cls)
                if "_make_handler" in cls.methods:
                    self.server_classes.append(cls)

    def _index_scope(self, mod, node, qual, cls_qual):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{qual}.{child.name}" if qual else child.name
                fn = FuncInfo(mod.file, q, child.name, child.lineno, child,
                              cls_qual)
                mod.functions[q] = fn
                self.functions[fn.key] = fn
                if cls_qual is not None and qual == cls_qual:
                    mod.classes[cls_qual].methods.setdefault(child.name, q)
                self._index_scope(mod, child, q, cls_qual)
            elif isinstance(child, ast.ClassDef):
                q = f"{qual}.{child.name}" if qual else child.name
                bases = [b for b in
                         (_resolve_dotted(mod.ctx, base)
                          for base in child.bases) if b]
                mod.classes[q] = ClassInfo(mod.file, q, child.name, child,
                                           bases)
                self._index_scope(mod, child, q, cls_qual=q)
            else:
                self._index_scope(mod, child, qual, cls_qual)

    def _scan_attr_types(self, mod, cls):
        """``self.X = ClassName(...)`` anywhere in the class body gives
        attribute X a type token (dotted path, project or stdlib)."""
        for node in ast.walk(cls.node):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            dotted = _resolve_dotted(mod.ctx, node.value.func)
            if not dotted:
                continue
            for t in node.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    cls.attr_types.setdefault(t.attr, dotted)

    # ---- resolution helpers ---------------------------------------------
    def project_classes(self, dotted: str) -> List[ClassInfo]:
        """Project ClassInfos a dotted type token may refer to (matched
        on the final path component)."""
        if not dotted:
            return []
        return self.classes_by_name.get(dotted.rsplit(".", 1)[-1], [])

    def mro(self, cls: ClassInfo) -> List[ClassInfo]:
        """cls followed by its project base chain (BFS, cycle-safe)."""
        out, seen, queue = [], set(), [cls]
        while queue:
            c = queue.pop(0)
            if c.key in seen:
                continue
            seen.add(c.key)
            out.append(c)
            for b in c.bases:
                queue.extend(self.project_classes(b))
        return out

    def lookup_method(self, cls: ClassInfo, name: str) -> Optional[FuncKey]:
        for c in self.mro(cls):
            q = c.methods.get(name)
            if q is not None:
                return (c.file, q)
        return None

    def is_handler_class(self, cls: ClassInfo) -> bool:
        for c in self.mro(cls):
            for b in c.bases:
                if b.rsplit(".", 1)[-1] in _HANDLER_BASES:
                    return True
        return cls.name in _HANDLER_BASES

    def enclosing_class(self, fn: FuncInfo) -> Optional[ClassInfo]:
        if fn.cls_qual is None:
            return None
        return self.modules[fn.file].classes.get(fn.cls_qual)

    def attr_type(self, fn: FuncInfo, attr: str) -> str:
        cls = self.enclosing_class(fn)
        if cls is None:
            return ""
        for c in self.mro(cls):
            if attr in c.attr_types:
                return c.attr_types[attr]
        return ""

    # ---- step 2: the call graph -----------------------------------------
    def _resolve_all(self):
        for mod in self.modules.values():
            body_key = (mod.file, self.MODULE_BODY)
            self.edges.setdefault(body_key, [])
            self._resolve_scope_body(mod, mod.ctx.tree, body_key,
                                     func=None)
            for fn in mod.functions.values():
                self.edges.setdefault(fn.key, [])
                self._resolve_scope_body(mod, fn.node, fn.key, func=fn)

    def _resolve_scope_body(self, mod, scope_node, key, func):
        """Walk one function body (or the module body) without
        descending into nested defs (they are their own scopes), collect
        call/ref edges, local types, and Thread spawn sites."""
        local_types: Dict[str, str] = {}

        def walk(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    continue
                if isinstance(child, ast.Assign):
                    self._note_local_type(mod, func, child, local_types)
                if isinstance(child, ast.Call):
                    self._resolve_call_node(mod, func, key, child,
                                            local_types)
                elif (isinstance(child, ast.Attribute)
                        and isinstance(child.ctx, ast.Load)):
                    self._resolve_method_ref(mod, func, key, child)
                walk(child)

        walk(scope_node)

    def _note_local_type(self, mod, func, assign, local_types):
        v = assign.value
        token = ""
        if isinstance(v, ast.Call):
            token = _resolve_dotted(mod.ctx, v.func)
        elif isinstance(v, ast.Attribute) and func is not None:
            if (isinstance(v.value, ast.Name) and v.value.id == "self"):
                if v.attr == "server_obj":
                    token = "<server_obj>"
                else:
                    token = self.attr_type(func, v.attr)
        elif isinstance(v, ast.Name):
            token = local_types.get(v.id, "")
        if not token:
            return
        for t in assign.targets:
            if isinstance(t, ast.Name):
                local_types[t.id] = token

    def _receiver_type(self, mod, func, expr, local_types) -> str:
        """Type token of a call receiver expression, "" when unknown."""
        if isinstance(expr, ast.Name):
            if expr.id == "self":
                return "<self>"
            return local_types.get(expr.id, "")
        if isinstance(expr, ast.Attribute):
            if expr.attr == "server_obj":
                return "<server_obj>"
            if (isinstance(expr.value, ast.Name)
                    and expr.value.id == "self" and func is not None):
                return self.attr_type(func, expr.attr)
        if isinstance(expr, ast.Call):
            # chained ctor: ClassName(...).m()
            return _resolve_dotted(mod.ctx, expr.func)
        return ""

    def _method_candidates(self, mod, func, recv_token,
                           name) -> List[FuncKey]:
        if recv_token == "<self>" and func is not None:
            cls = self.enclosing_class(func)
            if cls is not None:
                got = self.lookup_method(cls, name)
                return [got] if got else []
            return []
        if recv_token == "<server_obj>":
            out = []
            for cls in self.server_classes:
                got = self.lookup_method(cls, name)
                if got:
                    out.append(got)
            return out
        out = []
        for cls in self.project_classes(recv_token):
            got = self.lookup_method(cls, name)
            if got:
                out.append(got)
        return out

    def _bare_name_targets(self, mod, func, name) -> List[FuncKey]:
        """A bare ``name`` in call position: nearest nested def in the
        enclosing qualname chain, else a module-level function, else a
        project function reached through a from-import."""
        if func is not None:
            parts = func.qualname.split(".")
            for i in range(len(parts), 0, -1):
                q = ".".join(parts[:i] + [name])
                if q in self.modules[func.file].functions:
                    return [(func.file, q)]
        if name in mod.functions:
            return [(mod.file, name)]
        if name in mod.classes:      # same-module class: its ctor
            got = self.lookup_method(mod.classes[name], "__init__")
            return [got] if got else []
        dotted = mod.ctx.aliases.get(name, "")
        if dotted:
            return self._dotted_targets(mod, dotted)
        return []

    def _dotted_targets(self, mod, dotted) -> List[FuncKey]:
        """``pkg.module.fn`` / ``.module.fn`` -> a project module-level
        function or ``Class.__init__`` (matched on the trailing
        components; project files are keyed by path, so match module
        basename + symbol)."""
        parts = [p for p in dotted.split(".") if p]
        if not parts:
            return []
        name = parts[-1]
        # class constructor?
        ctors = []
        for cls in self.project_classes(name):
            got = self.lookup_method(cls, "__init__")
            if got:
                ctors.append(got)
            else:
                # a class with no project __init__ still anchors threads
                # at its methods through other edges; nothing to call
                pass
        if ctors:
            return ctors
        modbase = parts[-2] if len(parts) >= 2 else None
        out = []
        for file, m in self.modules.items():
            if name in m.functions and m.functions[name].cls_qual is None:
                base = os.path.basename(file)[:-3]
                if modbase is None or base == modbase or modbase == name:
                    out.append((file, name))
        # a unique project-wide match is safe even without module hints
        if not out:
            hits = [(f, name) for f, m in self.modules.items()
                    if name in m.functions
                    and m.functions[name].cls_qual is None]
            if len(hits) == 1:
                out = hits
        return out

    def _callable_targets(self, mod, func, node, local_types,
                          record=None) -> List[FuncKey]:
        """Resolve a callable-position expression (call func or callback
        argument) to project FuncKeys."""
        if isinstance(node, ast.Name):
            return self._bare_name_targets(mod, func, node.id)
        if isinstance(node, ast.Attribute):
            # super().m()
            if (isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Name)
                    and node.value.func.id == "super"
                    and func is not None):
                cls = self.enclosing_class(func)
                if cls is not None:
                    for c in self.mro(cls)[1:]:
                        q = c.methods.get(node.attr)
                        if q is not None:
                            return [(c.file, q)]
                return []
            recv = self._receiver_type(mod, func, node.value, local_types)
            if record is not None:
                record(recv)
            if recv:
                return self._method_candidates(mod, func, recv, node.attr)
            dotted = _resolve_dotted(mod.ctx, node)
            if dotted:
                return self._dotted_targets(mod, dotted)
        return []

    def _resolve_call_node(self, mod, func, key, call, local_types):
        dotted = _resolve_dotted(mod.ctx, call.func)
        self.call_dotted[id(call)] = dotted
        if isinstance(call.func, ast.Attribute):
            recv = self._receiver_type(mod, func, call.func.value,
                                       local_types)
            if recv:
                self.recv_types[id(call)] = recv
        if dotted in _THREAD_CALLS:
            self._spawn_site(mod, func, call, local_types)
            return
        targets = self._callable_targets(mod, func, call.func, local_types)
        self.call_targets[id(call)] = targets
        for t in targets:
            self.edges[key].append((t, call.lineno))
        # callbacks in argument position run on a thread the callee
        # chooses; attributing them to the passer is the conservative
        # closure (on_token handed to the engine, signal handlers, ...)
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if isinstance(arg, (ast.Name, ast.Attribute)):
                for t in self._callable_targets(mod, func, arg,
                                                local_types):
                    self.edges[key].append((t, call.lineno))

    def _resolve_method_ref(self, mod, func, key, attr_node):
        """A bare ``self.m`` load (returned bound method, stored
        callback) is an edge — the serving dispatch returns handler
        methods from ``_post_handler``."""
        if func is None or id(attr_node) in self._spawn_target_ids:
            return
        if not (isinstance(attr_node.value, ast.Name)
                and attr_node.value.id == "self"):
            return
        cls = self.enclosing_class(func)
        if cls is None:
            return
        got = self.lookup_method(cls, attr_node.attr)
        if got is not None:
            self.edges[key].append((got, attr_node.lineno))

    def _spawn_site(self, mod, func, call, local_types):
        target = None
        thread_name, has_name, daemon = None, False, False
        target_expr = None
        for kw in call.keywords:
            if kw.arg == "target":
                target_expr = kw.value
            elif kw.arg == "name":
                has_name = True
                if isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    thread_name = kw.value.value
            elif kw.arg == "daemon":
                daemon = bool(isinstance(kw.value, ast.Constant)
                              and kw.value.value)
        if target_expr is None and len(call.args) >= 2:
            target_expr = call.args[1]
        if target_expr is not None:
            # the target is a thread ROOT, not a call from the spawning
            # function — keep the generic ref walk off it
            self._spawn_target_ids.add(id(target_expr))
            cands = self._callable_targets(mod, func, target_expr,
                                           local_types)
            target = cands[0] if cands else None
        if thread_name is None:
            thread_name = f"thread@{mod.file}:{call.lineno}"
        self.spawn_sites.append(SpawnSite(
            mod.file, call.lineno, target, thread_name, has_name, daemon))

    # ---- step 3: threads -------------------------------------------------
    @staticmethod
    def _is_public(name: str) -> bool:
        return (not name.startswith("_")
                or (name.startswith("__") and name.endswith("__")))

    def _assign_threads(self):
        roots: List[Tuple[FuncKey, str]] = []
        target_keys = set()
        for sp in self.spawn_sites:
            if sp.target is not None:
                roots.append((sp.target, sp.thread_name))
                target_keys.add(sp.target)
        handler_methods = set()
        for mod in self.modules.values():
            for cls in mod.classes.values():
                if self.is_handler_class(cls):
                    for q in cls.methods.values():
                        k = (mod.file, q)
                        handler_methods.add(k)
                        roots.append((k, HANDLER_THREAD))
        for key, fn in self.functions.items():
            if key in target_keys or key in handler_methods:
                continue
            if self._is_public(fn.name):
                roots.append((key, MAIN_THREAD))
        for mod in self.modules.values():
            roots.append(((mod.file, self.MODULE_BODY), MAIN_THREAD))
        # propagate each label through the call graph to a fixpoint
        self.threads = {}
        work = []
        for key, label in roots:
            s = self.threads.setdefault(key, set())
            if label not in s:
                s.add(label)
                work.append((key, label))
        while work:
            key, label = work.pop()
            for callee, _line in self.edges.get(key, ()):
                s = self.threads.setdefault(callee, set())
                if label not in s:
                    s.add(label)
                    work.append((callee, label))

    # ---- query API -------------------------------------------------------
    def threads_of(self, file: str, qualname: str) -> Set[str]:
        return set(self.threads.get((file, qualname), ()))

    def ctx(self, file: str) -> ModuleContext:
        return self.modules[file].ctx


# ---- construction ----------------------------------------------------------

def model_from_root(root: str,
                    paths: Optional[List[str]] = None) -> ProjectModel:
    paths = paths or [os.path.join(root, "paddle_tpu")]
    sources = {}
    contexts = {}
    for path in iter_py_files(paths):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            ctx = module_context(path, rel)
        except OSError:
            continue
        except SyntaxError:
            try:
                with open(path, encoding="utf-8") as fh:
                    sources[rel] = fh.read()
            except OSError:
                pass
            continue
        sources[rel] = ctx.source
        contexts[rel] = ctx
    return ProjectModel(sources, contexts=contexts)


_CACHE: Dict[tuple, ProjectModel] = {}


def get_model(root: str) -> ProjectModel:
    """Model for ``<root>/paddle_tpu``, cached per (root, file set,
    newest mtime) so the three thread rules share one build."""
    files = iter_py_files([os.path.join(root, "paddle_tpu")])
    stamp = max((os.path.getmtime(f) for f in files), default=0.0)
    key = (root, len(files), stamp)
    model = _CACHE.get(key)
    if model is None:
        _CACHE.clear()
        model = model_from_root(root)
        _CACHE[key] = model
    return model
