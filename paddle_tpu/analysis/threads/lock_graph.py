"""Lock-order graph + blocking-under-lock + per-class access scan.

Built over the :class:`~.model.ProjectModel`:

- **Lock identities** are ``ClassName.attr`` for ``self.X =
  threading.Lock()/RLock()`` (or the witness factories
  ``make_lock``/``make_rlock``), ``module._NAME`` for module-level
  locks. ``self.Y = threading.Condition(self.X)`` aliases Y to X (a
  ``with self.Y`` holds X); a bare ``Condition()`` owns its own lock. A
  lock stored from a constructor *parameter* (the shared-registry-lock
  idiom in observability/metrics.py) keeps its own per-class identity —
  conflating unknown shared locks could fabricate cycles, so the graph
  stays conservative there.
- **Edges** ``A -> B``: B is acquired while A is held — directly nested
  ``with`` blocks, or transitively through the call graph (holding A and
  calling a function whose closure acquires B). Every edge carries a
  witness chain of ``file:line`` steps from A's acquisition through the
  call sites to B's.
- **Cycles** in the edge set are deadlock findings (two threads walking
  the cycle from different entry points block forever); the finding
  message and ``Finding.data`` carry the full witness chains.
- **Blocking-under-lock**: calls that can block indefinitely or for an
  operator-scale timeout — ``time.sleep``, ``ShmChannel.get/put``,
  ``queue.Queue.get/put`` without a timeout, store/collective
  ``barrier``, socket/SSE writes (``sendall``, ``wfile.write``,
  ``urlopen``, ``getresponse``), subprocess waits — reachable while a
  lock is held. ``Condition.wait`` is exempt (it *releases* the lock;
  that is its contract).
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .model import FuncKey, ProjectModel

__all__ = ["LockGraph", "build_lock_graph", "static_edge_pairs"]

_LOCK_CTORS = ("threading.Lock", "threading.RLock", "Lock", "RLock")
_LOCK_FACTORY_SUFFIX = ("make_lock", "make_rlock")
_COND_CTORS = ("threading.Condition", "Condition")

# dotted-call suffixes that block regardless of receiver type
_BLOCKING_DOTTED = {
    "time.sleep": "time.sleep",
    "urllib.request.urlopen": "urlopen (network wait)",
    "subprocess.run": "subprocess.run",
    "subprocess.call": "subprocess.call",
    "subprocess.check_call": "subprocess.check_call",
    "subprocess.check_output": "subprocess.check_output",
}
# method names that block regardless of receiver type
_BLOCKING_METHODS = {
    "barrier": "barrier (peer wait)",
    "sendall": "socket sendall",
    "getresponse": "HTTP response wait",
    "communicate": "subprocess communicate",
}
# receiver-typed blocking methods: type-token suffix -> {method: needs}
# needs "always" | "no_timeout" (blocking only without a timeout arg)
_TYPED_BLOCKING = {
    "ShmChannel": {"get": "always", "put": "always"},
    "Queue": {"get": "no_timeout", "put": "no_timeout"},
    "SimpleQueue": {"get": "no_timeout"},
    "Event": {"wait": "no_timeout"},
    "Popen": {"wait": "always", "communicate": "always"},
    "HTTPConnection": {"getresponse": "always", "request": "always"},
}


class Edge:
    __slots__ = ("src", "dst", "witness")

    def __init__(self, src, dst, witness):
        self.src = src
        self.dst = dst
        # [(file, line, note), ...] from src's acquisition to dst's
        self.witness: List[Tuple[str, int, str]] = witness

    def chain(self) -> List[str]:
        return [f"{f}:{ln} {note}" for f, ln, note in self.witness]


class BlockingSite:
    __slots__ = ("lock", "file", "line", "call", "chain")

    def __init__(self, lock, file, line, call, chain):
        self.lock = lock
        self.file = file
        self.line = line
        self.call = call        # human description of the blocking call
        self.chain: List[str] = chain


class LockGraph:
    def __init__(self):
        self.locks: Dict[str, Tuple[str, int]] = {}    # id -> def site
        self.edges: Dict[Tuple[str, str], Edge] = {}
        self.blocking: List[BlockingSite] = []
        # per-class: attr -> [(func_key, line, kind, locked)]
        self.accesses: Dict[Tuple[str, str],
                            Dict[str, List[tuple]]] = {}
        self.class_locks: Dict[Tuple[str, str], Set[str]] = {}

    def add_edge(self, src: str, dst: str, witness):
        if src == dst:
            return
        self.edges.setdefault((src, dst), Edge(src, dst, witness))

    def cycles(self) -> List[List[Tuple[str, str]]]:
        """Each lock-order cycle once, as its edge list, canonicalised
        to start at the smallest lock id."""
        adj: Dict[str, List[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, []).append(b)
        sccs = _tarjan(adj)
        out = []
        for comp in sccs:
            comp_set = set(comp)
            if len(comp) == 1:
                continue  # self-edges are filtered at add_edge
            cycle = _find_cycle(adj, comp_set)
            if cycle:
                out.append([(cycle[i], cycle[(i + 1) % len(cycle)])
                            for i in range(len(cycle))])
        return out


def _tarjan(adj: Dict[str, List[str]]) -> List[List[str]]:
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    def strong(v):
        work = [(v, iter(adj.get(v, ())))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(adj.get(w, ()))))
                    advanced = True
                    break
                if w in on:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                out.append(comp)

    for v in list(adj):
        if v not in index:
            strong(v)
    return out


def _find_cycle(adj, comp: Set[str]) -> Optional[List[str]]:
    start = min(comp)
    path, seen = [start], {start}
    node = start
    while True:
        nxt = None
        for w in adj.get(node, ()):
            if w == start and len(path) > 1:
                return path
            if w in comp and w not in seen:
                nxt = w
                break
        if nxt is None:
            if len(path) == 1:
                # need at least one hop before closing
                for w in adj.get(node, ()):
                    if w in comp:
                        nxt = w
                        break
                if nxt is None:
                    return None
            else:
                return None
        seen.add(nxt)
        path.append(nxt)
        node = nxt
        if len(path) > len(comp) + 1:
            return None


# ---- lock identity ----------------------------------------------------------

def _is_lock_ctor(dotted: str) -> bool:
    return (dotted in _LOCK_CTORS
            or dotted.rsplit(".", 1)[-1] in _LOCK_FACTORY_SUFFIX)


def _class_lock_attrs(model: ProjectModel, cls) -> Dict[str, str]:
    """attr -> lock id for the class, Condition aliases included."""
    out: Dict[str, str] = {}
    mod = model.modules[cls.file]
    assigns = []
    for node in ast.walk(cls.node):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            assigns.append(node)
    for node in assigns:   # locks first
        dotted = mod.ctx.resolve_call(node.value.func)
        if not _is_lock_ctor(dotted):
            continue
        for t in node.targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                out[t.attr] = f"{cls.name}.{t.attr}"
    for node in assigns:   # then conditions, which may alias them
        dotted = mod.ctx.resolve_call(node.value.func)
        if dotted not in _COND_CTORS:
            continue
        alias = None
        if node.value.args:
            a0 = node.value.args[0]
            if (isinstance(a0, ast.Attribute)
                    and isinstance(a0.value, ast.Name)
                    and a0.value.id == "self" and a0.attr in out):
                alias = out[a0.attr]
        for t in node.targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                out[t.attr] = alias or f"{cls.name}.{t.attr}"
    # shared-lock idiom: self._lock = <ctor param> — own identity, but
    # still recognised as "a lock" so nesting under it is tracked
    for node in ast.walk(cls.node):
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, ast.Name):
            continue
        for t in node.targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                    and t.attr not in out
                    and _lock_named(t.attr)
                    and _lock_named(node.value.id)):
                out[t.attr] = f"{cls.name}.{t.attr}"
    return out


def _lock_named(name: str) -> bool:
    low = name.lower()
    return low.endswith("lock") or low.endswith("_cond") \
        or low.endswith("condition")


def _module_locks(model: ProjectModel, mod) -> Dict[str, str]:
    """NAME -> lock id for module-level lock assignments."""
    out = {}
    base = mod.file.rsplit("/", 1)[-1][:-3]
    for node in mod.ctx.tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            dotted = mod.ctx.resolve_call(node.value.func)
            if not _is_lock_ctor(dotted):
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = f"{base}.{t.id}"
    return out


# ---- the build --------------------------------------------------------------

class _Builder:
    def __init__(self, model: ProjectModel):
        self.model = model
        self.graph = LockGraph()
        self.class_lock_attrs: Dict[Tuple[str, str], Dict[str, str]] = {}
        self.module_locks: Dict[str, Dict[str, str]] = {}
        # summaries for the transitive closure
        self.direct_acq: Dict[FuncKey, List[Tuple[str, int]]] = {}
        self.direct_block: Dict[FuncKey, List[Tuple[str, int]]] = {}
        self.trans_acq: Dict[FuncKey, Dict[str, List[tuple]]] = {}
        self.trans_block: Dict[FuncKey, Dict[str, List[tuple]]] = {}
        self.cond_ids: Set[str] = set()

    def build(self) -> LockGraph:
        model = self.model
        for mod in model.modules.values():
            self.module_locks[mod.file] = _module_locks(model, mod)
            for cls in mod.classes.values():
                attrs = _class_lock_attrs(model, cls)
                self.class_lock_attrs[cls.key] = attrs
                self.graph.class_locks[cls.key] = set(attrs.values())
                for attr, lid in attrs.items():
                    self.graph.locks.setdefault(lid,
                                                (cls.file, cls.node.lineno))
                    if self._is_condition_attr(model, cls, attr):
                        self.cond_ids.add(f"{cls.name}.{attr}")
        for key in model.functions:
            self._summarize(key)
        self._close()
        for key in model.functions:
            self._walk_function(key)
        self._scan_accesses()
        return self.graph

    @staticmethod
    def _is_condition_attr(model, cls, attr) -> bool:
        tok = cls.attr_types.get(attr, "")
        return tok.rsplit(".", 1)[-1] == "Condition"

    # ---- resolving an acquire expression ------------------------------
    def _lock_of_expr(self, fn, expr) -> Optional[str]:
        model = self.model
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            cls = model.enclosing_class(fn)
            if cls is not None:
                for c in model.mro(cls):
                    attrs = self.class_lock_attrs.get(c.key, {})
                    if expr.attr in attrs:
                        return attrs[expr.attr]
            return None
        if isinstance(expr, ast.Name):
            return self.module_locks.get(fn.file, {}).get(expr.id)
        return None

    def _cond_wait_exempt(self, fn, call) -> bool:
        """``<condition>.wait()`` releases the lock — never blocking."""
        if not (isinstance(call.func, ast.Attribute)
                and call.func.attr in ("wait", "wait_for")):
            return False
        lock_id = self._lock_of_expr(fn, call.func.value)
        return lock_id is not None

    # ---- direct summaries ---------------------------------------------
    def _classify_blocking(self, fn, call) -> Optional[str]:
        model = self.model
        if self._cond_wait_exempt(fn, call):
            return None
        dotted = model.call_dotted.get(id(call), "")
        for suffix, desc in _BLOCKING_DOTTED.items():
            if dotted == suffix or dotted.endswith("." + suffix):
                return desc
        if not isinstance(call.func, ast.Attribute):
            return None
        meth = call.func.attr
        # wfile.write — the SSE/socket write primitive
        if meth == "write" and isinstance(call.func.value, ast.Attribute) \
                and call.func.value.attr == "wfile":
            return "socket write (wfile)"
        recv_tok = model.recv_types.get(id(call), "")
        recv_name = recv_tok.rsplit(".", 1)[-1]
        typed = _TYPED_BLOCKING.get(recv_name)
        if typed and meth in typed:
            if typed[meth] == "always":
                return f"{recv_name}.{meth}"
            has_timeout = any(kw.arg == "timeout" for kw in call.keywords)
            has_timeout = has_timeout or len(call.args) >= (
                2 if meth in ("get", "put") else 1)
            if not has_timeout:
                return f"{recv_name}.{meth} without timeout"
            return None
        if meth in _BLOCKING_METHODS and recv_name not in _TYPED_BLOCKING:
            return _BLOCKING_METHODS[meth]
        return None

    def _summarize(self, key: FuncKey):
        fn = self.model.functions[key]
        acq: List[Tuple[str, int]] = []
        blk: List[Tuple[str, int]] = []

        def walk(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    for item in child.items:
                        lid = self._lock_of_expr(fn, item.context_expr)
                        if lid is not None:
                            acq.append((lid, child.lineno))
                if isinstance(child, ast.Call):
                    desc = self._classify_blocking(fn, child)
                    if desc is not None:
                        blk.append((desc, child.lineno))
                walk(child)

        walk(fn.node)
        self.direct_acq[key] = acq
        self.direct_block[key] = blk
        self.trans_acq[key] = {
            lid: [(fn.file, line, f"acquires {lid}")]
            for lid, line in acq}
        self.trans_block[key] = {
            desc: [(fn.file, line, f"blocks in {desc}")]
            for desc, line in blk}

    def _close(self):
        """Fixpoint: fold callee acquire/block summaries into callers,
        prefixing the call-site step onto the witness chain."""
        changed = True
        guard = 0
        while changed and guard < 50:
            changed = False
            guard += 1
            for key, callees in self.model.edges.items():
                if key not in self.trans_acq:
                    if key not in self.model.functions:
                        continue
                for callee, line in callees:
                    if callee not in self.trans_acq:
                        continue
                    ta = self.trans_acq.setdefault(key, {})
                    tb = self.trans_block.setdefault(key, {})
                    file = key[0]
                    cname = self.model.functions[callee].qualname \
                        if callee in self.model.functions else callee[1]
                    for lid, chain in self.trans_acq[callee].items():
                        if lid not in ta and len(chain) < 8:
                            ta[lid] = ([(file, line, f"calls {cname}()")]
                                       + chain)
                            changed = True
                    for desc, chain in self.trans_block[callee].items():
                        if desc not in tb and len(chain) < 8:
                            tb[desc] = ([(file, line, f"calls {cname}()")]
                                        + chain)
                            changed = True

    # ---- the scoped walk (edges + blocking findings) -------------------
    def _walk_function(self, key: FuncKey):
        fn = self.model.functions[key]
        held: List[Tuple[str, int]] = []

        def on_acquire(lid, line):
            for h, hline in held:
                self.graph.add_edge(h, lid, [
                    (fn.file, hline, f"{fn.qualname} acquires {h}"),
                    (fn.file, line, f"then acquires {lid}")])

        def on_call(call):
            if not held:
                return
            desc = self._classify_blocking(fn, call)
            if desc is not None:
                h, hline = held[-1]
                self.graph.blocking.append(BlockingSite(
                    h, fn.file, call.lineno, desc,
                    [f"{fn.file}:{hline} {fn.qualname} acquires {h}",
                     f"{fn.file}:{call.lineno} blocks in {desc}"]))
            for callee in self.model.call_targets.get(id(call), ()):
                ta = self.trans_acq.get(callee, {})
                tb = self.trans_block.get(callee, {})
                cname = (self.model.functions[callee].qualname
                         if callee in self.model.functions else callee[1])
                for h, hline in held:
                    for lid, chain in ta.items():
                        self.graph.add_edge(h, lid, [
                            (fn.file, hline,
                             f"{fn.qualname} acquires {h}"),
                            (fn.file, call.lineno, f"calls {cname}()"),
                        ] + chain)
                h, hline = held[-1]
                for desc, chain in tb.items():
                    self.graph.blocking.append(BlockingSite(
                        h, fn.file, call.lineno, desc,
                        [f"{fn.file}:{hline} {fn.qualname} acquires {h}",
                         f"{fn.file}:{call.lineno} calls {cname}()"]
                        + [f"{f}:{ln} {note}" for f, ln, note in chain]))

        def walk_node(node, is_root=False):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)) and not is_root:
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired = 0
                for item in node.items:
                    walk_node(item.context_expr)   # calls in the expr
                    lid = self._lock_of_expr(fn, item.context_expr)
                    if lid is not None:
                        on_acquire(lid, node.lineno)
                        held.append((lid, node.lineno))
                        acquired += 1
                for grand in node.body:
                    walk_node(grand)
                for _ in range(acquired):
                    held.pop()
                return
            if isinstance(node, ast.Call):
                on_call(node)
            for child in ast.iter_child_nodes(node):
                walk_node(child)

        walk_node(fn.node, is_root=True)

    # ---- per-class attribute accesses ----------------------------------
    def _scan_accesses(self):
        model = self.model
        for mod in model.modules.values():
            for cls in mod.classes.values():
                lock_attrs = self.class_lock_attrs.get(cls.key, {})
                acc: Dict[str, List[tuple]] = {}
                for mname, q in cls.methods.items():
                    fkey = (mod.file, q)
                    fn = model.functions.get(fkey)
                    if fn is None:
                        continue
                    self._scan_method(fn, fkey, mname, lock_attrs, acc)
                if acc:
                    self.graph.accesses[cls.key] = acc

    def _scan_method(self, fn, fkey, mname, lock_attrs, acc):
        def note(attr, line, kind, locked):
            if attr in lock_attrs:
                return
            acc.setdefault(attr, []).append((fkey, line, kind, locked,
                                             mname))

        def target_attr(node):
            n = node
            while isinstance(n, (ast.Subscript, ast.Attribute)):
                if (isinstance(n, ast.Attribute)
                        and isinstance(n.value, ast.Name)
                        and n.value.id == "self"):
                    return n.attr
                n = n.value
            return ""

        def walk(node, locked):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)) and node is not fn.node:
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    e = item.context_expr
                    n = e
                    while isinstance(n, ast.Attribute):
                        if n.attr in lock_attrs:
                            locked = True
                        n = n.value
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    attr = target_attr(t)
                    if attr:
                        kind = "write"
                        if isinstance(node, ast.Assign) \
                                and isinstance(t, ast.Attribute) \
                                and isinstance(node.value, ast.Constant):
                            kind = "write-const"
                        if isinstance(node, ast.AugAssign):
                            kind = "write-rmw"
                        note(attr, node.lineno, kind, locked)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    attr = target_attr(t)
                    if attr:
                        note(attr, node.lineno, "write", locked)
            elif (isinstance(node, ast.Attribute)
                    and isinstance(node.ctx, ast.Load)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                note(node.attr, node.lineno, "read", locked)
            for child in ast.iter_child_nodes(node):
                walk(child, locked)

        walk(fn.node, False)


def build_lock_graph(model: ProjectModel) -> LockGraph:
    return _Builder(model).build()


def static_edge_pairs(root: str) -> Set[Tuple[str, str]]:
    """The static lock-order edge set for the runtime witness to
    validate observed order against."""
    from .model import get_model

    graph = build_lock_graph(get_model(root))
    return set(graph.edges)
