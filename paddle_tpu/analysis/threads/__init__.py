"""paddle_tpu.analysis.threads — whole-program concurrency analysis.

The serving tier is genuinely concurrent (router/worker/pool/kv_handoff
watcher and drain threads, the engine thread beside HTTP handler threads,
rpc/elastic/watchdog/checkpoint spawn sites), and the only concurrency
rule pdlint had was per-class write discipline. This subpackage is the
whole-program layer:

- :mod:`model` — the **thread model**: walks ``threading.Thread(target=)``
  sites, handler-dispatch entry points and loop threads, closes over the
  project call graph, and maps every function to the set of threads that
  can execute it.
- :mod:`lock_graph` — the **lock-order graph**: lock identities per class
  (Condition aliasing included), acquisition nesting across calls, cycle
  detection with full file:line witness chains, and blocking-call
  reachability while a lock is held.
- :mod:`rules` — the pdlint rules over both: ``thread-naming`` (AST),
  ``thread-deadlock`` / ``thread-blocking-under-lock`` /
  ``thread-shared-state`` (project rules, opt-in via ``pdlint --threads``
  the way graph rules opt in via ``--graph``).
- :mod:`witness` — the **runtime lock-order witness**
  (``FLAGS_lock_witness``): a thin instrumented-lock wrapper recording
  per-thread acquisition order, validating it against the static graph,
  emitting ``lock.order_violation`` flight-recorder events and riding
  incident bundles.

See docs/ANALYSIS.md "Concurrency rules".
"""
from .model import ProjectModel, get_model  # noqa: F401
from .lock_graph import LockGraph, build_lock_graph  # noqa: F401
