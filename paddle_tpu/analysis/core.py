"""pdlint core: rule registry, pragma suppression, and the file driver.

The reference Paddle enforces framework invariants at generation time —
ops.yaml drives the dispatch generators, kernel registration validates
dtype/layout tables at load. The TPU-native collapse replaced those
generators with conventions (jit-traced code stays pure, hot paths never
sync to host, threaded state is lock-guarded), and conventions that
nothing checks are the invariants that rot. This package is the checker:
an AST-based analyzer with a pluggable rule registry, run over the whole
package by ``scripts/pdlint.py`` and as a tier-1 gate
(tests/test_static_analysis.py).

Two rule kinds:

- **AST rules** (`Rule`): per-module, pure ``ast`` — no paddle_tpu import
  needed, so fixture snippets unit-test them in isolation.
- **project rules** (`ProjectRule`): run once per invocation against the
  repo root (op-schema consistency, the metrics/span catalog lints that
  started life as standalone scripts).

Suppression is explicit and local: ``# pdlint: disable=rule-id`` on the
finding's line (comma-separate several ids, or ``disable=all``), or a
checked-in ``.pdlint_baseline.json`` for grandfathered findings (see
``baseline.py``). Baselines match on (file, rule, symbol, message) — not
line numbers — so unrelated edits don't churn them.
"""
from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence

__all__ = [
    "Finding", "Rule", "ProjectRule", "ModuleContext", "RULES",
    "register_rule", "analyze_source", "analyze_file", "iter_py_files",
    "run",
]

_PRAGMA = re.compile(
    r"#\s*pdlint:\s*disable="
    r"([A-Za-z0-9_-]+(?:\s*,\s*[A-Za-z0-9_-]+)*)")


@dataclasses.dataclass
class Finding:
    """One diagnostic: ``file:line rule-id message``.

    ``symbol`` is the innermost enclosing ``Class.method`` qualname — the
    line-number-free identity baselines key on. ``data`` is an optional
    JSON-able payload rules may attach (the shard-solver's rejected-plan
    ledger); it rides the ``--json`` report but never the key or the
    baseline.
    """

    file: str
    line: int
    rule: str
    message: str
    symbol: str = ""
    data: Optional[Dict] = None

    def key(self):
        return (self.file, self.rule, self.symbol, self.message)

    def render(self) -> str:
        where = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.file}:{self.line} {self.rule} {self.message}{where}"


class ModuleContext:
    """Everything an AST rule needs about one module: the parsed tree,
    source lines, the import alias map, per-line pragma suppressions, and
    enclosing-scope qualnames."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.aliases = _import_aliases(self.tree)
        self._scopes = _scope_spans(self.tree)

    # ---- pragmas --------------------------------------------------------
    def suppressed(self, line: int, rule_id: str) -> bool:
        if not (1 <= line <= len(self.lines)):
            return False
        m = _PRAGMA.search(self.lines[line - 1])
        if not m:
            return False
        ids = {s.strip() for s in m.group(1).split(",")}
        return rule_id in ids or "all" in ids

    # ---- scopes ---------------------------------------------------------
    def symbol_for_line(self, line: int) -> str:
        """Innermost def/class qualname containing ``line`` ("" at
        module level)."""
        best = ""
        best_span = None
        for (lo, hi, qual) in self._scopes:
            if lo <= line <= hi and (best_span is None
                                     or (hi - lo) <= best_span):
                best, best_span = qual, hi - lo
        return best

    # ---- name resolution ------------------------------------------------
    def resolve_call(self, func: ast.AST) -> str:
        """Dotted path of a call target with the root resolved through
        the module's import aliases (``np.asarray`` -> ``numpy.asarray``;
        relative imports resolve to a leading dot, so a local module
        aliased ``random`` never collides with the stdlib)."""
        parts = _dotted_parts(func)
        if not parts:
            return ""
        root = self.aliases.get(parts[0])
        if root is not None:
            parts = root.split(".") + parts[1:]
        return ".".join(parts)


def _dotted_parts(node: ast.AST) -> List[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


def _import_aliases(tree: ast.Module) -> Dict[str, str]:
    """Local name -> dotted module path, from every import in the module
    (function-level included). Relative imports keep a leading "." so
    they can never be mistaken for a stdlib module of the same name."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            prefix = "." * node.level + (node.module or "")
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{prefix}.{a.name}"
    return out


def _scope_spans(tree: ast.Module):
    spans = []

    def visit(node, qual):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                q = f"{qual}.{child.name}" if qual else child.name
                spans.append((child.lineno, child.end_lineno or child.lineno,
                              q))
                visit(child, q)
            else:
                visit(child, qual)

    visit(tree, "")
    return spans


# ---- rule registry ----------------------------------------------------------

class Rule:
    """An AST rule: ``check(ctx)`` yields findings for one module."""

    id: str = ""
    rationale: str = ""
    # graph rules trace model programs (expensive): excluded from default
    # runs, included by ``run(graph=True)`` / ``pdlint --graph`` or by
    # naming them in ``selected``
    graph: bool = False
    # thread rules build the whole-program concurrency model: excluded
    # from default runs, included by ``run(threads=True)`` /
    # ``pdlint --threads`` or by naming them in ``selected``
    threads: bool = False

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, ctx: ModuleContext, line: int, message: str) -> Finding:
        return Finding(file=ctx.path, line=line, rule=self.id,
                       message=message, symbol=ctx.symbol_for_line(line))


class ProjectRule(Rule):
    """A whole-project rule: ``check_project(root)`` runs once per
    invocation (op-schema consistency, catalog lints)."""

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        return ()

    def check_project(self, root: str) -> Iterable[Finding]:
        raise NotImplementedError


RULES: Dict[str, Rule] = {}


def register_rule(cls):
    """Class decorator: instantiate and register under ``cls.id``."""
    inst = cls()
    if not inst.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if inst.id in RULES and type(RULES[inst.id]) is not cls:
        raise ValueError(f"duplicate rule id {inst.id!r}")
    RULES[inst.id] = inst
    return cls


def _ensure_rules_loaded():
    from . import rules as _rules  # noqa: F401  (registers on import)


def ast_rules(selected: Optional[Sequence[str]] = None) -> List[Rule]:
    _ensure_rules_loaded()
    return [r for rid, r in sorted(RULES.items())
            if not isinstance(r, ProjectRule)
            and (selected is None or rid in selected)]


def project_rules(selected: Optional[Sequence[str]] = None,
                  graph: bool = False,
                  threads: bool = False) -> List[ProjectRule]:
    """Graph rules run only when ``graph=True`` OR explicitly selected —
    they trace model programs, and the default lint must stay instant.
    Thread rules gate on ``threads=True`` the same way (they build the
    whole-program concurrency model)."""
    _ensure_rules_loaded()
    return [r for rid, r in sorted(RULES.items())
            if isinstance(r, ProjectRule)
            and (selected is None or rid in selected)
            and (graph or not r.graph or
                 (selected is not None and rid in selected))
            and (threads or not r.threads or
                 (selected is not None and rid in selected))]


# ---- drivers ----------------------------------------------------------------

def analyze_source(source: str, filename: str = "<snippet>",
                   rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    """Run AST rules over one source string (the fixture-test entry
    point). Pragma suppression applies exactly as on disk."""
    ctx = ModuleContext(filename, source)
    out: List[Finding] = []
    for rule in (rules if rules is not None else ast_rules()):
        for f in rule.check(ctx):
            if not ctx.suppressed(f.line, f.rule):
                out.append(f)
    return out


def analyze_file(path: str, root: str,
                 rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    return analyze_source(source, rel, rules)


def iter_py_files(paths: Sequence[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, files in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            out.extend(os.path.join(dirpath, f) for f in sorted(files)
                       if f.endswith(".py"))
    return sorted(out)


def run(paths: Optional[Sequence[str]] = None, root: Optional[str] = None,
        selected: Optional[Sequence[str]] = None,
        with_project_rules: bool = True,
        graph: bool = False, threads: bool = False) -> List[Finding]:
    """Analyze ``paths`` (default: ``<root>/paddle_tpu``) and, unless
    disabled, run the project rules against ``root`` (graph rules only
    with ``graph=True``, thread rules only with ``threads=True``, or
    when explicitly selected). Findings come back sorted by (file,
    line, rule)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    if paths is None:
        paths = [os.path.join(root, "paddle_tpu")]
    arules = ast_rules(selected)
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        try:
            findings.extend(analyze_file(path, root, arules))
        except SyntaxError as e:
            findings.append(Finding(
                file=os.path.relpath(path, root).replace(os.sep, "/"),
                line=e.lineno or 1, rule="parse-error",
                message=f"could not parse: {e.msg}"))
    if with_project_rules:
        for rule in project_rules(selected, graph=graph, threads=threads):
            findings.extend(rule.check_project(root))
    findings.sort(key=lambda f: (f.file, f.line, f.rule, f.message))
    return findings
