"""pdlint core: rule registry, pragma suppression, and the file driver.

The reference Paddle enforces framework invariants at generation time —
ops.yaml drives the dispatch generators, kernel registration validates
dtype/layout tables at load. The TPU-native collapse replaced those
generators with conventions (jit-traced code stays pure, hot paths never
sync to host, threaded state is lock-guarded), and conventions that
nothing checks are the invariants that rot. This package is the checker:
an AST-based analyzer with a pluggable rule registry, run over the whole
package by ``scripts/pdlint.py`` and as a tier-1 gate
(tests/test_static_analysis.py).

Two rule kinds:

- **AST rules** (`Rule`): per-module, pure ``ast`` — no paddle_tpu import
  needed, so fixture snippets unit-test them in isolation.
- **project rules** (`ProjectRule`): run once per invocation against the
  repo root (op-schema consistency, the metrics/span catalog lints that
  started life as standalone scripts).

Suppression is explicit and local: ``# pdlint: disable=rule-id`` on the
finding's line (comma-separate several ids, or ``disable=all``), or a
checked-in ``.pdlint_baseline.json`` for grandfathered findings (see
``baseline.py``). Baselines match on (file, rule, symbol, message) — not
line numbers — so unrelated edits don't churn them.
"""
from __future__ import annotations

import ast
import contextlib
import dataclasses
import gc
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding", "Rule", "ProjectRule", "ModuleContext", "RULES",
    "register_rule", "analyze_source", "analyze_file", "iter_py_files",
    "module_context", "unused_pragma_findings", "run",
]

_PRAGMA = re.compile(
    r"#\s*pdlint:\s*disable="
    r"([A-Za-z0-9_-]+(?:\s*,\s*[A-Za-z0-9_-]+)*)")

UNUSED_DISABLE = "unused-disable"


def _parse_pragmas(source: str, lines: List[str]) -> Dict[int, Set[str]]:
    """line -> disabled rule ids, from COMMENT tokens only — a docstring
    that *quotes* a pragma (the rule docs do) is not a pragma. Falls back
    to a raw line scan when the file doesn't tokenize cleanly."""
    out: Dict[int, Set[str]] = {}
    if "pdlint:" not in source:
        return out          # skip tokenizing the ~90% of pragma-free files
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                m = _PRAGMA.search(tok.string)
                if m:
                    out[tok.start[0]] = {s.strip()
                                         for s in m.group(1).split(",")}
    except (tokenize.TokenError, IndentationError, SyntaxError):
        out = {}
        for i, line in enumerate(lines, 1):
            m = _PRAGMA.search(line)
            if m:
                out[i] = {s.strip() for s in m.group(1).split(",")}
    return out


@dataclasses.dataclass
class Finding:
    """One diagnostic: ``file:line rule-id message``.

    ``symbol`` is the innermost enclosing ``Class.method`` qualname — the
    line-number-free identity baselines key on. ``data`` is an optional
    JSON-able payload rules may attach (the shard-solver's rejected-plan
    ledger); it rides the ``--json`` report but never the key or the
    baseline.
    """

    file: str
    line: int
    rule: str
    message: str
    symbol: str = ""
    data: Optional[Dict] = None

    def key(self):
        return (self.file, self.rule, self.symbol, self.message)

    def render(self) -> str:
        where = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.file}:{self.line} {self.rule} {self.message}{where}"


class ModuleContext:
    """Everything an AST rule needs about one module: the parsed tree,
    source lines, the import alias map, per-line pragma suppressions, and
    enclosing-scope qualnames."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.aliases = _import_aliases(self.tree)
        self._scopes = _scope_spans(self.tree)
        self.pragmas = _parse_pragmas(source, self.lines)
        # (line, id) pairs that actually suppressed a finding this run —
        # what the unused-disable check keys on. Reset per invocation
        # because contexts are cached across runs (``module_context``).
        self.pragma_used: Set[Tuple[int, str]] = set()

    # ---- pragmas --------------------------------------------------------
    def reset_pragma_usage(self):
        self.pragma_used.clear()

    def suppressed(self, line: int, rule_id: str) -> bool:
        ids = self.pragmas.get(line)
        if not ids:
            return False
        if rule_id in ids:
            self.pragma_used.add((line, rule_id))
            return True
        if "all" in ids:
            self.pragma_used.add((line, "all"))
            return True
        return False

    # ---- scopes ---------------------------------------------------------
    def symbol_for_line(self, line: int) -> str:
        """Innermost def/class qualname containing ``line`` ("" at
        module level)."""
        best = ""
        best_span = None
        for (lo, hi, qual) in self._scopes:
            if lo <= line <= hi and (best_span is None
                                     or (hi - lo) <= best_span):
                best, best_span = qual, hi - lo
        return best

    def symbols(self) -> Set[str]:
        """Every def/class qualname this module defines, plus "" for
        module level — the namespace finding/baseline symbols live in."""
        out = {""}
        out.update(q for (_lo, _hi, q) in self._scopes)
        return out

    # ---- name resolution ------------------------------------------------
    def resolve_call(self, func: ast.AST) -> str:
        """Dotted path of a call target with the root resolved through
        the module's import aliases (``np.asarray`` -> ``numpy.asarray``;
        relative imports resolve to a leading dot, so a local module
        aliased ``random`` never collides with the stdlib)."""
        parts = _dotted_parts(func)
        if not parts:
            return ""
        root = self.aliases.get(parts[0])
        if root is not None:
            parts = root.split(".") + parts[1:]
        return ".".join(parts)


def _dotted_parts(node: ast.AST) -> List[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


def _import_aliases(tree: ast.Module) -> Dict[str, str]:
    """Local name -> dotted module path, from every import in the module
    (function-level included). Relative imports keep a leading "." so
    they can never be mistaken for a stdlib module of the same name."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            prefix = "." * node.level + (node.module or "")
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{prefix}.{a.name}"
    return out


def _scope_spans(tree: ast.Module):
    spans = []

    def visit(node, qual):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                q = f"{qual}.{child.name}" if qual else child.name
                spans.append((child.lineno, child.end_lineno or child.lineno,
                              q))
                visit(child, q)
            else:
                visit(child, qual)

    visit(tree, "")
    return spans


# ---- rule registry ----------------------------------------------------------

class Rule:
    """An AST rule: ``check(ctx)`` yields findings for one module."""

    id: str = ""
    rationale: str = ""
    # graph rules trace model programs (expensive): excluded from default
    # runs, included by ``run(graph=True)`` / ``pdlint --graph`` or by
    # naming them in ``selected``
    graph: bool = False
    # thread rules build the whole-program concurrency model: excluded
    # from default runs, included by ``run(threads=True)`` /
    # ``pdlint --threads`` or by naming them in ``selected``
    threads: bool = False
    # lifecycle rules walk per-function CFGs for every catalog resource:
    # excluded from default runs, included by ``run(lifecycle=True)`` /
    # ``pdlint --lifecycle`` or by naming them in ``selected``
    lifecycle: bool = False
    # error rules compute interprocedural exception summaries (thread
    # model + CFG fixpoint): excluded from default runs, included by
    # ``run(errors=True)`` / ``pdlint --errors`` or by naming them in
    # ``selected``
    errors: bool = False

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, ctx: ModuleContext, line: int, message: str) -> Finding:
        return Finding(file=ctx.path, line=line, rule=self.id,
                       message=message, symbol=ctx.symbol_for_line(line))


class ProjectRule(Rule):
    """A whole-project rule: ``check_project(root)`` runs once per
    invocation (op-schema consistency, catalog lints)."""

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        return ()

    def check_project(self, root: str) -> Iterable[Finding]:
        raise NotImplementedError


RULES: Dict[str, Rule] = {}


def register_rule(cls):
    """Class decorator: instantiate and register under ``cls.id``."""
    inst = cls()
    if not inst.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if inst.id in RULES and type(RULES[inst.id]) is not cls:
        raise ValueError(f"duplicate rule id {inst.id!r}")
    RULES[inst.id] = inst
    return cls


def _ensure_rules_loaded():
    from . import rules as _rules  # noqa: F401  (registers on import)


def ast_rules(selected: Optional[Sequence[str]] = None,
              lifecycle: bool = False) -> List[Rule]:
    """Lifecycle rules gate exactly like graph/thread project rules: on
    ``lifecycle=True`` / ``pdlint --lifecycle``, or by naming them in
    ``selected`` — the default lint stays instant."""
    _ensure_rules_loaded()
    return [r for rid, r in sorted(RULES.items())
            if not isinstance(r, ProjectRule)
            and (selected is None or rid in selected)
            and (lifecycle or not r.lifecycle or
                 (selected is not None and rid in selected))]


def project_rules(selected: Optional[Sequence[str]] = None,
                  graph: bool = False,
                  threads: bool = False,
                  lifecycle: bool = False,
                  errors: bool = False) -> List[ProjectRule]:
    """Graph rules run only when ``graph=True`` OR explicitly selected —
    they trace model programs, and the default lint must stay instant.
    Thread rules gate on ``threads=True`` the same way (they build the
    whole-program concurrency model), lifecycle rules on
    ``lifecycle=True``, error-flow rules on ``errors=True``."""
    _ensure_rules_loaded()
    return [r for rid, r in sorted(RULES.items())
            if isinstance(r, ProjectRule)
            and (selected is None or rid in selected)
            and (graph or not r.graph or
                 (selected is not None and rid in selected))
            and (threads or not r.threads or
                 (selected is not None and rid in selected))
            and (lifecycle or not r.lifecycle or
                 (selected is not None and rid in selected))
            and (errors or not r.errors or
                 (selected is not None and rid in selected))]


# ---- shared parse cache -----------------------------------------------------

# abs path -> ((mtime_ns, size), ModuleContext). One parse per file per
# run, shared by the AST pass, the thread model, and the baseline stale
# check; invalidated by any on-disk change.
_CTX_CACHE: Dict[str, Tuple[Tuple[int, int], "ModuleContext"]] = {}


def module_context(path: str, rel: Optional[str] = None) -> ModuleContext:
    """The cached ModuleContext for ``path`` (re-parsed only when the
    file changed). ``rel`` is the repo-relative name findings carry;
    a cached context built under a different name is rebuilt."""
    st = os.stat(path)
    key = (st.st_mtime_ns, st.st_size)
    name = rel if rel is not None else path
    hit = _CTX_CACHE.get(path)
    if hit is not None and hit[0] == key and hit[1].path == name:
        return hit[1]
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    ctx = ModuleContext(name, source)
    _CTX_CACHE[path] = (key, ctx)
    return ctx


# ---- drivers ----------------------------------------------------------------

def _check_ctx(ctx: ModuleContext, rules: Sequence[Rule]) -> List[Finding]:
    out: List[Finding] = []
    for rule in rules:
        for f in rule.check(ctx):
            if not ctx.suppressed(f.line, f.rule):
                out.append(f)
    return out


def unused_pragma_findings(ctx: ModuleContext,
                           ran_ids: Set[str]) -> List[Finding]:
    """``unused-disable`` findings: a pragma naming a rule that RAN this
    invocation but suppressed nothing (dead suppression rots into a
    false sense of coverage), or naming no registered rule at all (a
    typo that silently disables nothing). Ids of rules that did not run
    — a ``leak-path`` pragma on a default, non-``--lifecycle`` pass —
    are never flagged; neither is ``disable=all`` (the escape hatch for
    generated code)."""
    out: List[Finding] = []
    for line in sorted(ctx.pragmas):
        for rid in sorted(ctx.pragmas[line]):
            if rid in ("all", UNUSED_DISABLE):
                continue
            if rid not in RULES:
                f = Finding(file=ctx.path, line=line, rule=UNUSED_DISABLE,
                            message=(f"disable pragma names unknown rule "
                                     f"'{rid}' (typo? see --list-rules)"),
                            symbol=ctx.symbol_for_line(line))
            elif rid in ran_ids and (line, rid) not in ctx.pragma_used:
                f = Finding(file=ctx.path, line=line, rule=UNUSED_DISABLE,
                            message=(f"disable pragma for '{rid}' "
                                     "suppresses nothing on this line"),
                            symbol=ctx.symbol_for_line(line))
            else:
                continue
            if not ctx.suppressed(f.line, f.rule):
                out.append(f)
    return out


def analyze_source(source: str, filename: str = "<snippet>",
                   rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    """Run AST rules over one source string (the fixture-test entry
    point). Pragma suppression applies exactly as on disk."""
    ctx = ModuleContext(filename, source)
    rules = list(rules) if rules is not None else ast_rules()
    out = _check_ctx(ctx, rules)
    ran_ids = {r.id for r in rules}
    if UNUSED_DISABLE in ran_ids:
        out.extend(unused_pragma_findings(ctx, ran_ids))
    return out


def analyze_file(path: str, root: str,
                 rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    ctx = module_context(path, rel)
    ctx.reset_pragma_usage()
    return _check_ctx(ctx, list(rules) if rules is not None
                      else ast_rules())


def iter_py_files(paths: Sequence[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, files in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            out.extend(os.path.join(dirpath, f) for f in sorted(files)
                       if f.endswith(".py"))
    return sorted(out)


def run(paths: Optional[Sequence[str]] = None, root: Optional[str] = None,
        selected: Optional[Sequence[str]] = None,
        with_project_rules: bool = True,
        graph: bool = False, threads: bool = False,
        lifecycle: bool = False, errors: bool = False) -> List[Finding]:
    """Analyze ``paths`` (default: ``<root>/paddle_tpu``) and, unless
    disabled, run the project rules against ``root`` (graph rules only
    with ``graph=True``, thread rules only with ``threads=True``,
    lifecycle rules only with ``lifecycle=True``, error-flow rules only
    with ``errors=True``, or when explicitly selected). Every finding —
    AST and project alike — honors the per-line disable pragma; pragmas
    that suppress nothing are themselves findings (``unused-disable``).
    Findings come back sorted by (file, line, rule)."""
    with _gc_paused():
        return _run(paths, root, selected, with_project_rules, graph,
                    threads, lifecycle, errors)


@contextlib.contextmanager
def _gc_paused():
    """Cyclic GC off for the duration of a run: the shared parse cache
    keeps every module's AST alive, and gen-2 collections re-traversing
    millions of live AST nodes mid-walk double the wall time. Linting
    allocates nothing cyclic that refcounting doesn't already free."""
    enabled = gc.isenabled()
    gc.disable()
    try:
        yield
    finally:
        if enabled:
            gc.enable()


def _run(paths, root, selected, with_project_rules, graph, threads,
         lifecycle, errors) -> List[Finding]:
    if root is None:
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    if paths is None:
        paths = [os.path.join(root, "paddle_tpu")]
    arules = ast_rules(selected, lifecycle=lifecycle)
    ran_ids = {r.id for r in arules}
    findings: List[Finding] = []
    ctxs: Dict[str, ModuleContext] = {}
    for path in iter_py_files(paths):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            ctx = module_context(path, rel)
        except SyntaxError as e:
            findings.append(Finding(
                file=rel, line=e.lineno or 1, rule="parse-error",
                message=f"could not parse: {e.msg}"))
            continue
        ctx.reset_pragma_usage()
        ctxs[rel] = ctx
        findings.extend(_check_ctx(ctx, arules))
    if with_project_rules:
        prules = project_rules(selected, graph=graph, threads=threads,
                               lifecycle=lifecycle, errors=errors)
        ran_ids |= {r.id for r in prules}
        for rule in prules:
            for f in rule.check_project(root):
                # uniform pragma handling: a project-rule finding on a
                # file we parsed is suppressible exactly like an AST one
                # (thread rules also self-filter; marking usage on the
                # shared context is what keeps unused-disable honest)
                c = ctxs.get(f.file)
                if c is not None and c.suppressed(f.line, f.rule):
                    continue
                findings.append(f)
    if UNUSED_DISABLE in ran_ids:
        for rel in sorted(ctxs):
            findings.extend(unused_pragma_findings(ctxs[rel], ran_ids))
    findings.sort(key=lambda f: (f.file, f.line, f.rule, f.message))
    return findings
