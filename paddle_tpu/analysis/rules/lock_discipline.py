"""lock-discipline: lock-owning classes guard every shared-attr write.

MetricsRegistry, Tracer, and the serving front-end mutate shared state
from HTTP handler threads and the engine thread concurrently; the
convention is one lock per owning class and every mutation under ``with
self._lock``. The hazard this rule catches is the half-guarded
attribute: written under the lock in one method and bare in another —
the single pattern behind lost-update races (two interleaved
read-modify-writes) and torn multi-field snapshots.

A class "owns a lock" when a method assigns ``self.X =
threading.Lock()/RLock()`` (or the witness factories
``make_lock``/``make_rlock`` from analysis/threads/witness.py — same
semantics, optionally instrumented), wraps one in a Condition
(``self.Y = threading.Condition(self.X)`` — a ``with self.Y`` holds X),
or ``__init__`` stores a lock-named parameter (``self._lock = lock`` —
the shared-registry-lock idiom in observability/metrics.py). For each such class, instance-attribute
writes (rebinds, augmented assigns, and subscript/attribute stores like
``self._children[k] = v``) are classified as inside or outside a ``with
self.<lock>`` block; an attribute with writes on BOTH sides is a
finding. ``__init__``/``__new__`` writes don't count as off-lock — the
object isn't shared during construction.

Single-writer attributes (only ever written off-lock, e.g. a monotonic
flag read lock-free on a hot path) are by design NOT findings: the rule
targets mixed discipline, not lock-free design.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Set, Tuple

from ..core import Finding, ModuleContext, Rule, register_rule

_LOCK_CALLS = {"threading.Lock", "threading.RLock", "Lock", "RLock"}
_LOCK_FACTORIES = ("make_lock", "make_rlock")
_COND_CALLS = {"threading.Condition", "Condition"}
_LOCK_NAME = re.compile(r"(^|_)r?lock$")
_CTOR_METHODS = {"__init__", "__new__"}


def _is_lock_ctor(resolved: str) -> bool:
    return (resolved in _LOCK_CALLS
            or resolved.rsplit(".", 1)[-1] in _LOCK_FACTORIES)


def _self_attr(node) -> str:
    """'X' for a ``self.X`` attribute node, else ""."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return ""


def _store_target_attr(target) -> str:
    """The self-attribute a store mutates: ``self.X = ...``,
    ``self.X[k] = ...``, ``self.X.y = ...`` all mutate X."""
    node = target
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        name = _self_attr(node)
        if name:
            return name
        node = node.value
    return ""


@register_rule
class LockDisciplineRule(Rule):
    id = "lock-discipline"
    rationale = ("an attribute written both under and outside the owning "
                 "lock is a lost-update race between threads")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, node)

    def _check_class(self, ctx: ModuleContext, cls) -> Iterable[Finding]:
        lock_attrs = self._lock_attrs(ctx, cls)
        if not lock_attrs:
            return
        # attr -> [(inside_lock, method, line)]
        writes: Dict[str, List[Tuple[bool, str, int]]] = {}
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            self._scan_method(item, lock_attrs, writes)
        for attr in sorted(writes):
            if attr in lock_attrs:
                continue
            recs = writes[attr]
            inside = [r for r in recs if r[0]]
            outside = [r for r in recs
                       if not r[0] and r[1] not in _CTOR_METHODS]
            if inside and outside:
                _, method, line = outside[0]
                _, lmethod, lline = inside[0]
                yield self.finding(
                    ctx, line,
                    f"attribute 'self.{attr}' of lock-owning class "
                    f"'{cls.name}' is written off-lock in {method}() but "
                    f"under the lock in {lmethod}() (line {lline}) — "
                    "hold the lock for every write or split the state")

    # ---- helpers --------------------------------------------------------
    def _lock_attrs(self, ctx, cls) -> Set[str]:
        out: Set[str] = set()
        conds = []
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                name = _self_attr(t)
                if not name:
                    continue
                v = node.value
                if isinstance(v, ast.Call):
                    resolved = ctx.resolve_call(v.func)
                    if _is_lock_ctor(resolved):
                        out.add(name)
                    elif resolved in _COND_CALLS:
                        conds.append((name, v))
                elif (_LOCK_NAME.search(name)
                        and isinstance(v, ast.Name)
                        and _LOCK_NAME.search(v.id)):
                    out.add(name)  # self._lock = lock (shared-lock idiom)
        for name, call in conds:
            # Condition() owns its own lock; Condition(self.X) guards X —
            # either way `with self.<cond>` holds the lock
            out.add(name)
        return out

    def _scan_method(self, method, lock_attrs: Set[str],
                     writes: Dict[str, List[Tuple[bool, str, int]]]):
        def holds_lock(withnode) -> bool:
            for item in withnode.items:
                expr = item.context_expr
                node = expr
                while isinstance(node, ast.Attribute):
                    if node.attr in lock_attrs:
                        return True
                    node = node.value
            return False

        def visit(node, locked: bool):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                locked = locked or holds_lock(node)
            elif isinstance(node, (ast.Assign, ast.AugAssign,
                                   ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    attr = _store_target_attr(t)
                    if attr:
                        writes.setdefault(attr, []).append(
                            (locked, method.name, node.lineno))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not method:
                return  # nested defs have their own self
            for child in ast.iter_child_nodes(node):
                visit(child, locked)

        visit(method, False)
